"""The flagship PCoA pipeline: ``VariantsPcaDriver`` rebuilt TPU-first.

Mirrors the 7-stage pipeline of ``VariantsPca.scala:45-336`` —
data → filter → calls → similarity → PCA → emit → stats — with the Spark
machinery replaced stage-by-stage:

- per-partition Breeze pair counting + ``reduceByKey`` shuffle
  (``:222-231``) → blockwise ``G += XᵀX`` on the MXU + one cross-device
  reduction (``ops/gramian.py``);
- driver-side ``collect`` of row sums + broadcast centering (``:238-263``)
  → fused on-device Gower centering (``ops/centering.py``);
- MLlib ``RowMatrix.computePrincipalComponents`` (``:264-266``) →
  ``jnp.linalg.eigh`` on the HBM-resident matrix (``ops/pca.py``);
- join/merge of multiple datasets via key shuffles (``:155-188``) →
  per-window hash joins (windows align across datasets because all datasets
  share one partitioner, exactly as the reference builds one
  ``VariantsPartitioner`` from the flattened contig list, ``:111-125``).

Two compute backends, selected by ``--pca-backend`` (the BASELINE.json north
star): ``tpu`` (device pipeline) and ``host`` (a literal NumPy replication of
the reference algorithm, kept as the cross-check oracle).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from spark_examples_tpu.config import PcaConf
from spark_examples_tpu.models.variant import Variant
from spark_examples_tpu.ops.centering import gower_center
from spark_examples_tpu.ops.gramian import (
    GramianAccumulator,
    ShardedGramianAccumulator,
    accumulate_index_rows,
)
from spark_examples_tpu.ops.pca import (
    mllib_reference_pca,
    principal_components_subspace,
)
from spark_examples_tpu.parallel.mesh import SAMPLES_AXIS, resolve_run_mesh
from spark_examples_tpu.pipeline.checkpoint import load_variants
from spark_examples_tpu.pipeline.datasets import VariantsDataset, _parallel_shards
from spark_examples_tpu.pipeline.stats import VariantsDatasetStats
from spark_examples_tpu.sharding.partitioners import VariantsPartitioner
from spark_examples_tpu.sources import partition_page_requests
from spark_examples_tpu.sources.base import GenomicsSource
from spark_examples_tpu.sources.files import FileGenomicsSource, af_float
from spark_examples_tpu.sources.stream import MergeJoinStats, merge_join
from spark_examples_tpu.sources.synthetic import SyntheticGenomicsSource
from spark_examples_tpu.utils import faults


@dataclass(frozen=True)
class CallData:
    """``(hasVariation, callsetIndex)`` (``VariantsPca.scala:338``)."""

    has_variation: bool
    callset_id: int


def extract_call_info(variant: Variant, mapping: Dict[str, int]) -> List[CallData]:
    """``VariantsPcaDriver.extractCallInfo`` (``VariantsPca.scala:65-69``)."""
    if variant.calls is None:
        return []
    return [
        CallData(call.has_variation(), mapping[call.callset_id])
        for call in variant.calls
    ]


def _samples_sharded_mesh(similarity):
    """The mesh of a samples-axis row-sharded similarity matrix, or ``None``.

    Shardedness travels WITH the matrix (its ``NamedSharding``), not via
    driver state: ``compute_pca`` routes to the sharded centering/eigensolve
    exactly when the rows are actually partitioned over ``samples``.
    """
    sharding = getattr(similarity, "sharding", None)
    spec = getattr(sharding, "spec", None)
    if (
        spec is not None
        and len(spec) > 0
        and spec[0] == SAMPLES_AXIS
        and sharding.mesh.shape.get(SAMPLES_AXIS, 1) > 1
    ):
        return sharding.mesh
    return None


def _fetch_components_and_nonzero(device_components, nz, mesh):
    """ONE host transfer for {components, nonzero-row count}: the count
    rides behind the flattened (N, num_pc) components (cohort sizes are far
    below f32's 2^24 exact-integer range). Returns ``(components, nonzero)``.

    The separate nonzero and components fetches were the dominant share of
    small-region wall-clock (VERDICT r4 weakness 1); the batched-transfer
    pattern lives in ``parallel/mesh.py:packed_host_fetch``. ``mesh`` is
    the samples-sharded mesh for the sharded eigensolve path (the packed
    result is replicated so every process of a multi-controller run reads
    its local copy); ``None`` for the dense path, whose operands are
    process-local or fully replicated already.
    """
    import jax.numpy as jnp

    from spark_examples_tpu.parallel.mesh import packed_host_fetch

    rows, num_pc = device_components.shape
    flat = packed_host_fetch(
        [
            jnp.asarray(device_components, jnp.float32),
            nz.astype(jnp.float32),
        ],
        mesh,
    )
    return flat[:-1].reshape(rows, num_pc), int(flat[-1])


def make_source(conf: PcaConf) -> GenomicsSource:
    if conf.source == "synthetic":
        sizes = getattr(conf, "num_samples_per_set", None)
        return SyntheticGenomicsSource(
            num_samples=conf.num_samples,
            seed=conf.seed,
            cohort_sizes=(
                dict(zip(conf.variant_set_id, sizes)) if sizes else None
            ),
        )
    if conf.source == "file":
        return FileGenomicsSource(
            conf.input_files or [],
            stream_chunk_bytes=getattr(conf, "stream_chunk_bytes", None),
            ingest_workers=getattr(conf, "ingest_workers", None),
        )
    from spark_examples_tpu.sources.base import get_access_token
    from spark_examples_tpu.sources.rest import RestGenomicsSource

    return RestGenomicsSource(auth=get_access_token(conf.client_secrets))


class VariantsPcaDriver:
    """Reusable driver (``VariantsPca.scala:89-336``)."""

    def __init__(
        self,
        conf: PcaConf,
        source: Optional[GenomicsSource] = None,
        devices=None,
    ):
        self.conf = conf
        self.source = source if source is not None else make_source(conf)
        # Executor-slice support (serve/): when given, every mesh this
        # driver resolves is built over exactly these devices, so
        # concurrent drivers on disjoint slices never contend for HBM or
        # accumulator state. None = all devices (the historical rule).
        self.devices = list(devices) if devices is not None else None
        # One telemetry namespace per run: every counter/gauge/span of this
        # driver's pipeline lands here, and the run manifest
        # (``--metrics-json``) snapshots exactly this registry+recorder —
        # concurrent drivers (tests, bench configs) never cross-contaminate.
        from spark_examples_tpu.obs import MetricsRegistry, SpanRecorder

        self.registry = MetricsRegistry()
        self.spans = SpanRecorder()
        self._overlap: Optional[Dict] = None
        # Crash-consistent Gramian checkpointing (pipeline/checkpoint.py):
        # the resume artifact is loaded HERE, before any ingest, so a conf
        # fingerprint mismatch or a corrupt artifact fails the run in
        # milliseconds instead of after a re-ingest pass. The feeder is
        # created lazily around the run's accumulator (_wrap_accumulator).
        self.feeder = None
        # The manifest's ``schedule`` block (reduction-schedule kind +
        # predicted-vs-measured ring bytes), stashed from the sharded
        # accumulator when one runs; None on dense/host runs.
        self._sched_block: Optional[Dict] = None
        # Host-sharded pod ingest (sharding/contig.py:host_partition):
        # resolved ONCE per run by _plan_host_sharded_ingest (every contig
        # enumeration and the finalize merge must agree on the same
        # decision); None = not yet resolved, 1 = whole-cohort ingest.
        self._ingest_hosts: Optional[int] = None
        self._gramian_resume: Optional[Dict] = None
        self._ckpt_fingerprint = ""
        if getattr(conf, "gramian_checkpoint_dir", None) or getattr(
            conf, "resume_from", None
        ):
            from spark_examples_tpu.pipeline.checkpoint import (
                gramian_checkpoint_fingerprint,
                load_gramian_checkpoint,
            )

            self._ckpt_fingerprint = gramian_checkpoint_fingerprint(conf)
            if getattr(conf, "resume_from", None):
                self._gramian_resume = load_gramian_checkpoint(
                    conf.resume_from, self._ckpt_fingerprint
                )
                if self._gramian_resume is not None:
                    meta = self._gramian_resume["meta"]
                    print(
                        f"Resuming from Gramian checkpoint at "
                        f"{conf.resume_from}: {meta['sites']} sites "
                        f"already accumulated."
                    )
        # Stats are disabled when resuming from materialized input
        # (``VariantsPca.scala:332-335``).
        self.io_stats: Optional[VariantsDatasetStats] = (
            None if conf.input_path else VariantsDatasetStats(self.registry)
        )
        # Driver-side callset fetch → (indexes, names) (``VariantsPca.scala:97-109``).
        callsets = self.source.search_callsets(conf.variant_set_id)
        self.indexes: Dict[str, int] = {
            cs["id"]: i for i, cs in enumerate(callsets)
        }
        self.names: Dict[str, str] = {cs["id"]: cs["name"] for cs in callsets}
        print(f"Matrix size: {len(self.indexes)}.")
        # After callset discovery: the static bound needs the REAL cohort
        # width (file sources carry theirs in the data, not the flag).
        self._register_host_memory_gauges()

    def _register_host_memory_gauges(self) -> None:
        """The host-memory cross-validation pair (``graftcheck hostmem``'s
        runtime half): a function-backed peak-RSS gauge — every read
        (heartbeat tick, manifest snapshot) samples the OS high-water mark
        — and the static bound from the ONE formula
        ``parallel/mesh.py:host_peak_bytes`` (resolved by
        ``check/hostmem.py:conf_host_peak_bytes``, which is TOTAL — every
        configured ingest path gets a finite bound — and the same
        resolver ``graftcheck plan --host-mem-budget`` enforces, so the
        bound the manifest records and the budget the validator proves
        cannot drift). Best-effort: telemetry must never take down a run;
        if the resolver itself raises, the runtime-baseline bound is
        registered so the gauge is never absent."""
        from spark_examples_tpu.obs.metrics import (
            HOST_PEAK_RSS_BYTES,
            HOST_STATIC_BOUND_BYTES,
            read_host_peak_rss_bytes,
            well_known_gauge,
        )

        if read_host_peak_rss_bytes() is not None:
            well_known_gauge(self.registry, HOST_PEAK_RSS_BYTES).set_function(
                lambda: float(read_host_peak_rss_bytes() or 0)
            )
        try:
            from spark_examples_tpu.check.hostmem import conf_host_peak_bytes

            # Resolved against the declared flag surface; the device count
            # only caps the default mesh's data axis, so jax stays
            # uninitialized here unless a mesh decision truly needs it.
            device_count = None
            num_hosts = 1
            if self.devices is not None:
                device_count = len(self.devices)
            elif not getattr(self.conf, "mesh_shape", None):
                import jax

                device_count = jax.device_count()
            import sys

            if "jax" in sys.modules:
                # PER-HOST bound: under multi-process init every process
                # registers the same formula with the merge term charged
                # (conservative for ring runs, exact for host-sharded
                # ingest — the merge gather is the peak either way). The
                # probe never forces a backend into being on its own.
                import jax

                num_hosts = jax.process_count()
            bound = conf_host_peak_bytes(
                self.conf,
                device_count=device_count,
                num_samples=len(self.indexes) or None,
                num_hosts=num_hosts,
            )
        except Exception:
            from spark_examples_tpu.parallel.mesh import (
                HOST_RUNTIME_BASELINE_BYTES,
            )

            bound = HOST_RUNTIME_BASELINE_BYTES
        well_known_gauge(self.registry, HOST_STATIC_BOUND_BYTES).set(
            float(bound)
        )

    # ------------------------------------------------------------------ data

    def get_data(self) -> List[VariantsDataset]:
        """One sharded dataset per variant set (``VariantsPca.scala:111-125``);
        all datasets share one partitioner built from the flattened contig
        list, or a checkpoint reader under ``--input-path``."""
        if self.conf.input_path:
            return [load_variants(self.conf.input_path)]
        contigs = self._host_contigs(
            self.conf.get_contigs(self.source, self.conf.variant_set_id)
        )
        partitioner = VariantsPartitioner(contigs, self.conf.bases_per_partition)
        return [
            VariantsDataset(
                self.source,
                variant_set_id,
                partitioner,
                stats=self.io_stats,
                num_workers=getattr(self.conf, "num_workers", 8),
            )
            for variant_set_id in self.conf.variant_set_id
        ]

    # ---------------------------------------------------------------- filter

    def filter_variant(self, variant: Variant) -> bool:
        """``--min-allele-frequency`` on the AF info field
        (``VariantsPca.scala:136-148``): strictly greater, first AF value,
        variants without AF dropped.

        For the synthetic source the comparison uses the canonical micro-unit
        rule (``utils/af.py``) so the wire path agrees bit-for-bit with the
        packed and device ingest paths (whose AF lives on the 6-decimal
        grid); generic sources keep the reference's plain float comparison.
        """
        if self.conf.min_allele_frequency is None:
            return True
        af = variant.info.get("AF")
        if not af:
            return False
        if isinstance(self.source, SyntheticGenomicsSource):
            from spark_examples_tpu.utils.af import af_passes

            return bool(
                af_passes(float(af[0]), self.conf.min_allele_frequency)
            )
        if isinstance(self.source, FileGenomicsSource):
            # Same AF grammar as the packed/native ingest of the SAME file
            # (unparseable → NaN → dropped): the two ingest modes must agree
            # record for record.
            return af_float(af[0]) > self.conf.min_allele_frequency
        return float(af[0]) > self.conf.min_allele_frequency

    # ----------------------------------------------------------------- calls

    def iter_calls(self, datasets: List[VariantsDataset]) -> Iterator[List[int]]:
        """Variant → varying callset column indices
        (``VariantsPca.scala:193-208``): single-dataset map, two-dataset key
        join, ≥3 merge-intersect; keep varying calls, drop empty rows."""
        n_sets = len(self.conf.variant_set_id)
        if self.conf.min_allele_frequency is not None:
            print(f"Min allele frequency {self.conf.min_allele_frequency}.")

        if n_sets == 1:
            save_path = getattr(self.conf, "save_variants", None)
            if save_path:
                yield from self._iter_calls_saving(datasets[0], save_path)
                return
            for variant in datasets[0].variants():
                if not self.filter_variant(variant):
                    continue
                calls = extract_call_info(variant, self.indexes)
                row = [c.callset_id for c in calls if c.has_variation]
                if row:
                    yield row
            return

        # Multi-dataset: all datasets share the same partitions, so records
        # with equal variant keys co-locate per window; join there (multi-set
        # --save-variants is rejected up front: --input-path resume loads ONE
        # dataset, so a joined save could not round-trip). Window
        # record-building streams through the same bounded thread pool the
        # single-set path uses (the Spark-executor analog,
        # ``pipeline/datasets.py:_parallel_shards``): windows N+1..N+k build
        # all their datasets' records while window N's join is consumed,
        # keeping --num-workers saturated instead of computing every
        # dataset's window serially per index. The join itself is the
        # streaming k-way ``sources/stream.py:merge_join`` over per-set
        # key-sorted streams: only the records of the CURRENT group key are
        # resident per set, which is exactly the merge-join term the
        # host-memory bound charges (``parallel/mesh.py:host_peak_bytes``).
        partitions = datasets[0].partitions()
        # One partition list per dataset, built once — not per window per
        # worker (a whole-genome join has thousands of windows).
        partition_lists = [dataset.partitions() for dataset in datasets]
        debug = self.conf.debug_datasets

        def window_records(index: int) -> List[List[Tuple[str, List[CallData]]]]:
            per_set: List[List[Tuple[str, List[CallData]]]] = []
            for dataset, parts in zip(datasets, partition_lists):
                part = parts[index]
                keyed: List[Tuple[str, List[CallData]]] = []
                for variant in (v for _, v in dataset.compute(part)):
                    if not self.filter_variant(variant):
                        continue
                    keyed.append(
                        (
                            variant.variant_key(debug),
                            extract_call_info(variant, self.indexes),
                        )
                    )
                # Within one window the records are per-set ordered but not
                # necessarily key-sorted; sort here (window-sized, bounded)
                # so merge_join's sortedness contract holds per stream.
                keyed.sort(key=lambda kr: kr[0])
                per_set.append(keyed)
            return per_set

        num_workers = getattr(self.conf, "num_workers", 8)
        stats = MergeJoinStats()
        for _, per_set in _parallel_shards(
            list(range(len(partitions))), window_records, num_workers
        ):
            for _key, groups in merge_join(
                [iter(keyed) for keyed in per_set], stats=stats
            ):
                if n_sets == 2:
                    # joinDatasets (``VariantsPca.scala:155-168``): inner
                    # join, concatenate both call lists.
                    calls_a, calls_b = groups
                    for ca in calls_a:
                        for cb in calls_b:
                            row = [
                                c.callset_id
                                for c in ca + cb
                                if c.has_variation
                            ]
                            if row:
                                yield row
                else:
                    # mergeDatasets (``VariantsPca.scala:176-188``): keep
                    # keys whose total record count equals the dataset
                    # count, flatten.
                    if sum(len(g) for g in groups) != n_sets:
                        continue
                    merged: List[CallData] = []
                    for records in groups:
                        for calls in records:
                            merged.extend(calls)
                    row = [c.callset_id for c in merged if c.has_variation]
                    if row:
                        yield row

    def _iter_calls_saving(self, dataset, path: str) -> Iterator[List[int]]:
        """Single-set wire ingest that ALSO materializes every shard as a
        checkpoint part while it streams (``--save-variants``): records are
        written UNFILTERED, before the AF filter — the reference applied its
        filters after ``getData`` (``VariantsPca.scala:112-148``), so a
        resumed run re-applies them and any threshold still works against
        the saved data. Stats are untouched (accounting lives in
        ``dataset.compute``). The manifest is written only after the last
        shard, so an interrupted save fails loudly on resume instead of
        silently analyzing a truncated cohort."""
        from spark_examples_tpu.pipeline.checkpoint import CheckpointWriter

        writer = CheckpointWriter(path)
        for _part, records in dataset.iter_shards():
            writer.write_shard(records)
            for _key, variant in records:
                if not self.filter_variant(variant):
                    continue
                calls = extract_call_info(variant, self.indexes)
                row = [c.callset_id for c in calls if c.has_variation]
                if row:
                    yield row
        writer.close()
        print(f"Saved {writer.total} variants to {path}.")

    # ------------------------------------------------------------ similarity

    def _make_mesh(self):
        return resolve_run_mesh(
            self.conf.mesh_shape,
            self.conf.num_reduce_partitions,
            devices=self.devices,
        )

    def _resolve_sharded(self, sharded: Optional[bool], mesh) -> bool:
        """``--similarity-strategy``: explicit dense/sharded, or auto from
        per-device memory (the reference's ~50K-samples/~20GB in-memory
        guidance, ``VariantsPca.scala:216-217,296-297``, restated in bytes
        against the actual HBM — ``ops/gramian.py:dense_strategy_fits``)."""
        from spark_examples_tpu.ops.gramian import dense_strategy_fits

        strategy = getattr(self.conf, "similarity_strategy", "auto")
        if sharded is None:
            if strategy == "sharded":
                sharded = True
            elif strategy == "dense":
                sharded = False
            else:
                sharded = not dense_strategy_fits(len(self.indexes))
        if sharded and (mesh is None or SAMPLES_AXIS not in mesh.shape or mesh.shape[SAMPLES_AXIS] < 2):
            if strategy == "sharded":
                raise ValueError(
                    "--similarity-strategy sharded needs a mesh with a "
                    "samples axis of at least 2 (use --mesh-shape data,samples)"
                )
            sharded = False
        return sharded

    def _plan_host_sharded_ingest(self) -> int:
        """Resolve ONCE whether this run ingests host-sharded, and over how
        many hosts — the pod-scale ingest split (``sharding/contig.py:
        partition_contigs_by_host``).

        Host-sharded ingest engages only when ALL of:

        - the run is multi-process (``jax.process_count() > 1``);
        - the resolved similarity strategy is DENSE — the sharded ring is a
          global SPMD program whose every process must feed the identical
          site stream in lockstep, so it keeps the full-cohort ingest;
        - the device path owns the data plane (``--pca-backend tpu``) with a
          live source (no ``--input-path`` resume, no ``--save-variants``
          wire materialization, no Gramian checkpoint cursor — a
          fast-forward cursor over a PARTITION would not match the artifact
          of a differently-sized fleet).

        When it engages, each process ingests only its contig partition on
        a process-local mesh and the partial Gramians are merged exactly at
        finalize (``_merge_host_partials``) — byte-identical to the
        single-process run, with per-host ingest bytes ~1/H of it.
        """
        if self._ingest_hosts is not None:
            return self._ingest_hosts
        hosts = 1
        conf = self.conf
        if (
            getattr(conf, "pca_backend", "tpu") == "tpu"
            and not getattr(conf, "input_path", None)
            and not getattr(conf, "save_variants", None)
            and not getattr(conf, "gramian_checkpoint_dir", None)
            and not getattr(conf, "resume_from", None)
        ):
            import jax

            if jax.process_count() > 1 and not self._resolve_sharded(
                None, self._make_mesh()
            ):
                hosts = jax.process_count()
        self._ingest_hosts = hosts
        return hosts

    def _host_contigs(self, contigs) -> List:
        """This process's contig partition under host-sharded ingest; the
        full list otherwise. The ONE seam every ingest path (wire, packed,
        device-generation) partitions through, so they cannot disagree on
        the split."""
        contigs = list(contigs)
        hosts = self._plan_host_sharded_ingest()
        if hosts <= 1:
            return contigs
        import jax

        from spark_examples_tpu.sharding.contig import host_partition

        local = host_partition(
            contigs,
            jax.process_index(),
            hosts,
            weight=self.source.declared_sites,
        )
        print(
            f"Host-sharded ingest: process {jax.process_index()} of "
            f"{hosts} reads {len(local)} of {len(contigs)} contig(s)."
        )
        return local

    def _ingest_mesh(self):
        """The dense accumulator's mesh: the run mesh, or — under
        host-sharded ingest — a mesh over THIS process's local devices
        only, so per-process ingest streams of different lengths never
        deadlock a global collective (each process accumulates its partial
        Gramian independently; the one cross-process collective is the
        finalize merge)."""
        if self._plan_host_sharded_ingest() > 1:
            import jax

            return resolve_run_mesh(
                None,
                self.conf.num_reduce_partitions,
                devices=jax.local_devices(),
            )
        return self._make_mesh()

    def _merge_host_partials(self, result):
        """The ONE cross-process collective of host-sharded ingest: gather
        every process's dense N×N partial Gramian and sum them exactly.
        ``G += XᵀX`` commutes over any partition of the row set, and the
        sum runs in an 8-byte intermediate (int64 for count partials,
        float64 otherwise) before casting back — int partials are exact
        outright, and float partials hold integer-valued counts inside the
        accumulator's proven exact window (GR005), so the merged matrix is
        byte-identical to the single-process result. No-op for
        single-process runs."""
        if self._plan_host_sharded_ingest() <= 1:
            return result
        from jax.experimental import multihost_utils

        partial = np.asarray(result)
        stacked = np.asarray(multihost_utils.process_allgather(partial))
        wide = (
            np.int64
            if np.issubdtype(partial.dtype, np.integer)
            else np.float64
        )
        return stacked.astype(wide).sum(axis=0).astype(partial.dtype)

    def _wrap_accumulator(self, acc):
        """Interpose the checkpoint feeder between the ingest stream and a
        fresh accumulator when checkpointing/resume is configured; a plain
        pass-through otherwise (zero overhead for normal runs). The feeder
        restores the persisted partial into ``acc`` on construction and
        fast-forwards the first ``checkpoint_sites`` rows it is fed."""
        conf = self.conf
        directory = getattr(conf, "gramian_checkpoint_dir", None)
        if directory is None and getattr(conf, "resume_from", None) is None:
            # Neither flag: pure pass-through, zero overhead. (A resume
            # flag with no complete artifact yet still gets a feeder — it
            # starts from zero and the manifest records that honestly.)
            return acc
        from spark_examples_tpu.pipeline.checkpoint import GramianFeeder

        self.feeder = GramianFeeder(
            acc,
            directory=directory,
            every_sites=getattr(conf, "checkpoint_every_sites", None),
            fingerprint=self._ckpt_fingerprint,
            resume=self._gramian_resume,
            registry=self.registry,
        )
        return self.feeder

    def _finish_checkpointing(self) -> None:
        """End of ingest: final snapshot (a crash between here and the
        finalize reduce resumes at O(1) re-ingest), then the registered
        pre-finalize kill-point — a no-op unless a fault plan names it."""
        if self.feeder is not None:
            self.feeder.finish()
        faults.kill_point("driver.pre-finalize")

    def get_similarity_matrix(
        self, calls: Iterable[List[int]], sharded: Optional[bool] = None
    ) -> np.ndarray:
        """Similarity counts G = XᵀX (``VariantsPca.scala:210-231`` dense
        strategy; ``sharded=True`` is the memory-bounded analog of
        ``getSimilarityMatrixStream``, ``:288-319``; ``None`` resolves
        ``--similarity-strategy``)."""
        n = len(self.indexes)
        if self.conf.pca_backend == "host":
            return self._host_similarity(calls)
        mesh = self._make_mesh()
        exact = getattr(self.conf, "exact_similarity", False)
        check_ranges = bool(getattr(self.conf, "check_ranges", False))
        if self._resolve_sharded(sharded, mesh):
            acc: object = ShardedGramianAccumulator(
                n, mesh, block_size=self.conf.block_size, exact_int=exact,
                registry=self.registry, spans=self.spans,
                pack_bits=getattr(self.conf, "ring_pack_bits", "auto"),
                check_ranges=check_ranges,
                reduce_schedule=getattr(
                    self.conf, "reduce_schedule", "auto"
                ),
            )
        else:
            acc = GramianAccumulator(
                n, self._ingest_mesh(), block_size=self.conf.block_size,
                exact_int=exact, registry=self.registry, spans=self.spans,
                check_ranges=check_ranges,
            )
        # Duplicate callset indices only arise when a variant set is joined
        # with itself (duplicate ids collapse the column index); only then is
        # the slower unbuffered accumulation needed to reproduce the
        # reference's pair-loop multiplicity (``VariantsPca.scala:224-229``).
        ids = self.conf.variant_set_id
        accumulate_index_rows(
            self._wrap_accumulator(acc),
            calls,
            n,
            self.conf.block_size,
            accumulate_duplicates=len(set(ids)) != len(ids),
        )
        self._finish_checkpointing()
        # Stay on device either way: centering/PCA consume this directly;
        # fetching the N×N matrix to host is pointless and degrades
        # remote-attached backends (see ops/gramian.py). The sharded result
        # remains row-tile-sharded (padded) for the sharded PCA stage.
        if isinstance(acc, GramianAccumulator):
            return self._merge_host_partials(acc.finalize_device())
        self._sched_block = acc.schedule_block()
        return acc.finalize_sharded()

    def get_similarity_rows(
        self,
        blocks: Iterable[np.ndarray],
        sharded: Optional[bool] = None,
        pipeline_depth: Optional[int] = None,
    ) -> np.ndarray:
        """Packed fast path: feed dense uint8 row blocks directly.

        ``pipeline_depth`` (dense accumulator only) keeps that many flushed
        device updates in flight instead of syncing per flush — the
        double-buffered feed that overlaps block *k+1*'s host pack +
        ``device_put`` with block *k*'s Gramian dispatch
        (``ops/gramian.py``)."""
        n = len(self.indexes)
        if self.conf.pca_backend == "host":
            # Host oracle on the packed rows (same result surface as
            # _host_similarity): keeps compute_pca's host branch centered
            # over the true N.
            matrix = np.zeros((n, n), dtype=np.int64)
            for block in blocks:
                X = np.asarray(block, dtype=np.int64)
                matrix += X.T @ X
            return matrix.astype(np.float64)
        mesh = self._make_mesh()
        exact = getattr(self.conf, "exact_similarity", False)
        check_ranges = bool(getattr(self.conf, "check_ranges", False))
        if self._resolve_sharded(sharded, mesh):
            acc: object = ShardedGramianAccumulator(
                n, mesh, block_size=self.conf.block_size, exact_int=exact,
                registry=self.registry, spans=self.spans,
                pack_bits=getattr(self.conf, "ring_pack_bits", "auto"),
                check_ranges=check_ranges,
                reduce_schedule=getattr(
                    self.conf, "reduce_schedule", "auto"
                ),
            )
        else:
            acc = GramianAccumulator(
                n,
                self._ingest_mesh(),
                block_size=self.conf.block_size,
                exact_int=exact,
                pipeline_depth=pipeline_depth,
                registry=self.registry,
                spans=self.spans,
                check_ranges=check_ranges,
            )
        feed = self._wrap_accumulator(acc)
        for block in blocks:
            feed.add_rows(block)
        self._finish_checkpointing()
        if isinstance(acc, GramianAccumulator):
            return self._merge_host_partials(acc.finalize_device())
        self._sched_block = acc.schedule_block()
        return acc.finalize_sharded()

    def get_similarity_device_gen(self, contigs) -> "object":
        """Fully fused TPU ingest+similarity for the synthetic source: the
        host streams per-site thresholds, the device generates genotypes and
        accumulates ``G += XᵀX`` in one scanned XLA program per dispatch group
        (``ops/devicegen.py``).

        Multi-dataset configurations need no join machinery here: synthetic
        variant sets share the site grid, so the reference's 2-set join and
        ≥3-set merge-intersect (``VariantsPca.scala:155-188``) reduce to
        column concatenation of per-set genotype matrices — verified against
        the wire path in tests.
        """
        from spark_examples_tpu.ops.devicegen import (
            DeviceGenGramianAccumulator,
            DeviceGenRingGramianAccumulator,
            auto_blocks_per_dispatch,
        )
        from spark_examples_tpu.sources.synthetic import af_filter_micro

        source: SyntheticGenomicsSource = self.source  # type: ignore[assignment]
        conf = self.conf
        mesh = self._make_mesh()
        # Dispatch-group length: explicit flag, or constant-work auto rule
        # (small cohorts get longer scans — per-dispatch overhead is fixed).
        # `is None`, not falsy-or: config validation rejects non-positive
        # explicit values, and a falsy test would silently remap them to
        # auto if that gate were ever bypassed.
        blocks_per_dispatch = (
            conf.blocks_per_dispatch
            if conf.blocks_per_dispatch is not None
            else auto_blocks_per_dispatch(len(self.indexes), conf.block_size)
        )
        use_ring = self._resolve_sharded(None, mesh)
        # The generation ring speaks both schedules: `hier` factors the
        # samples axis host-major and runs the two-level tile exchange
        # (ops/gramian.py:_hier_ring_tiles inside ops/devicegen.py:
        # _ring_update), byte-identical to flat. An explicit hier request
        # whose host factor does not divide the samples axis still raises
        # inside the accumulator — same policy as the host-fed path.
        reduce_schedule = getattr(conf, "reduce_schedule", "auto")
        if not use_ring:
            # Dense multi-process: host-sharded pod ingest. Each process
            # generates/accumulates only its contig partition on its local
            # devices; the partials merge exactly at finalize.
            contigs = self._host_contigs(contigs)
            mesh = self._ingest_mesh()
        if use_ring and len(conf.variant_set_id) > 1:
            # Sharded multi-set: the joint cohort's concatenated per-set
            # column blocks ride the same ring kernel (the join/merge
            # scenario past the dense HBM rule, ``VariantsPca.scala:
            # 155-188`` — previously a silent fallback to host wire
            # ingest, orders of magnitude slower).
            sizes = [source.num_samples_for(v) for v in conf.variant_set_id]
            acc: object = DeviceGenRingGramianAccumulator(
                num_samples=source.num_samples,
                vs_key=[
                    source.genotype_stream_key(v) for v in conf.variant_set_id
                ],
                pops=source.populations,
                site_key=source.site_key,
                spacing=source.variant_spacing,
                ref_block_fraction=source.ref_block_fraction,
                mesh=mesh,
                min_af_micro=af_filter_micro(conf.min_allele_frequency),
                block_size=conf.block_size,
                blocks_per_dispatch=blocks_per_dispatch,
                exact_int=True,
                n_pops=source.n_pops,
                set_sizes=sizes,
                pops_per_set=[
                    source.populations_for(v) for v in conf.variant_set_id
                ],
                pack_bits=getattr(conf, "ring_pack_bits", "auto"),
                reduce_schedule=reduce_schedule,
            )
        elif use_ring:
            # Sharded strategy, fully on device: each samples-slice
            # generates its own column block and ring-exchanges tiles — the
            # large-cohort (~50K samples) regime with zero host traffic.
            acc = DeviceGenRingGramianAccumulator(
                num_samples=source.num_samples_for(conf.variant_set_id[0]),
                vs_key=source.genotype_stream_key(conf.variant_set_id[0]),
                pops=source.populations_for(conf.variant_set_id[0]),
                site_key=source.site_key,
                spacing=source.variant_spacing,
                ref_block_fraction=source.ref_block_fraction,
                mesh=mesh,
                min_af_micro=af_filter_micro(conf.min_allele_frequency),
                block_size=conf.block_size,
                blocks_per_dispatch=blocks_per_dispatch,
                exact_int=True,
                n_pops=source.n_pops,
                pack_bits=getattr(conf, "ring_pack_bits", "auto"),
                reduce_schedule=reduce_schedule,
            )
        else:
            # Asymmetric joint cohorts (per-set sizes) ride the same kernel
            # via concatenated per-set population vectors.
            sizes = [source.num_samples_for(v) for v in conf.variant_set_id]
            asymmetric = any(s != source.num_samples for s in sizes)
            acc = DeviceGenGramianAccumulator(
                num_samples=source.num_samples,
                vs_keys=[
                    source.genotype_stream_key(v) for v in conf.variant_set_id
                ],
                pops=source.populations,
                site_key=source.site_key,
                spacing=source.variant_spacing,
                ref_block_fraction=source.ref_block_fraction,
                min_af_micro=af_filter_micro(conf.min_allele_frequency),
                block_size=conf.block_size,
                blocks_per_dispatch=blocks_per_dispatch,
                exact_int=True,
                mesh=mesh,
                n_pops=source.n_pops,
                set_sizes=sizes if asymmetric else None,
                pops_per_set=(
                    [source.populations_for(v) for v in conf.variant_set_id]
                    if asymmetric
                    else None
                ),
            )

        from spark_examples_tpu.obs.metrics import (
            INGEST_PARTITIONS_PLANNED,
            INGEST_SITES_SCANNED,
            well_known_gauge,
        )

        self._device_gen_scanned = 0
        # One shard enumeration per contig, shared by the planned-work
        # gauge and the per-contig stats accounting below.
        shards_by_contig = [
            (contig, contig.get_shards(conf.bases_per_partition))
            for contig in contigs
        ]
        well_known_gauge(self.registry, INGEST_PARTITIONS_PLANNED).set(
            sum(len(shards) for _, shards in shards_by_contig)
            * len(conf.variant_set_id)
        )
        sites_gauge = well_known_gauge(self.registry, INGEST_SITES_SCANNED)
        ring_counter = None
        if use_ring:
            from spark_examples_tpu.obs.metrics import (
                GRAMIAN_RING_BYTES,
                well_known_counter,
            )

            # Deterministic host-side accounting of the ICI ring traffic
            # (the device-generation ring has no host flush to instrument);
            # same counter the host-fed sharded accumulator feeds. Advanced
            # per contig so the heartbeat's "ring traffic" segment is live
            # during ingest, not a post-finalize surprise.
            ring_counter = well_known_counter(self.registry, GRAMIAN_RING_BYTES)
        ring_bytes_published = 0
        for contig, shards in shards_by_contig:
            k0, k1 = source.site_grid_range(contig)
            if k1 > k0:
                acc.add_grid(k0, k1)
            self._device_gen_scanned += k1 - k0
            sites_gauge.set(self._device_gen_scanned)
            if ring_counter is not None:
                ring_counter.inc(acc.ring_bytes_total - ring_bytes_published)
                ring_bytes_published = acc.ring_bytes_total
            if self.io_stats is not None:
                # Wire-equivalent accounting: per shard, per variant set
                # (``SyntheticGenomicsSource.page_requests``).
                for _ in conf.variant_set_id:
                    for shard in shards:
                        self.io_stats.add_partition(shard.range)
                self.io_stats.add_requests(
                    source.page_requests(contig, conf.bases_per_partition)
                    * len(conf.variant_set_id)
                )
        self._device_gen_acc = acc
        if use_ring:
            # Row-sharded (padded) result; compute_pca routes to the sharded
            # centering/eigensolve from its NamedSharding.
            self._sched_block = acc.schedule_block()
            result = acc.finalize_sharded()
        else:
            result = self._merge_host_partials(acc.finalize_device())
        from spark_examples_tpu.obs.metrics import (
            DEVICEGEN_DISPATCHES,
            DEVICEGEN_SITES_CAPACITY,
        )

        well_known_gauge(self.registry, DEVICEGEN_DISPATCHES).set(
            acc.dispatches
        )
        # Dispatched grid capacity vs the valid sites inside it — the
        # padding-waste denominator bench.py reports per config (the fixed
        # tail-group overhead that dominates small regions). Ring traffic
        # was already published incrementally inside the ingest loop.
        well_known_gauge(self.registry, DEVICEGEN_SITES_CAPACITY).set(
            acc.sites_capacity
        )
        # Epilogue: record the device-counted variant rows (per variant set,
        # rows with variation in that set's columns — the same count the
        # packed host path reports after its nonzero drop). Doing it here
        # rather than leaving a flush for callers to remember keeps the
        # stats-parity invariant even if a later stage raises, and the
        # synchronous counter fetch makes the ingest stage's wall-clock
        # honest on asynchronous backends. With stats disabled only the
        # honesty sync remains (one fetch instead of two).
        if self.io_stats is not None:
            per_set, _kept = acc.ingest_counters()
            self.io_stats.add_variants(int(per_set.sum()))
        else:
            acc.sync()
        return result

    def _host_similarity(self, calls: Iterable[List[int]]) -> np.ndarray:
        """Literal host replication of ``getSimilarityMatrix``
        (``VariantsPca.scala:222-231``)."""
        n = len(self.indexes)
        matrix = np.zeros((n, n), dtype=np.int64)
        for row in calls:
            idx = np.asarray(row, dtype=np.int64)
            # Unbuffered accumulation: duplicate callset indices contribute
            # per occurrence pair, as the reference's loop does
            # (``VariantsPca.scala:224-229``).
            np.add.at(matrix, np.ix_(idx, idx), 1)
        return matrix.astype(np.float64)

    # ----------------------------------------------------------------- pca

    def compute_pca(self, similarity) -> List[Tuple[str, List[float]]]:
        """Center + eigendecompose (``VariantsPca.scala:238-271``).

        ``similarity`` may be a host array or a device-resident matrix from
        :meth:`get_similarity_matrix`; the TPU path runs every stage on
        device and fetches only the (N, num_pc) result.
        """
        import jax
        import jax.numpy as jnp

        n = len(self.indexes)
        sharded_mesh = _samples_sharded_mesh(similarity)
        if self.conf.pca_backend == "host":
            similarity = np.asarray(similarity)
            nonzero = int((similarity.sum(axis=1) > 0).sum())
            print(f"Non zero rows in matrix: {nonzero} / {n}.")
            centered = self._host_center(similarity)
            components, _ = mllib_reference_pca(centered, self.conf.num_pc)
        elif sharded_mesh is not None:
            # Sharded strategy end to end: the (padded) Gramian stays
            # row-tile-sharded through centering AND the eigensolve — no
            # device ever holds the full N×N (the large-N completion of
            # ``VariantsPca.scala:288-319``'s memory-bounded path).
            from spark_examples_tpu.ops.centering import gower_center_sharded
            from spark_examples_tpu.ops.pca import (
                principal_components_subspace_sharded,
            )

            # Centering arithmetic in float64 (fused upcast, f32 tiles out):
            # the reference centers in Double (``VariantsPca.scala:
            # 246-263``), and whole-genome counts exceed f32's 2^24 exact
            # range — this is what keeps --exact-similarity exact PAST the
            # accumulator (ops/centering.py:_dtypes).
            with self.spans.span("center"):
                with jax.enable_x64(True):
                    centered = gower_center_sharded(
                        similarity, sharded_mesh, n_true=n
                    )
            with self.spans.span("eigh"):
                device_components, _ = principal_components_subspace_sharded(
                    centered, sharded_mesh, self.conf.num_pc, n_true=n
                )
            # any() rather than sum() > 0: entries are non-negative counts,
            # and int32 row sums would overflow at whole-genome scale. Under
            # x64 because the finalize reduce hands back an int64 Gramian.
            with jax.enable_x64(True):
                nz = jnp.any(similarity != 0, axis=1).sum()
            fetched, nonzero = _fetch_components_and_nonzero(
                device_components, nz, sharded_mesh
            )
            print(f"Non zero rows in matrix: {nonzero} / {n}.")
            components = fetched.astype(np.float64)[:n]
        else:
            # Subspace iteration, not full eigh: num_pc is tiny and XLA's TPU
            # eigh is pathologically slow at cohort sizes (see ops/pca.py).
            # f64 centering arithmetic under x64 (the reference's Double
            # centering) with f32 out for the eigensolve; identical results
            # for an int32 exact Gramian and an f32 Gramian holding the
            # same integers (ops/centering.py:_dtypes). The asarray sits
            # INSIDE the x64 block so a float64 host similarity (exact
            # counts past 2^24) is not silently truncated to f32 on entry.
            with self.spans.span("center"):
                with jax.enable_x64(True):
                    S = jnp.asarray(similarity)
                    centered = gower_center(S)
                centered = centered.astype(jnp.float32)
            with self.spans.span("eigh"):
                device_components, _ = principal_components_subspace(
                    centered, self.conf.num_pc
                )
            # All dispatches issued; fetching results is now safe. any()
            # rather than sum() > 0: int32 row sums would overflow at
            # whole-genome scale. Under x64 because S may be the int64
            # result of the finalize reduce.
            with jax.enable_x64(True):
                nz = jnp.any(S != 0, axis=1).sum()
            fetched, nonzero = _fetch_components_and_nonzero(
                device_components, nz, None
            )
            print(f"Non zero rows in matrix: {nonzero} / {n}.")
            components = fetched.astype(np.float64)
        reverse = {i: cs_id for cs_id, i in self.indexes.items()}
        return [
            (reverse[i], [float(c) for c in components[i]]) for i in range(n)
        ]

    @staticmethod
    def _host_center(similarity: np.ndarray) -> np.ndarray:
        """Literal replication of the centering at ``VariantsPca.scala:246-263``."""
        n = similarity.shape[0]
        row_sums = similarity.sum(axis=1)
        matrix_mean = row_sums.sum() / n / n
        row_mean = row_sums / n
        col_mean = row_sums / n  # symmetric matrix: column sums == row sums
        return similarity - row_mean[:, None] - col_mean[None, :] + matrix_mean

    # ----------------------------------------------------------------- emit

    def emit_result(self, result: Sequence[Tuple[str, List[float]]]) -> List[str]:
        """Print and optionally save the TSV (``VariantsPca.scala:273-286``).

        Console format: ``name<TAB>dataset<TAB>pc...``, sorted by name; saved
        format keeps the reference's column order ``name, pcs..., dataset``
        under ``<output-path>-pca.tsv/part-00000``.
        """
        rows = []
        for callset_id, pcs in result:
            dataset = callset_id.split("-")[0]
            rows.append((self.names[callset_id], dataset, pcs))
        rows.sort(key=lambda r: r[0])
        lines = []
        for name, dataset, pcs in rows:
            pc_text = "\t".join(str(c) for c in pcs)
            lines.append(f"{name}\t{dataset}\t{pc_text}")
            print(lines[-1])
        if self.conf.output_path:
            out_dir = self.conf.output_path + "-pca.tsv"
            os.makedirs(out_dir, exist_ok=True)
            with open(os.path.join(out_dir, "part-00000"), "w") as f:
                for name, dataset, pcs in rows:
                    pc_text = "\t".join(str(c) for c in pcs)
                    f.write(f"{name}\t{pc_text}\t{dataset}\n")
        return lines

    # ---------------------------------------------------------------- stats

    def report_io_stats(self) -> None:
        if self.io_stats is not None:
            print(str(self.io_stats))

    def stop(self) -> None:
        pass  # no SparkContext to tear down; kept for API parity


@dataclass
class PipelineResult:
    """One completed analysis: the emitted TSV lines (empty for
    similarity-only runs), the similarity summary (similarity-only runs),
    the run-manifest document when one was built, and the path it was
    written to when the write succeeded. This is the library surface the
    resident service (``serve/executor.py``) consumes; ``run`` keeps the
    historical lines-only CLI contract on top of it."""

    lines: List[str]
    similarity_summary: Optional[Dict] = None
    manifest: Optional[Dict] = None
    manifest_path: Optional[str] = None


def jax_default_device(device):
    """``jax.default_device(device)`` behind a lazy import (the driver
    module must stay importable without initializing a backend)."""
    import jax

    return jax.default_device(device)


def run(argv: Sequence[str]) -> List[str]:
    """``VariantsPcaDriver.main`` (``VariantsPca.scala:47-59``)."""
    conf = PcaConf.parse(argv)
    conf.init_distributed()
    return run_pipeline(conf).lines


def run_pipeline(
    conf: PcaConf, similarity_only: bool = False, devices=None
) -> PipelineResult:
    """The run-an-analysis core, CLI-free: config in, result + manifest
    out. ``run`` (batch) and the resident service's executor
    (``serve/executor.py``) both call this, so a served job and a batch
    invocation execute the identical pipeline and produce the identical
    schema-v2 manifest. ``similarity_only`` stops after the
    ingest+similarity stage and returns a host-side summary of the
    Gramian instead of PC rows (the service's similarity request kind).
    ``devices`` restricts the run to an executor slice's devices
    (``parallel/mesh.py:plan_executor_slices``): meshes resolve over the
    slice only, and mesh-less (dense, single-device) work is pinned to
    the slice's first device so concurrent slices never contend for one
    default device."""
    if getattr(conf, "fault_plan", None) is not None:
        # The flag wins over the SPARK_EXAMPLES_TPU_FAULTS environment
        # variable; configuring resets hit counts, so every run starts a
        # fresh deterministic schedule.
        faults.configure(conf.fault_plan)
    else:
        # Force the lazy env-var plan to parse NOW: a typo'd site name
        # must fail here in milliseconds, not hours later at the first
        # checkpoint hook of a whole-genome run.
        faults.active()
    synthetic_tpu = (
        conf.source == "synthetic"
        and not conf.input_path
        and conf.pca_backend == "tpu"
    )
    # Device generation needs distinct variant sets (duplicate ids collapse
    # the column index, a same-set join the wire path handles via count
    # multiplicity). Both strategies now cover multi-set configurations:
    # dense concatenates per-set column blocks, and past the HBM rule the
    # ring kernel does the same per samples-slice
    # (``get_similarity_device_gen``).
    unique_sets = len(set(conf.variant_set_id)) == len(conf.variant_set_id)
    device_ok = unique_sets
    use_device = conf.ingest == "device" or (
        conf.ingest == "auto" and synthetic_tpu and device_ok
    )
    if conf.ingest == "auto" and synthetic_tpu and not device_ok:
        # The one remaining fallback to wire ingest must be loud — it is
        # orders of magnitude slower than device generation.
        print(
            "Device ingest unavailable (duplicate variant-set ids collapse "
            "the column index); using wire ingest."
        )
    # Every auto-eligible synthetic single-set config now takes the device
    # path (dense or ring); packed ingest remains available explicitly —
    # for the synthetic source AND for single-set VCF file inputs (the
    # native-parser fast path, ``sources/files.py:genotype_blocks``).
    use_packed = conf.ingest == "packed"
    file_packed = (
        conf.source == "file"
        and not conf.input_path
        and conf.pca_backend == "tpu"
    )
    source = make_source(conf) if conf.source != "rest" else None
    if (
        not use_packed
        and conf.ingest == "auto"
        and file_packed
        and len(conf.variant_set_id) == 1
        and isinstance(source, FileGenomicsSource)
        and source.wants_streaming(conf.variant_set_id[0])
    ):
        # Auto-ingest for a large (or explicitly streamed) single-set VCF:
        # the packed path with the bounded-memory streaming pass — the wire
        # path would materialize the whole file as Python records.
        use_packed = True
    if conf.save_variants:
        # The writer materializes WIRE records shard by shard; device/packed
        # ingest never builds them. 'auto' quietly takes the wire path;
        # an explicit fast-path request conflicts and must fail loudly.
        if conf.ingest in ("device", "packed"):
            raise ValueError(
                "--save-variants materializes wire records; it needs the "
                "wire ingest (--ingest wire, or leave --ingest auto)"
            )
        if conf.input_path:
            raise ValueError(
                "--save-variants with --input-path would re-save an "
                "existing checkpoint; copy the directory instead"
            )
        if len(conf.variant_set_id) != 1:
            raise ValueError(
                "--save-variants supports a single variant set "
                "(--input-path resume loads one dataset)"
            )
        if isinstance(source, FileGenomicsSource) and source.wants_streaming(
            conf.variant_set_id[0]
        ):
            # The wire ingest the writer needs would materialize every
            # record of a streaming-scale VCF in host memory — refuse
            # rather than silently OOM a file that runs fine without the
            # flag. (The input is already an on-disk source; resume from
            # it directly.)
            raise ValueError(
                "--save-variants uses the wire ingest, which would load "
                "this streaming-scale VCF fully into host memory; the "
                "input is already resumable from disk. Force the in-memory "
                "path with --stream-chunk-bytes 0 if the host has room."
            )
        use_device = False
        use_packed = False
    if getattr(conf, "gramian_checkpoint_dir", None) or getattr(
        conf, "resume_from", None
    ):
        # Gramian checkpointing snapshots the DEVICE accumulator against a
        # host-fed, deterministically-ordered row cursor; the host backend
        # has no accumulator and the fused on-device generator has no
        # host-side cursor to fast-forward.
        if conf.pca_backend != "tpu":
            raise ValueError(
                "--gramian-checkpoint-dir/--resume-from checkpoint the "
                "device accumulator; they need --pca-backend tpu"
            )
        if conf.ingest == "device":
            raise ValueError(
                "--ingest device has no host-fed row cursor to checkpoint "
                "or resume; use --ingest packed or wire (or leave --ingest "
                "auto, which falls back for checkpointed runs)"
            )
        if use_device:
            print(
                "Device ingest disabled for Gramian checkpointing (the "
                "fused generator has no host-fed cursor); using "
                + (
                    "packed ingest."
                    if len(conf.variant_set_id) == 1
                    else "wire ingest."
                )
            )
            use_device = False
            use_packed = len(conf.variant_set_id) == 1
    if use_device and not (synthetic_tpu and device_ok):
        raise ValueError(
            "--ingest device requires --source synthetic, --pca-backend tpu, "
            "and distinct variant-set ids"
        )
    if use_packed and not (synthetic_tpu or file_packed):
        raise ValueError(
            "--ingest packed requires --pca-backend tpu and --source "
            "synthetic or file (VCF inputs)"
        )
    if use_packed and len(conf.variant_set_id) != 1:
        raise ValueError(
            "--ingest packed supports a single variant set; use --ingest "
            "device (distinct sets) or --ingest wire"
        )
    if use_packed and file_packed and not synthetic_tpu:
        # Fail fast here with the other ingest preconditions, not from a
        # worker thread mid-pipeline: packed file ingest is VCF-only.
        from spark_examples_tpu.sources.files import file_set_ids

        selected = dict(zip(file_set_ids(conf.input_files or []), conf.input_files))[
            conf.variant_set_id[0]
        ]
        lowered = selected[:-3] if selected.endswith(".gz") else selected
        if not lowered.endswith(".vcf"):
            raise ValueError(
                f"--ingest packed needs a .vcf[.gz] input; got {selected!r} "
                "(use --ingest wire for JSONL/checkpoint inputs)"
            )
    driver = VariantsPcaDriver(conf, source, devices=devices)
    _export_compile_cache_gauges(driver.registry)
    from spark_examples_tpu.utils.tracing import StageTimes, device_trace

    # Stages record into the driver's span recorder, so the manifest's span
    # tree and the printed "Stage timings" report are views of one
    # measurement; deeper phases (chunk-parse, dispatch, reduce-flush,
    # center, eigh) nest under the stages they ran in.
    times = StageTimes(recorder=driver.spans)
    heartbeat = None
    if getattr(conf, "heartbeat_seconds", 0) and conf.heartbeat_seconds > 0:
        from spark_examples_tpu.obs.heartbeat import Heartbeat

        heartbeat = Heartbeat(conf.heartbeat_seconds, driver.registry).start()
    similarity_summary: Optional[Dict] = None
    recorder = None
    if getattr(conf, "trace_dir", None):
        # Crash-durable stage timeline (obs/recorder.py): one segment per
        # process named by its multi-controller identity, so an N-process
        # run's timelines merge into ONE Chrome trace (`trace export
        # --run-dir <dir>`) with each host its own trace process row.
        from spark_examples_tpu.obs.recorder import FlightRecorder

        import jax

        recorder = FlightRecorder(
            conf.trace_dir, f"host{jax.process_index()}"
        )
        recorder.begin("run", tid="pipeline")
    import contextlib

    # Slice placement: without a mesh, jit'd work lands on the process
    # default device — two concurrent slices would silently share device
    # 0. Pinning the default to the slice's first device keeps mesh-less
    # paths (dense accumulator, small cohorts) inside the slice too.
    placement = (
        jax_default_device(devices[0])
        if devices
        else contextlib.nullcontext()
    )
    try:
        with placement, device_trace(conf.profile_dir):
            # The device path already ends in a synchronous counter fetch
            # (the stats epilogue); packed/wire paths end in a one-scalar
            # fetch so the stage wall-clock is honest on asynchronous
            # backends rather than dispatch-time only (utils/tracing.py).
            if recorder is not None:
                recorder.begin("ingest+similarity", tid="pipeline")
            with times.stage("ingest+similarity"):
                similarity = _similarity_stage(
                    conf, driver, use_device, use_packed
                )
                if not use_device:
                    _sync_scalar(similarity)
            if recorder is not None:
                recorder.end("ingest+similarity", tid="pipeline")
                if (driver._ingest_hosts or 1) > 1:
                    recorder.record(
                        "host_sharded_ingest",
                        tid="pipeline",
                        hosts=int(driver._ingest_hosts),
                    )
            if similarity_only:
                result = None
                similarity_summary = _summarize_similarity(
                    similarity, len(driver.indexes)
                )
            else:
                # compute_pca ends in the synchronous components fetch, so
                # its stage time is honest even on asynchronous
                # remote-attached backends.
                if recorder is not None:
                    recorder.begin("center+pca", tid="pipeline")
                with times.stage("center+pca"):
                    result = driver.compute_pca(similarity)
                if recorder is not None:
                    recorder.end("center+pca", tid="pipeline")
    finally:
        # Emits-then-stops-cleanly contract: a mid-run exception gets its
        # last heartbeat, then silence — never a progress line racing the
        # traceback (or a leaked thread outliving the run).
        if heartbeat is not None:
            heartbeat.stop()
        if recorder is not None:
            # Durability before correctness of shape: whatever happened
            # above, the events recorded so far reach the segment file
            # (the crash-durable contract; an open "run" span exports as
            # a truncated span, never disappears).
            recorder.flush()
    # Warm the ledger only now, with every kernel this run dispatches
    # compiled and executed — a failure above must not leave a fingerprint
    # behind that makes a retry report "warm" for kernels never built. The
    # kind is part of the key: a similarity-only run does not pre-warm the
    # PCA geometry. Recorded before the manifest snapshot below so the
    # run's own hit/miss is in its own manifest.
    from spark_examples_tpu.utils.cache import (
        compile_fingerprint,
        record_geometry,
    )

    record_geometry(
        compile_fingerprint(
            conf, kind="similarity" if similarity_only else "pca"
        )
    )
    _register_prover_conformance(driver)
    lines = driver.emit_result(result) if result is not None else []
    driver.report_io_stats()
    if conf.profile_dir:
        print(str(times))
        print(f"Device trace written to {conf.profile_dir}.")
    import jax

    manifest_doc: Optional[Dict] = None
    manifest_path: Optional[str] = None
    if getattr(conf, "metrics_json", None) or jax.process_count() > 1:
        # Built LAST, after every report printed above, so the manifest
        # snapshots the same registry state the epilogue rendered — the
        # numbers are identical by construction, not by parallel
        # bookkeeping. Multi-controller runs build it on EVERY process
        # (not only those given --metrics-json): the cross-process counter
        # aggregation inside is a collective, and a process skipping it
        # would deadlock the ones that reached it.
        from spark_examples_tpu.obs.manifest import (
            build_run_manifest,
            write_manifest,
        )

        resume_block = None
        if driver.feeder is not None:
            # The v2-additive ``resume`` block: where this run started
            # from (0 for a fresh checkpointed run), how much ingest the
            # cursor fast-forwarded, and whether any deterministic fault
            # fired in-process — the chaos matrix's assertion surface.
            resume_block = {
                "checkpoint_sites": int(driver.feeder.checkpoint_sites),
                "sites_skipped": int(driver.feeder.sites_skipped),
                "faults_injected": int(faults.injected_count()),
            }
        manifest_doc = build_run_manifest(
            conf=conf,
            spans=driver.spans,
            registry=driver.registry,
            io_stats=driver.io_stats,
            overlap=driver._overlap,
            resume=resume_block,
            schedule=driver._sched_block,
        )
        if conf.metrics_json:
            try:
                write_manifest(conf.metrics_json, manifest_doc)
            except OSError as e:
                # A bad path must not destroy hours of completed compute:
                # the results are already printed/returned — report the
                # telemetry loss loudly and keep the run's exit intact.
                import sys

                print(
                    f"Run manifest NOT written to {conf.metrics_json}: {e}",
                    file=sys.stderr,
                )
            else:
                manifest_path = conf.metrics_json
                print(f"Run manifest written to {conf.metrics_json}.")
    if recorder is not None:
        recorder.end("run", tid="pipeline")
        recorder.close()
    driver.stop()
    return PipelineResult(
        lines=lines,
        similarity_summary=similarity_summary,
        manifest=manifest_doc,
        manifest_path=manifest_path,
    )


def _register_prover_conformance(driver: "VariantsPcaDriver") -> None:
    """The run-epilogue prover-conformance snapshot: for every static
    prover with a runtime-measured subject this run produced, register the
    measured/proven pair as the labeled conformance gauges
    (``obs/metrics.py:record_prover_conformance``) — the manifest's
    ``conformance`` block and the serve fleet's ``/metrics`` mirror both
    read these. Pairs: ``hostmem`` (peak RSS vs the ``host_peak_bytes``
    bound the driver proved at startup — measured always recorded, bound
    null on declared-unbounded paths), ``sched`` (the sharded
    accumulator's per-flush-accounted ring bytes vs its static
    projection), ``ranges`` (the ``--check-ranges`` entry-max sample vs
    the GR005-proven projection). Best-effort: telemetry must never take
    down a completed run."""
    from spark_examples_tpu.obs.metrics import (
        GRAMIAN_ENTRY_MAX,
        GRAMIAN_STATIC_ENTRY_BOUND,
        HOST_PEAK_RSS_BYTES,
        HOST_STATIC_BOUND_BYTES,
        record_prover_conformance,
    )

    registry = driver.registry
    try:
        measured_rss = registry.value(HOST_PEAK_RSS_BYTES)
        if measured_rss is not None and measured_rss == measured_rss:
            bound = registry.value(HOST_STATIC_BOUND_BYTES)
            record_prover_conformance(
                registry,
                "hostmem",
                measured_rss,
                bound if bound is not None and bound == bound else None,
            )
        sched = driver._sched_block
        if sched is not None:
            record_prover_conformance(
                registry,
                "sched",
                sched["measured_ring_bytes"],
                sched["predicted_ring_bytes"],
            )
        entry_max = registry.value(GRAMIAN_ENTRY_MAX)
        if entry_max is not None and entry_max == entry_max:
            entry_bound = registry.value(GRAMIAN_STATIC_ENTRY_BOUND)
            record_prover_conformance(
                registry,
                "ranges",
                entry_max,
                entry_bound
                if entry_bound is not None and entry_bound == entry_bound
                else None,
            )
    except Exception:
        pass


def _export_compile_cache_gauges(registry) -> None:
    """Expose the warm-geometry ledger's counters (``utils/cache.py``) as
    the well-known function-backed gauges, so the manifest and any
    heartbeat sampling this registry show warm-vs-cold directly. The
    ledger itself is fed at the END of ``run_pipeline`` — only a run that
    actually compiled and executed its kernels warms a fingerprint.
    Inside the resident daemon a repeated geometry is a hit (the
    in-process jit caches are warm); each batch CLI process starts cold
    by construction — both are honest."""
    from spark_examples_tpu.obs.metrics import (
        COMPILE_CACHE_GEOMETRY_HITS,
        COMPILE_CACHE_GEOMETRY_MISSES,
        well_known_gauge,
    )
    from spark_examples_tpu.utils.cache import compile_cache_stats

    well_known_gauge(registry, COMPILE_CACHE_GEOMETRY_HITS).set_function(
        lambda: float(compile_cache_stats()[0])
    )
    well_known_gauge(registry, COMPILE_CACHE_GEOMETRY_MISSES).set_function(
        lambda: float(compile_cache_stats()[1])
    )


def _summarize_similarity(similarity, n: int) -> Dict:
    """Host-side facts about a similarity matrix (the similarity request
    kind's result surface): the served response must not ship an N×N
    matrix, so the summary carries shape, dtype, the nonzero-row count the
    PCA path would have printed, and the trace (total variation count) as
    a cheap content fingerprint. Padded sharded results are trimmed to
    the true cohort before summarizing."""
    S = np.asarray(similarity)
    S = S[:n, :n]
    counts = S.astype(np.int64, copy=False)
    return {
        "shape": [int(s) for s in S.shape],
        "dtype": str(S.dtype),
        "nonzero_rows": int((counts.sum(axis=1) > 0).sum()),
        "trace": float(np.trace(counts)),
    }


def _sync_scalar(similarity) -> None:
    """Force outstanding device work to completion with a one-scalar fetch
    that depends on the full accumulation chain (``block_until_ready`` can
    ACK early on remote-attached backends; a host array is a no-op)."""
    import jax
    import jax.numpy as jnp

    if isinstance(similarity, jax.Array):
        jax.device_get(jnp.any(similarity != 0))


def _similarity_stage(conf, driver, use_device: bool, use_packed: bool):
    """The ingest+similarity stage of :func:`run`, one of the three paths."""
    if use_device:
        contigs = conf.get_contigs(driver.source, conf.variant_set_id)
        return driver.get_similarity_device_gen(contigs)
    if use_packed:
        # Packed fast path: dense genotype blocks straight onto the device
        # — synthetic generation, or VCF arrays from the chunk-parallel
        # native parser (``sources/files.py``; pure-Python fallback,
        # identical output). With ingest workers enabled, the block stream
        # rides a bounded prefetch queue (parse runs ahead of the feeder)
        # and the dense accumulator double-buffers its device feed
        # (``pipeline_depth=2``): parse, H2D transfer, and Gramian dispatch
        # of consecutive blocks overlap instead of serializing.
        from spark_examples_tpu.pipeline.datasets import PrefetchIterator
        from spark_examples_tpu.sources.files import _resolve_ingest_workers

        # The ONE resolution of --ingest-workers (None→default, 0=serial),
        # shared with the parse pool inside FileGenomicsSource — the
        # prefetch/double-buffer decision must not drift from it.
        ingest_workers = _resolve_ingest_workers(conf.ingest_workers)
        pipeline_depth = 2 if ingest_workers > 0 else None

        def feed_rows(row_stream):
            """Run the row stream through the prefetch queue (when enabled)
            and the double-buffered accumulator; the structured overlap
            numbers land in the registry/manifest either way, and the
            historical one-line report still prints under --profile-dir."""
            prefetch = None
            if ingest_workers > 0:
                row_stream = prefetch = PrefetchIterator(
                    row_stream,
                    depth=2,
                    registry=driver.registry,
                    spans=driver.spans,
                )
            try:
                return driver.get_similarity_rows(
                    row_stream, pipeline_depth=pipeline_depth
                )
            finally:
                if prefetch is not None:
                    prefetch.close()
                    driver._overlap = prefetch.overlap_stats()
                    if conf.profile_dir:
                        print(prefetch.overlap_report())

        source = driver.source
        synthetic = isinstance(source, SyntheticGenomicsSource)
        contigs = driver._host_contigs(
            conf.get_contigs(source, conf.variant_set_id)
        )
        partitioner = VariantsPartitioner(contigs, conf.bases_per_partition)
        partitions = partitioner.get_partitions(conf.variant_set_id[0])
        from spark_examples_tpu.obs.metrics import (
            INGEST_PARTITIONS_DONE,
            INGEST_PARTITIONS_PLANNED,
            well_known_gauge,
        )

        well_known_gauge(driver.registry, INGEST_PARTITIONS_PLANNED).set(
            len(partitions)
        )

        if not synthetic and source.wants_streaming(conf.variant_set_id[0]):
            # Bounded-memory ingest: ONE pass over the file serves every
            # shard window in file order (G += XᵀX commutes), peak host
            # memory O(chunk) instead of O(file) — the capability the
            # reference's paging had by construction
            # (``rdd/VariantsRDD.scala:198-225``). Stats are accumulated
            # in-pass with the same per-shard page/variant accounting the
            # random-access path computes.
            from spark_examples_tpu.sources.files import StreamCounters

            counters = StreamCounters(len(partitions), registry=driver.registry)
            set_id = conf.variant_set_id[0]
            shard_windows = [p.contig for p in partitions]

            def streamed_rows():
                for block in source.stream_genotype_blocks(
                    set_id,
                    shard_windows,
                    block_size=conf.block_size,
                    min_allele_frequency=conf.min_allele_frequency,
                    counters=counters,
                ):
                    yield block["has_variation"]

            similarity = feed_rows(streamed_rows())
            # The pass is over: every window is done, including any past
            # the file's last record that the cursor never reached — the
            # heartbeat's progress gauge must converge to planned.
            well_known_gauge(driver.registry, INGEST_PARTITIONS_DONE).set(
                len(partitions)
            )
            # get_similarity_rows consumed the stream; the counters are
            # complete. Partition/request accounting matches the per-shard
            # path: every shard contributes its range and ≥1 page.
            if driver.io_stats is not None:
                for part in partitions:
                    driver.io_stats.add_partition(part.range)
                driver.io_stats.add_requests(counters.requests())
                driver.io_stats.add_variants(counters.variants)
            return similarity

        def block_stream():
            # Bounded iteration (the first nibble of ROADMAP item 1): blocks
            # flow one at a time from the per-window producer into the
            # prefetch queue — peak host memory O(block), not O(window).
            # This replaced the per-window `list(genotype_blocks)` pool
            # worker, which was the hostmem declared_unbounded inventory's
            # pca_driver entry; stats account per block as it streams, with
            # identical totals and identical block order (windows in
            # partition order, blocks in producer order — byte-identical
            # output, test-asserted).
            done_gauge = well_known_gauge(
                driver.registry, INGEST_PARTITIONS_DONE
            )
            for index, part in enumerate(partitions):
                if driver.io_stats is not None:
                    driver.io_stats.add_partition(part.range)
                    # Wire-equivalent page accounting (shared helper —
                    # the same rule analyses/base.py streams under).
                    driver.io_stats.add_requests(
                        partition_page_requests(
                            source,
                            part.variant_set_id,
                            part.contig,
                            conf.bases_per_partition,
                        )
                    )
                window_variants = 0
                for block in source.genotype_blocks(
                    part.variant_set_id,
                    part.contig,
                    block_size=conf.block_size,
                    min_allele_frequency=conf.min_allele_frequency,
                ):
                    window_variants += len(block["positions"])
                    yield block["has_variation"]
                if driver.io_stats is not None:
                    driver.io_stats.add_variants(window_variants)
                done_gauge.set(index + 1)

        return feed_rows(block_stream())
    data = driver.get_data()
    calls = driver.iter_calls(data)
    return driver.get_similarity_matrix(calls)


__all__ = [
    "CallData",
    "PipelineResult",
    "VariantsPcaDriver",
    "extract_call_info",
    "make_source",
    "run",
    "run_pipeline",
]

"""Per-site case/control association scan (L5): allelic 2×2 chi-square.

Phenotypes arrive as a two-column TSV (callset name, status 0/1); per
streamed site the device counts carriers among cases ``a`` and carriers
total ``t`` (``ops/ld.py:build_case_counts`` — one matvec per block,
riding the same dispatch loop as every other analysis), and the host
closes the 2×2 table in exact integers:

    a = case carriers        b = n_cases − a
    c = control carriers = t − a
    d = n_controls − c

    χ² = n · (a·d − b·c)² / (n_cases · n_controls · t · (n − t))

The cross-product difference is computed in int64 (|a·d − b·c| ≤ n²/4,
exact through the declared 25K-sample geometry) and squared in float64 —
so the statistic is the exact float64 of the integer counts, and the
NumPy oracle (:func:`chi2_from_counts` over :func:`case_counts_reference`)
matches it to ZERO tolerance (the documented tolerance: float64-exact,
not approximate). Sites with ``t == n`` (every sample a carrier: zero
genotype variance) get χ² = 0 via the shared zero-variance convention;
``t == 0`` rows never arrive (the sources drop all-zero rows).

Per-site statistics spill incrementally through the windowed writer
(``pipeline/sitewriter.py``); the printed ranking rides a bounded
``--assoc-top`` heap — nothing O(M) ever lives on host.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from spark_examples_tpu.analyses.base import (
    AnalysisContext,
    finish_analysis_run,
)
from spark_examples_tpu.config import AssocConf
from spark_examples_tpu.ops.ld import build_case_counts


def load_phenotypes(path: str) -> Dict[str, int]:
    """Parse the ``--phenotypes`` TSV: ``name<TAB>status`` per line, '#'
    comments and blank lines skipped, status strictly 0 or 1. Duplicate
    names and malformed lines fail loudly — a silently-dropped sample
    would bias every statistic. Device-free; the plan validator calls
    this too, so a bad file is an exit-2 reject before any ingest."""
    statuses: Dict[str, int] = {}
    with open(path, "r", encoding="utf-8") as f:
        for lineno, line in enumerate(f, start=1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split("\t")
            if len(parts) != 2:
                raise ValueError(
                    f"{path}:{lineno}: expected 'name<TAB>status', got "
                    f"{line!r}"
                )
            name, status = parts[0].strip(), parts[1].strip()
            if status not in ("0", "1"):
                raise ValueError(
                    f"{path}:{lineno}: status must be 0 (control) or 1 "
                    f"(case), got {status!r}"
                )
            if name in statuses:
                raise ValueError(
                    f"{path}:{lineno}: duplicate sample {name!r}"
                )
            statuses[name] = int(status)
    if not statuses:
        raise ValueError(f"{path}: no phenotype rows")
    values = set(statuses.values())
    if values != {0, 1}:
        missing = "case (1)" if 1 not in values else "control (0)"
        raise ValueError(
            f"{path}: needs at least one case AND one control; no "
            f"{missing} rows present"
        )
    return statuses


def case_vector(
    statuses: Dict[str, int], sample_names: Sequence[str]
) -> np.ndarray:
    """The cohort-ordered {0,1} case mask. Coverage is strict both ways:
    every cohort sample must carry a status, and every status row must
    name a cohort sample — anything else is a silent cohort mismatch."""
    missing = [n for n in sample_names if n not in statuses]
    if missing:
        raise ValueError(
            f"--phenotypes covers {len(statuses)} samples but the cohort "
            f"has {len(sample_names)}; missing e.g. {missing[:5]}"
        )
    extra = set(statuses) - set(sample_names)
    if extra:
        raise ValueError(
            f"--phenotypes names {len(extra)} sample(s) not in the "
            f"cohort, e.g. {sorted(extra)[:5]}"
        )
    return np.array(
        [statuses[n] for n in sample_names], dtype=np.uint8
    )


def chi2_from_counts(
    a: np.ndarray,
    t: np.ndarray,
    n_cases: int,
    n_controls: int,
) -> np.ndarray:
    """Vectorized allelic chi-square from integer per-site counts (module
    docstring formula), float64, with the zero-variance guard (``t == 0``
    or ``t == n`` → 0). Shared verbatim by the streamed run and the
    NumPy oracle — parity is exact equality."""
    n = int(n_cases) + int(n_controls)
    a = np.asarray(a, dtype=np.int64)
    t = np.asarray(t, dtype=np.int64)
    c = t - a
    b = n_cases - a
    d = n_controls - c
    diff = a * d - b * c  # |diff| <= n_cases*n_controls <= n²/4: exact int64
    denom = (
        float(n_cases)
        * float(n_controls)
        * t.astype(np.float64)
        * (n - t).astype(np.float64)
    )
    num = float(n) * diff.astype(np.float64) ** 2
    out = np.zeros_like(num)
    np.divide(num, denom, out=out, where=denom > 0)
    return out


@dataclass
class AssocResult:
    """One completed scan: tested-site count, the bounded top ranking
    (``(chi2, contig, pos, case_carriers, total_carriers)`` descending),
    the output path (when written), and the manifest bookkeeping."""

    sites_tested: int
    top: List[Tuple[float, str, int, int, int]]
    n_cases: int
    n_controls: int
    out_path: Optional[str] = None
    manifest: Optional[Dict] = None
    manifest_path: Optional[str] = None


def run_assoc_pipeline(conf: AssocConf) -> AssocResult:
    """The association-scan core, CLI-free: conf in, per-site statistics
    out (spilled), bounded top ranking returned."""
    import jax

    from spark_examples_tpu.utils.tracing import StageTimes

    if not getattr(conf, "phenotypes", None):
        raise ValueError("the assoc analysis requires --phenotypes TSV")
    ctx = AnalysisContext(conf, "assoc")
    statuses = load_phenotypes(conf.phenotypes)
    case = case_vector(statuses, ctx.sample_names())
    n_cases = int(case.sum())
    n_controls = ctx.num_samples - n_cases
    print(f"Phenotypes: {n_cases} cases / {n_controls} controls.")
    times = StageTimes(recorder=ctx.spans)
    host_oracle = conf.pca_backend == "host"
    counts_fn = None if host_oracle else build_case_counts()
    writer = None
    if conf.assoc_out:
        from spark_examples_tpu.pipeline.sitewriter import SiteOutputWriter

        writer = SiteOutputWriter(
            conf.assoc_out,
            header=("contig", "pos", "case_carriers", "carriers", "chi2"),
        )
    heartbeat = None
    if getattr(conf, "heartbeat_seconds", 0) and conf.heartbeat_seconds > 0:
        from spark_examples_tpu.obs.heartbeat import Heartbeat

        heartbeat = Heartbeat(conf.heartbeat_seconds, ctx.registry).start()
    sites_tested = 0
    # Bounded ranking: a size-K min-heap of (chi2, tie-break) — the O(M)
    # stream never accumulates, only the K best survive on host.
    top_heap: List[Tuple[float, int, str, int, int, int]] = []
    seq = 0
    try:
        with times.stage("ingest+assoc-scan"):
            for contig, block in ctx.blocks():
                hv = np.asarray(block["has_variation"], dtype=np.uint8)
                positions = np.asarray(block["positions"], dtype=np.int64)
                if host_oracle:
                    from spark_examples_tpu.ops.ld import (
                        case_counts_reference,
                    )

                    a, t = case_counts_reference(hv, case)
                else:
                    # Static-shape the dispatch: ragged blocks (the
                    # nonzero/AF drops) pad to --block-size with zero
                    # rows so ONE compiled program serves every block —
                    # padding rows are trimmed right back off.
                    b = hv.shape[0]
                    if b < conf.block_size:
                        padded = np.zeros(
                            (conf.block_size, hv.shape[1]), dtype=np.uint8
                        )
                        padded[:b] = hv
                        hv_dev = padded
                    else:
                        hv_dev = hv
                    a_dev, t_dev = counts_fn(hv_dev, case)
                    a = np.asarray(jax.device_get(a_dev))[:b]  # graftcheck: disable=GC001 -- deliberate per-block fetch: the chi-square close-out and the bounded ranking are host-side scalar work on two B-length vectors
                    t = np.asarray(jax.device_get(t_dev))[:b]  # graftcheck: disable=GC001 -- same per-block fetch as `a` above
                chi2 = chi2_from_counts(a, t, n_cases, n_controls)
                if writer is not None:
                    writer.write_rows(
                        (
                            contig,
                            int(positions[i]),
                            int(a[i]),
                            int(t[i]),
                            repr(float(chi2[i])),
                        )
                        for i in range(len(positions))
                    )
                # Vectorized candidate pre-filter: once the heap is full,
                # a streamed site can only displace the minimum with a
                # STRICTLY greater chi2 (every heap entry has an earlier
                # seq, so equal statistics always lose the -seq
                # tie-break) — the Python-level heap loop runs over the
                # handful of block rows above the floor, not all M sites.
                if len(top_heap) < conf.assoc_top:
                    candidates = range(len(positions))
                else:
                    candidates = np.nonzero(chi2 > top_heap[0][0])[0]
                for i in candidates:
                    # seq is a deterministic tie-break (stream order) so
                    # equal statistics rank stably across runs.
                    entry = (
                        float(chi2[i]),
                        -(seq + int(i)),
                        contig,
                        int(positions[i]),
                        int(a[i]),
                        int(t[i]),
                    )
                    if len(top_heap) < conf.assoc_top:
                        heapq.heappush(top_heap, entry)
                    elif entry > top_heap[0]:
                        heapq.heapreplace(top_heap, entry)
                seq += len(positions)
                sites_tested += len(positions)
    except BaseException:
        if writer is not None:
            writer.abort()
        raise
    finally:
        if heartbeat is not None:
            heartbeat.stop()
    if writer is not None:
        writer.close()
        print(f"Per-site scan written to {conf.assoc_out}.")
    top = [
        (chi2, contig, pos, a_i, t_i)
        for chi2, _seq, contig, pos, a_i, t_i in sorted(
            top_heap, reverse=True
        )
    ]
    print(f"Association scan: {sites_tested} sites tested.")
    for chi2, contig, pos, a_i, t_i in top:
        print(f"{contig}\t{pos}\t{a_i}\t{t_i}\t{chi2:.6g}")
    print(str(ctx.io_stats))
    if conf.profile_dir:
        print(str(times))
    manifest, manifest_path, _ = finish_analysis_run(
        conf,
        "assoc",
        ctx.spans,
        ctx.registry,
        ctx.io_stats,
        sites_tested=sites_tested,
        sites_kept=None,
    )
    return AssocResult(
        sites_tested=sites_tested,
        top=top,
        n_cases=n_cases,
        n_controls=n_controls,
        out_path=conf.assoc_out,
        manifest=manifest,
        manifest_path=manifest_path,
    )


def run(argv: Sequence[str]) -> AssocResult:
    """The ``assoc-scan`` CLI verb."""
    conf = AssocConf.parse(argv)
    conf.init_distributed()
    return run_assoc_pipeline(conf)


__all__ = [
    "AssocResult",
    "case_vector",
    "chi2_from_counts",
    "load_phenotypes",
    "run",
    "run_assoc_pipeline",
]

"""Shared plumbing of the population-genetics analyses (``analyses/``).

The three analyses (GRM/kinship, windowed LD pruning, association scan)
are new L5 applications on the proven substrate: they stream the SAME
contig-ordered has-variation blocks the PCA Gramian accumulates (one
``genotype_blocks`` contract across the synthetic and file sources), under
the same partitioner, the same telemetry registry/span/heartbeat stack,
and the same manifest epilogue. This module is the one home of that
shared plumbing, so each analysis file holds only its own math:

- :func:`check_analysis_conf` — the runtime half of the admission
  contract (``check/plan.py`` repeats it device-free): analyses are
  single-variant-set, synthetic/file-source runs; the PCA-only flags
  (checkpoint/resume, ``--save-variants``, ``--input-path`` resume,
  explicit streaming) are rejected loudly instead of half-working;
- :func:`iter_site_blocks` — the contig-ordered block stream with the
  standard ingest accounting (partition/request/variant stats, the
  planned/done/sites gauges the heartbeat reads);
- :class:`AnalysisContext` — source + callsets + registry/spans/stats +
  mesh resolution for the analyses that do not embed a full
  ``VariantsPcaDriver`` (LD, assoc; GRM reuses the driver so the Gramian
  strategy/dtype-ladder/ring machinery stays single-sourced);
- :func:`finish_analysis_run` — the manifest epilogue: the schema-v2 run
  manifest with the v2-additive ``analysis`` block
  (``{kind, sites_kept, sites_tested}``), warm-geometry ledger recording,
  and the same atomic ``--metrics-json`` write contract as the PCA
  pipeline.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from spark_examples_tpu.pipeline.stats import VariantsDatasetStats
from spark_examples_tpu.sharding.partitioners import VariantsPartitioner
from spark_examples_tpu.utils import faults

#: The analysis kinds this subsystem ships; ``serve/protocol.py`` keys its
#: job-kind table off the same spellings (``grm`` served, ``ld``/``assoc``
#: reserved batch-only for now).
ANALYSIS_KINDS = ("grm", "ld", "assoc")


def analysis_conf_violations(conf, kind: str) -> List[Tuple[str, str]]:
    """Every shared-precondition violation of ``conf`` for analysis
    ``kind``, as ``(code, message)`` pairs — the ONE catalogue behind both
    the runtime gate (:func:`check_analysis_conf`, first violation raises)
    and the device-free plan validator
    (``check/plan.py:validate_plan(analysis=...)``, every violation an
    exit-2 plan error), so the two can never drift."""
    if kind not in ANALYSIS_KINDS:
        raise ValueError(f"unknown analysis kind {kind!r}")
    violations: List[Tuple[str, str]] = []
    if len(conf.variant_set_id) != 1:
        violations.append((
            "analysis-variant-sets",
            f"the {kind} analysis takes exactly one variant set "
            f"(got {len(conf.variant_set_id)}); joins/merges are a PCA "
            "pipeline capability",
        ))
    if getattr(conf, "source", "synthetic") == "rest":
        violations.append((
            "analysis-source",
            f"the {kind} analysis streams packed genotype blocks; the "
            "paginated REST source has no packed path (--source synthetic "
            "or file)",
        ))
    if getattr(conf, "input_path", None):
        violations.append((
            "analysis-input-path",
            "--input-path checkpoint resume loads wire records; the "
            f"{kind} analysis streams packed blocks (run from the "
            "original source)",
        ))
    if getattr(conf, "save_variants", None):
        violations.append((
            "analysis-save-variants",
            "--save-variants materializes wire records; the packed "
            f"{kind} analysis never builds them",
        ))
    if getattr(conf, "gramian_checkpoint_dir", None) or getattr(
        conf, "resume_from", None
    ):
        violations.append((
            "analysis-checkpoint",
            "--gramian-checkpoint-dir/--resume-from checkpoint the PCA "
            f"similarity accumulator; the {kind} analysis is not "
            "checkpointable yet",
        ))
    if getattr(conf, "ingest", "auto") not in ("auto", "packed"):
        violations.append((
            "analysis-ingest",
            f"the {kind} analysis has one ingest path (packed blocks); "
            f"--ingest {conf.ingest} does not apply",
        ))
    stream = getattr(conf, "stream_chunk_bytes", None)
    if stream is not None and stream > 0:
        violations.append((
            "analysis-streaming",
            f"explicit --stream-chunk-bytes streaming is not wired into "
            f"the {kind} analysis yet; it uses the windowed packed parse "
            "(drop the flag, or 0 to silence the auto decision)",
        ))
    return violations


def check_analysis_conf(conf, kind: str) -> None:
    """Runtime preconditions every analysis shares — mirrored device-free
    by ``check/plan.py:validate_plan(analysis=...)`` so a doomed
    configuration is rejected at admission, not after ingest."""
    violations = analysis_conf_violations(conf, kind)
    if violations:
        raise ValueError(violations[0][1])


def analysis_partitions(conf, source):
    """The run's shard windows: the SAME contig resolution and partitioner
    the PCA driver builds (one ``VariantsPartitioner`` over the flattened
    contig list), for the analyses' single variant set."""
    contigs = conf.get_contigs(source, conf.variant_set_id)
    partitioner = VariantsPartitioner(contigs, conf.bases_per_partition)
    return partitioner.get_partitions(conf.variant_set_id[0])


def iter_site_blocks(
    conf, source, partitions, io_stats, registry
) -> Iterator[Tuple[str, Dict[str, np.ndarray]]]:
    """Contig-ordered block stream for one variant set with the standard
    ingest accounting: yields ``(contig_name, block)`` where ``block`` is
    the sources' ``genotype_blocks`` dict (``positions``,
    ``has_variation``, ``af``) — blocks flow one at a time (peak host
    memory O(block), the bounded-iteration idiom of the PCA packed path).

    Deliberately parallel to ``pipeline/pca_driver.py``'s ``block_stream``
    (same partition/page/variant accounting around the same
    ``genotype_blocks`` call; the page-request branch is shared via
    ``sources.partition_page_requests``). The loops stay separate because
    the PCA path must NOT set the sites-scanned gauge here (file sources
    already advance it during parse, and the PCA device-gen path owns its
    own count) — keep accounting changes mirrored in both."""
    from spark_examples_tpu.obs.metrics import (
        INGEST_PARTITIONS_DONE,
        INGEST_PARTITIONS_PLANNED,
        INGEST_SITES_SCANNED,
        well_known_gauge,
    )
    from spark_examples_tpu.sources import partition_page_requests

    well_known_gauge(registry, INGEST_PARTITIONS_PLANNED).set(len(partitions))
    done_gauge = well_known_gauge(registry, INGEST_PARTITIONS_DONE)
    sites_gauge = well_known_gauge(registry, INGEST_SITES_SCANNED)
    sites_scanned = 0
    for index, part in enumerate(partitions):
        if io_stats is not None:
            io_stats.add_partition(part.range)
            io_stats.add_requests(
                partition_page_requests(
                    source,
                    part.variant_set_id,
                    part.contig,
                    conf.bases_per_partition,
                )
            )
        window_variants = 0
        for block in source.genotype_blocks(
            part.variant_set_id,
            part.contig,
            block_size=conf.block_size,
            min_allele_frequency=conf.min_allele_frequency,
        ):
            window_variants += len(block["positions"])
            sites_scanned += len(block["positions"])
            sites_gauge.set(sites_scanned)
            yield part.contig.reference_name, block
        if io_stats is not None:
            io_stats.add_variants(window_variants)
        done_gauge.set(index + 1)


def cohort_sample_names(
    indexes: Dict[str, int], names: Dict[str, str]
) -> List[str]:
    """Callset names in cohort column order, from the ``{id: index}`` /
    ``{id: name}`` pair every driver carries — ONE ordering rule, so GRM
    row labels can never disagree with LD/assoc labels."""
    reverse = {i: cs_id for cs_id, i in indexes.items()}
    return [names[reverse[i]] for i in range(len(indexes))]


class AnalysisContext:
    """Source + callsets + telemetry + mesh for the per-site analyses.

    Deliberately a subset of ``VariantsPcaDriver``: LD and assoc have no
    N×N accumulator, so they need the shared *plumbing* (cohort
    discovery, partitioning, registry/spans/stats, mesh resolution) but
    none of the similarity machinery. GRM, which DOES accumulate an N×N
    Gramian, embeds the real driver instead — the strategy/dtype-ladder
    logic stays single-sourced there.
    """

    def __init__(self, conf, kind: str):
        check_analysis_conf(conf, kind)
        from spark_examples_tpu.obs import MetricsRegistry, SpanRecorder
        from spark_examples_tpu.pipeline.pca_driver import make_source

        self.conf = conf
        self.kind = kind
        self.source = make_source(conf)
        self.registry = MetricsRegistry()
        self.spans = SpanRecorder()
        self.io_stats = VariantsDatasetStats(self.registry)
        callsets = self.source.search_callsets(conf.variant_set_id)
        self.indexes: Dict[str, int] = {
            cs["id"]: i for i, cs in enumerate(callsets)
        }
        self.names: Dict[str, str] = {cs["id"]: cs["name"] for cs in callsets}
        self.num_samples = len(self.indexes)
        if self.num_samples < 1:
            raise ValueError(
                f"the {kind} analysis found an empty cohort for variant "
                f"set {conf.variant_set_id[0]!r}"
            )
        print(f"Cohort size: {self.num_samples}.")

    def sample_names(self) -> List[str]:
        """Callset names in column order (the analyses' row/column label
        order — cohort order, not the PCA emit's name-sorted order)."""
        return cohort_sample_names(self.indexes, self.names)

    def partitions(self):
        return analysis_partitions(self.conf, self.source)

    def blocks(self) -> Iterator[Tuple[str, Dict[str, np.ndarray]]]:
        return iter_site_blocks(
            self.conf,
            self.source,
            self.partitions(),
            self.io_stats,
            self.registry,
        )

    def make_mesh(self):
        """The run's mesh, resolved by the same rule as the PCA driver
        (``parallel/mesh.py:resolve_run_mesh``)."""
        from spark_examples_tpu.parallel.mesh import resolve_run_mesh

        return resolve_run_mesh(
            self.conf.mesh_shape, self.conf.num_reduce_partitions
        )


def finish_analysis_run(
    conf,
    kind: str,
    spans,
    registry,
    io_stats,
    sites_tested: int,
    sites_kept: Optional[int],
) -> Tuple[Optional[Dict], Optional[str], Dict]:
    """The analyses' run epilogue, mirroring ``run_pipeline``'s: record
    the geometry in the warm ledger (kind-keyed — a GRM run never
    pre-warms the PCA fingerprint), build the schema-v2 manifest with the
    v2-additive ``analysis`` block, and write it atomically when
    ``--metrics-json`` asked. Returns ``(manifest_doc, manifest_path,
    analysis_block)``."""
    from spark_examples_tpu.obs.metrics import (
        ANALYSIS_SITES_KEPT,
        ANALYSIS_SITES_TESTED,
        well_known_gauge,
    )
    from spark_examples_tpu.utils.cache import (
        compile_fingerprint,
        record_geometry,
    )

    faults.kill_point("analysis.pre-manifest")
    record_geometry(compile_fingerprint(conf, kind=kind))
    well_known_gauge(registry, ANALYSIS_SITES_TESTED).set(int(sites_tested))
    well_known_gauge(registry, ANALYSIS_SITES_KEPT).set(
        int(sites_kept if sites_kept is not None else sites_tested)
    )
    analysis_block = {
        "kind": kind,
        "sites_kept": int(sites_kept) if sites_kept is not None else None,
        "sites_tested": int(sites_tested),
    }
    manifest_doc: Optional[Dict] = None
    manifest_path: Optional[str] = None
    if getattr(conf, "metrics_json", None):
        from spark_examples_tpu.obs.manifest import (
            build_run_manifest,
            write_manifest,
        )

        manifest_doc = build_run_manifest(
            conf=conf,
            spans=spans,
            registry=registry,
            io_stats=io_stats,
            analysis=analysis_block,
        )
        try:
            write_manifest(conf.metrics_json, manifest_doc)
        except OSError as e:
            # Same contract as run_pipeline: a bad telemetry path must not
            # destroy completed compute — report loudly, keep the results.
            import sys

            print(
                f"Run manifest NOT written to {conf.metrics_json}: {e}",
                file=sys.stderr,
            )
        else:
            manifest_path = conf.metrics_json
            print(f"Run manifest written to {conf.metrics_json}.")
    return manifest_doc, manifest_path, analysis_block


__all__ = [
    "ANALYSIS_KINDS",
    "AnalysisContext",
    "analysis_conf_violations",
    "analysis_partitions",
    "check_analysis_conf",
    "cohort_sample_names",
    "finish_analysis_run",
    "iter_site_blocks",
]

"""GRM/kinship: allele-frequency-standardized genetic relatedness (L5).

The VanRaden genetic relatedness matrix over has-variation genotypes
``X ∈ {0,1}^(M×N)`` with per-site observed frequencies ``p_v = k_v / n``:

    GRM = (X − P)ᵀ (X − P) / Σ_v p_v·q_v,       P[v, s] = p_v

This is a TWO-PASS REWEIGHTING of the existing Gramian, not a new
reduction: expanding the centering,

    (X − P)ᵀ(X − P) = XᵀX − (U·1ᵀ + 1·Uᵀ)/n + (Σ_v k_v²)/n² · J

where ``U = Σ_v k_v·x_v`` (an N-vector) — so the O(M·N²) device work is
EXACTLY the PCA similarity accumulation (``ops/gramian.py``: same dtype
ladder, same packed ring, same exactness contracts ``check/ranges.py``
proves), and the AF pass is O(M·N) integer moments computed on host from
the same streamed blocks (``utils/af.py``: carrier counts, the integer
variance numerator ``k·(n−k)`` with its monomorphic zero-variance guard).
The finalize is one float64 formula over EXACT int64 numerators:

    GRM = (n²·G − n·(U·1ᵀ + 1·Uᵀ) + S2·J) / C,
    S2 = Σ k_v²,   C = Σ k_v·(n − k_v) = n²·Σ p·q

— every term an exact integer (int64 headroom: ``n²·G ≤ n²·M < 2^63``
through the declared 40M-site, 25K-sample geometry), so the NumPy oracle
computes the IDENTICAL float64 matrix and CI's byte compare is exact,
not approximate. ``C == 0`` (every site monomorphic) is an error, not a
NaN matrix.

The device accumulation rides a full ``VariantsPcaDriver`` — strategy
resolution (dense vs packed-ring sharded), the f32→int32 dtype ladder,
``--ring-pack-bits``, flush telemetry — so the GRM inherits every Gramian
hardening without duplicating a line of it.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from spark_examples_tpu.analyses.base import (
    analysis_partitions,
    check_analysis_conf,
    cohort_sample_names,
    finish_analysis_run,
    iter_site_blocks,
)
from spark_examples_tpu.config import GrmConf
from spark_examples_tpu.utils.af import carrier_counts, variance_counts


class GrmMoments:
    """The AF pass: exact int64 per-site moments accumulated block by
    block alongside the device Gramian feed — ``U = Σ k·x`` (N,), ``S2 =
    Σ k²``, ``C = Σ k·(n−k)`` — O(N) host state, never O(M)."""

    def __init__(self, num_samples: int):
        self.n = int(num_samples)
        self.U = np.zeros(self.n, dtype=np.int64)
        self.S2 = 0
        self.C = 0
        self.sites = 0

    def add_block(self, rows: np.ndarray) -> None:
        X = np.asarray(rows, dtype=np.int64)
        k = carrier_counts(X)
        self.U += k @ X
        self.S2 += int((k * k).sum())
        self.C += int(variance_counts(k, self.n).sum())
        self.sites += X.shape[0]


def grm_finalize(G: np.ndarray, moments: GrmMoments) -> np.ndarray:
    """The float64 VanRaden finalize over exact int64 numerators (module
    docstring formula). ``G`` is the raw integer Gramian ``XᵀX`` (any
    accumulator dtype whose entries are exact integers — the
    ``graftcheck ranges`` invariant)."""
    n = moments.n
    if moments.C == 0:
        raise ValueError(
            f"kinship undefined: all {moments.sites} streamed site(s) are "
            "monomorphic (zero variance) — nothing to standardize by"
        )
    Gi = np.asarray(G).astype(np.int64)  # private copy, mutated in place
    if Gi.shape != (n, n):
        raise ValueError(f"expected a ({n}, {n}) Gramian, got {Gi.shape}")
    # n²·G − n·(U·1ᵀ + 1·Uᵀ) + S2, built in place: the transients are two
    # N-vectors, not N×N temporaries — at the declared 25K-sample geometry
    # each N×N int64 is ~5 GB, so the expression form would triple the
    # finalize's peak host memory after all device work succeeded.
    Gi *= n * n
    nU = n * moments.U
    Gi -= nU[:, None]
    Gi -= nU[None, :]
    Gi += moments.S2
    return np.true_divide(Gi, float(moments.C))


def grm_reference(rows: np.ndarray, num_samples: int) -> np.ndarray:
    """Host NumPy oracle: the same integer-moment formula over the full
    (M, N) genotype matrix at once — what the streamed two-pass result
    must match byte for byte."""
    X = np.asarray(rows, dtype=np.int64)
    moments = GrmMoments(num_samples)
    moments.add_block(X)
    return grm_finalize(X.T @ X, moments)


def format_grm_rows(
    names: Sequence[str], matrix: np.ndarray
) -> Iterator[Tuple]:
    """The kinship TSV rows (name + float64 reprs) — ONE formatter shared
    by the CLI writer and the CI oracle, so the byte compare tests the
    math, never the formatting."""
    for name, row in zip(names, np.asarray(matrix)):
        yield (name, *(repr(float(v)) for v in row))


@dataclass
class GrmResult:
    """One completed GRM run: the host kinship matrix (trimmed, float64),
    column-order sample names, the served-surface summary, and the
    manifest bookkeeping."""

    matrix: np.ndarray
    sample_names: List[str]
    summary: Dict
    manifest: Optional[Dict] = None
    manifest_path: Optional[str] = None


def _summarize(matrix: np.ndarray, sites: int) -> Dict:
    """Host-side facts about a kinship matrix (the serve result surface —
    a served response must not ship the N×N matrix)."""
    M = np.asarray(matrix)
    n = M.shape[0]
    diag = np.diagonal(M)
    off_mask = ~np.eye(n, dtype=bool)
    return {
        "shape": [int(s) for s in M.shape],
        "sites": int(sites),
        "trace": float(np.trace(M)),
        "diag_mean": float(diag.mean()),
        "off_diag_mean": float(M[off_mask].mean()) if n > 1 else 0.0,
    }


def run_grm_pipeline(conf: GrmConf, devices=None) -> GrmResult:
    """The GRM core, CLI-free: conf in, kinship + manifest out — the
    batch verb and the serve executor's ``grm`` kind both call this, so a
    served job executes the identical analysis. ``devices`` restricts the
    run to an executor slice's devices (the serve daemon's sub-mesh),
    exactly like ``run_pipeline``."""
    import jax

    check_analysis_conf(conf, "grm")
    from spark_examples_tpu.pipeline.pca_driver import VariantsPcaDriver
    from spark_examples_tpu.utils.tracing import StageTimes

    driver = VariantsPcaDriver(conf, devices=devices)
    n = len(driver.indexes)
    moments = GrmMoments(n)
    times = StageTimes(recorder=driver.spans)
    heartbeat = None
    if getattr(conf, "heartbeat_seconds", 0) and conf.heartbeat_seconds > 0:
        from spark_examples_tpu.obs.heartbeat import Heartbeat

        heartbeat = Heartbeat(conf.heartbeat_seconds, driver.registry).start()
    import contextlib

    # Slice placement, mirroring run_pipeline: without a mesh, jit'd work
    # lands on the process default device — pin it to the slice's first
    # device so a grm job on a 1-device small slice never contends with
    # the large slice's device 0.
    placement = (
        jax.default_device(devices[0]) if devices else contextlib.nullcontext()
    )
    try:
        with placement, times.stage("ingest+gramian"):

            def rows():
                for _contig, block in iter_site_blocks(
                    conf,
                    driver.source,
                    analysis_partitions(conf, driver.source),
                    driver.io_stats,
                    driver.registry,
                ):
                    hv = block["has_variation"]
                    moments.add_block(hv)
                    yield hv

            similarity = driver.get_similarity_rows(rows())
        with times.stage("grm-finalize"):
            if conf.pca_backend == "host":
                G_host = np.asarray(similarity)
            else:
                G_host = np.asarray(jax.device_get(similarity))
            # Sharded finalizes return the padded matrix; trim to the
            # true cohort (pad columns are all-zero by construction).
            G_host = G_host[:n, :n]
            matrix = grm_finalize(G_host, moments)
    finally:
        if heartbeat is not None:
            heartbeat.stop()

    names = cohort_sample_names(driver.indexes, driver.names)
    if conf.grm_out:
        from spark_examples_tpu.pipeline.sitewriter import SiteOutputWriter

        with SiteOutputWriter(
            conf.grm_out, header=("name", *names)
        ) as writer:
            writer.write_rows(format_grm_rows(names, matrix))
        print(f"Kinship matrix written to {conf.grm_out}.")

    summary = _summarize(matrix, moments.sites)
    print(
        f"GRM over {moments.sites} sites x {n} samples: trace "
        f"{summary['trace']:.4f}, diag mean {summary['diag_mean']:.4f}."
    )
    driver.report_io_stats()
    if conf.profile_dir:
        print(str(times))
    manifest, manifest_path, _ = finish_analysis_run(
        conf,
        "grm",
        driver.spans,
        driver.registry,
        driver.io_stats,
        sites_tested=moments.sites,
        sites_kept=None,
    )
    return GrmResult(
        matrix=matrix,
        sample_names=names,
        summary=summary,
        manifest=manifest,
        manifest_path=manifest_path,
    )


def run(argv: Sequence[str]) -> GrmResult:
    """The ``grm`` CLI verb."""
    conf = GrmConf.parse(argv)
    conf.init_distributed()
    return run_grm_pipeline(conf)


__all__ = [
    "GrmMoments",
    "GrmResult",
    "format_grm_rows",
    "grm_finalize",
    "grm_reference",
    "run",
    "run_grm_pipeline",
]

from spark_examples_tpu.analyses import reads_examples, variants_examples

__all__ = ["reads_examples", "variants_examples"]

"""The four read analyses (``SearchReadsExample.scala:76-307``), TPU-style.

Output strings replicate the reference's formats (including Scala tuple
rendering in the saved text files) so results are comparable byte-for-byte;
the per-position aggregations run as dense scatter-adds on device
(``ops/depth.py``) instead of flatMap+shuffle.

Reads contribute coverage beyond their own shard's right edge; the reference
merged those contributions in the ``reduceByKey`` shuffle. Here each shard
computes an extended window and the tail is carried into the next shard — the
streaming equivalent, exact for shards processed in coordinate order.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from spark_examples_tpu.config import GenomicsConf
from spark_examples_tpu.constants import Examples
from spark_examples_tpu.models.read import Read
from spark_examples_tpu.ops.depth import (
    BASES,
    base_counts,
    depth_counts,
    encode_bases,
)
from spark_examples_tpu.pipeline.datasets import ReadsDataset
from spark_examples_tpu.pipeline.sitewriter import SiteOutputWriter
from spark_examples_tpu.sharding.partitioners import (
    FixedSplits,
    ReadsPartitioner,
    TargetSizeSplits,
)
from spark_examples_tpu.sources.base import GenomicsSource


def _pad_read_length(max_len: int) -> int:
    """Round a shard's max read length up to a multiple of 64: the scatter
    kernels take it as a static shape, so bucketing bounds recompiles while
    never truncating long reads (reads of any length are fully counted)."""
    return max(64, -(-int(max_len) // 64) * 64)


def _write_part_file(out_dir: str, lines: Sequence[str]) -> None:
    """``saveAsTextFile`` shape: a directory with a part file."""
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "part-00000"), "w") as f:
        for line in lines:
            f.write(line + "\n")


def run_example1(
    conf: GenomicsConf,
    source: GenomicsSource,
    snp: int = Examples.CILANTRO,
    sequence: str = "11",
    readset: str = Examples.GOOGLE_EXAMPLE_READSET,
) -> List[str]:
    """Pileup around the cilantro/soap SNP
    (``SearchReadsExample.scala:76-111``): filter covering reads, align text
    columns, print the quality of the SNP base inline."""
    region = {sequence: (snp - 1000, snp + 1000)}
    dataset = ReadsDataset(
        source, [readset], ReadsPartitioner(region, FixedSplits(1))
    )
    covering = [
        read
        for _, read in dataset
        if read.position <= snp
        and read.position + len(read.aligned_sequence) >= snp
    ]
    first = min((r.position for r in covering), default=999999999)
    out = []
    out.append(" " * (snp - first) + "v")
    for read in covering:
        i = snp - read.position
        head, tail = read.aligned_sequence[: i + 1], read.aligned_sequence[i + 1 :]
        q = "%02d" % read.aligned_quality[i]
        out.append(" " * (read.position - first) + head + "(" + q + ") " + tail)
    out.append(" " * (snp - first) + "^")
    for line in out:
        print(line)
    return out


def run_example2(
    conf: GenomicsConf,
    source: GenomicsSource,
    sequence: str = "21",
    region: Optional[Tuple[int, int]] = None,
    readset: str = Examples.GOOGLE_EXAMPLE_READSET,
) -> float:
    """Mean coverage of a chromosome (``SearchReadsExample.scala:116-135``):
    Σ aligned-sequence lengths / sequence length, one device reduce."""
    length = Examples.HUMAN_CHROMOSOMES[sequence]
    if region is None:
        region = (1, length)
    dataset = ReadsDataset(
        source,
        [readset],
        ReadsPartitioner(
            {sequence: region}, TargetSizeSplits(100, 5, 1024, 16 * 1024 * 1024)
        ),
    )
    total = 0
    for _, shard in dataset.iter_shards():
        if shard:
            lengths = jnp.asarray(
                [len(read.aligned_sequence) for _, read in shard], dtype=jnp.int32
            )
            total += int(jnp.sum(lengths))  # graftcheck: disable=GC001 -- deliberate per-shard scalar fetch: the running total is host state and shards arrive serially from the paged source; there is no dispatch pipeline to stall
    coverage = total / float(length)
    print(f"Coverage of chromosome {sequence} = {coverage}")
    return coverage


def _shard_reads_arrays(
    records: Sequence[Tuple[object, Read]],
) -> Tuple[np.ndarray, np.ndarray]:
    positions = np.asarray([r.position for _, r in records], dtype=np.int32)
    lengths = np.asarray(
        [len(r.aligned_sequence) for _, r in records], dtype=np.int32
    )
    return positions, lengths


def run_example3(
    conf: GenomicsConf,
    source: GenomicsSource,
    sequence: str = "21",
    region: Optional[Tuple[int, int]] = None,
    readset: str = Examples.GOOGLE_EXAMPLE_READSET,
) -> str:
    """Per-base read depth (``SearchReadsExample.scala:140-167``): dense
    scatter-add per shard with boundary carry; ``(pos,depth)`` lines for
    covered positions stream, ascending, through the bounded per-site
    writer into ``coverage_<chr>/part-00000`` (the reference's
    ``saveAsTextFile`` bytes, headerless) — peak host memory is O(shard
    window), never O(region). Returns the part-file path."""
    out_path = conf.output_path or "."
    length = Examples.HUMAN_CHROMOSOMES[sequence]
    if region is None:
        region = (1, length)
    dataset = ReadsDataset(
        source,
        [readset],
        ReadsPartitioner(
            {sequence: region}, TargetSizeSplits(100, 5, 1024, 16 * 1024 * 1024)
        ),
    )
    part_path = os.path.join(out_path, f"coverage_{sequence}", "part-00000")
    carry = np.zeros(0, dtype=np.int64)
    carry_start = None
    # Each shard's covered (pos,depth) rows stream straight into the
    # bounded writer — the whole-region in-memory line list (the last
    # hostmem(unbounded) surface of analyses/) is retired.
    with SiteOutputWriter(part_path) as writer:
        for part, shard in dataset.iter_shards():
            span = int(part.end - part.start)
            positions = lengths = None
            read_pad = 64
            if shard:
                positions, lengths = _shard_reads_arrays(shard)
                read_pad = _pad_read_length(int(lengths.max()))
            # The window covers the shard span plus the longest read's
            # overhang (and any carry from the previous shard) — no
            # truncation cap.
            overhang = carry_start + len(carry) - part.start if carry_start is not None else 0
            window = max(span + read_pad, int(overhang))
            # Fresh per-shard window (O(window), reset every iteration — the
            # carry below is the only state crossing shards).
            if shard:
                counts = np.asarray(
                    depth_counts(
                        jnp.asarray(positions),
                        jnp.asarray(lengths),
                        jnp.int32(part.start),
                        window,
                        read_pad,
                    ),
                    dtype=np.int64,
                )
            else:
                counts = np.zeros(window, dtype=np.int64)
            if carry_start is not None and len(carry):
                off = carry_start - part.start
                lo, hi = max(0, off), min(window, off + len(carry))
                if hi > lo:
                    counts[lo:hi] += carry[lo - off : hi - off]
            covered = np.nonzero(counts[:span] > 0)[0]
            writer.write_rows(
                (f"({part.start + i},{counts[i]})",) for i in covered
            )
            carry = counts[span:].copy()
            carry_start = part.end
        if carry_start is not None:
            writer.write_rows(
                (f"({carry_start + i},{carry[i]})",)
                for i in np.nonzero(carry > 0)[0]
            )
    return part_path


def _base_frequencies(
    source: GenomicsSource,
    readsets: List[str],
    partitioner: ReadsPartitioner,
    sequence: str,
    region: Tuple[int, int],
    min_mapping_quality: int,
    min_base_quality: int,
) -> Dict[int, np.ndarray]:
    """Position → per-base counts (the ``freqRDD`` construction,
    ``SearchReadsExample.scala:219-244``), scatter-added per shard on device
    with boundary carry."""
    dataset = ReadsDataset(source, readsets, partitioner)
    result: Dict[int, np.ndarray] = {}
    carry = np.zeros((0, len(BASES)), dtype=np.int64)
    carry_start = None
    for part, shard in dataset.iter_shards():
        span = int(part.end - part.start)
        kept = [r for _, r in shard if r.mapping_quality >= min_mapping_quality]
        L = max((len(r.aligned_sequence) for r in kept), default=0)
        read_pad = _pad_read_length(L) if kept else 64
        overhang = carry_start + len(carry) - part.start if carry_start is not None else 0
        window = max(span + read_pad, int(overhang))
        # Fresh per-shard window (O(window); the carry is the only state
        # crossing shards) — the device scatter-add result, or zeros when
        # no read passed the mapping-quality gate.
        if kept:
            positions = np.asarray([r.position for r in kept], dtype=np.int32)
            codes = np.full((len(kept), L), -1, dtype=np.int8)
            qual_ok = np.zeros((len(kept), L), dtype=bool)
            for i, read in enumerate(kept):
                seq = read.aligned_sequence
                codes[i, : len(seq)] = encode_bases(seq)
                # Base-quality gate (``SearchReadsExample.scala:228``): index
                # must exist in alignedQuality and pass the threshold.
                nq = min(len(read.aligned_quality), len(seq))
                qual_ok[i, :nq] = (
                    np.asarray(read.aligned_quality[:nq]) >= min_base_quality
                )
            counts = np.asarray(
                base_counts(
                    jnp.asarray(positions),
                    jnp.asarray(codes),
                    jnp.asarray(qual_ok),
                    jnp.int32(part.start),
                    window,
                ),
                dtype=np.int64,
            )
        else:
            counts = np.zeros((window, len(BASES)), dtype=np.int64)
        if carry_start is not None and len(carry):
            off = carry_start - part.start
            lo, hi = max(0, off), min(window, off + len(carry))
            if hi > lo:
                counts[lo:hi] += carry[lo - off : hi - off]
        covered = np.nonzero(counts[:span].sum(axis=1) > 0)[0]
        for i in covered:
            result[part.start + int(i)] = counts[i].copy()
        carry = counts[span:].copy()
        carry_start = part.end
    if carry_start is not None:
        for i in np.nonzero(carry.sum(axis=1) > 0)[0]:
            result[carry_start + int(i)] = carry[i].copy()
    return result


def run_example4(
    conf: GenomicsConf,
    source: GenomicsSource,
    sequence: str = "1",
    region: Tuple[int, int] = (100_000_000, 101_000_000),
    normal_readset: str = Examples.GOOGLE_DREAM_SET3_NORMAL,
    tumor_readset: str = Examples.GOOGLE_DREAM_SET3_TUMOR,
    min_mapping_quality: int = 30,
    min_base_quality: int = 30,
    min_freq: float = 0.25,
) -> List[str]:
    """Tumor/normal base-frequency comparison
    (``SearchReadsExample.scala:174-307``): per-position frequent-base sets
    from both readsets, join on position, keep differing sets; saved as
    ``(pos,(normalBases,tumorBases))`` lines under ``diff_<chr>``."""
    out_path = conf.output_path or "."
    partitioner = ReadsPartitioner(
        {sequence: region}, TargetSizeSplits(100, 30, 1024, 16 * 1024 * 1024)
    )
    normal = _base_frequencies(
        source, [normal_readset], partitioner, sequence, region,
        min_mapping_quality, min_base_quality,
    )
    tumor = _base_frequencies(
        source, [tumor_readset], partitioner, sequence, region,
        min_mapping_quality, min_base_quality,
    )

    def frequent(counts: np.ndarray) -> str:
        total = counts.sum()
        if total == 0:
            return ""
        return "".join(
            sorted(
                BASES[i]
                for i in range(len(BASES))
                if counts[i] / total >= min_freq
            )
        )

    lines = []
    for pos in sorted(set(normal) & set(tumor)):
        a, b = frequent(normal[pos]), frequent(tumor[pos])
        if a != b:
            lines.append(f"({pos},({a},{b}))")
    _write_part_file(os.path.join(out_path, f"diff_{sequence}"), lines)
    return lines


__all__ = ["run_example1", "run_example2", "run_example3", "run_example4"]

"""Variant-counting example analyses.

``SearchVariantsExampleKlotho`` (``SearchVariantsExample.scala:39-82``) and
``SearchVariantsExampleBRCA1`` (``SearchVariantsExample.scala:87-112``):
count overlapping records, split variant records from reference-matching
blocks, and (Klotho) exercise the wire-format round trip.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from spark_examples_tpu.config import GenomicsConf
from spark_examples_tpu.constants import GoogleGenomicsPublicData
from spark_examples_tpu.pipeline.datasets import VariantsDataset
from spark_examples_tpu.sharding.contig import Contig
from spark_examples_tpu.sharding.partitioners import VariantsPartitioner
from spark_examples_tpu.sources.base import GenomicsSource

#: rs9536314, Klotho KL F327V (``SearchVariantsExample.scala:34-38,45``).
KLOTHO_CONTIG = Contig("chr13", 33628137, 33628138)
#: BRCA1 gene range (``SearchVariantsExample.scala:93``).
BRCA1_CONTIG = Contig("chr17", 41196311, 41277499)


def _dataset(
    conf: GenomicsConf, source: GenomicsSource, contig: Contig
) -> VariantsDataset:
    partitioner = VariantsPartitioner([contig], conf.bases_per_partition)
    variant_set_id = (
        conf.variant_set_id[0]
        if conf.variant_set_id
        else GoogleGenomicsPublicData.PLATINUM_GENOMES
    )
    return VariantsDataset(source, variant_set_id, partitioner)


def run_klotho(
    conf: GenomicsConf,
    source: GenomicsSource,
    contig: Contig = KLOTHO_CONTIG,
) -> List[str]:
    """``SearchVariantsExampleKlotho.main`` (``SearchVariantsExample.scala:40-81``)."""
    records = list(_dataset(conf, source, contig))
    variants = [v for _, v in records]
    out = []
    out.append(f"We have {len(records)} records that overlap Klotho.")
    n_variant = sum(1 for v in variants if v.alternate_bases is not None)
    out.append(f"But only {n_variant} records are of a variant.")
    n_ref = sum(1 for v in variants if v.alternate_bases is None)
    out.append(f"The other {n_ref} records are reference-matching blocks.")
    for v in variants:
        if v.reference_bases != "N":
            out.append(f"Reference: {v.contig} @ {v.start}")
    # Wire-format round trip (the reference's toJavaVariant smoke check,
    # ``SearchVariantsExample.scala:77-79``).
    for v in variants:
        v.to_json()
    for line in out:
        print(line)
    return out


def run_brca1(
    conf: GenomicsConf,
    source: GenomicsSource,
    contig: Contig = BRCA1_CONTIG,
) -> List[str]:
    """``SearchVariantsExampleBRCA1.main`` (``SearchVariantsExample.scala:88-111``)."""
    records = list(_dataset(conf, source, contig))
    variants = [v for _, v in records]
    out = []
    out.append(f"We have {len(records)} records that overlap BRCA1.")
    n_variant = sum(1 for v in variants if v.reference_bases != "N")
    out.append(f"But only {n_variant} records are of a variant.")
    n_ref = sum(1 for v in variants if v.reference_bases == "N")
    out.append(f"The other {n_ref} records are reference-matching blocks.")
    for line in out:
        print(line)
    return out


__all__ = ["run_klotho", "run_brca1", "KLOTHO_CONTIG", "BRCA1_CONTIG"]

"""Windowed LD r² pruning (L5): the first M-sized-output analysis.

A streaming device pass over contig-ordered site windows: sites fill a
fixed ``(W, N)`` window buffer as blocks stream; each full window runs ONE
device dispatch (``ops/ld.py:build_ld_window_stats`` — blockwise
co-carrier counts under ``shard_map`` when the mesh has a samples axis),
the host greedy-prunes the W×W r² matrix in contig order
(``ops/ld.py:greedy_prune``, strictly-above ``--ld-r2-threshold``), and
the window's kept-mask rows spill straight to the windowed writer
(``pipeline/sitewriter.py``). Windows never cross a contig boundary and
tail windows are zero-padded to the static ``W`` (padding rows are
monomorphic → r² 0 → never pruned against — one compiled program serves
every window).

Host memory is O(window), device memory O(W² + W·N/devices), and the
O(M) result exists only on disk — the per-site output path the N²
reduction layer never needed, bounded by construction (no O(M) host
list anywhere; ``graftcheck hostmem`` audits this file like any staging
layer).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from spark_examples_tpu.analyses.base import (
    AnalysisContext,
    finish_analysis_run,
)
from spark_examples_tpu.config import LdConf
from spark_examples_tpu.ops.ld import build_ld_window_stats, greedy_prune


@dataclass
class LdResult:
    """One completed LD prune: tested/kept counts, the output path (when
    written), and the manifest bookkeeping."""

    sites_tested: int
    sites_kept: int
    out_path: Optional[str] = None
    manifest: Optional[Dict] = None
    manifest_path: Optional[str] = None


class _WindowedPruner:
    """The bounded window engine: a pre-allocated ``(W, N)`` buffer fills
    from the block stream; each flush is one device dispatch + one host
    greedy prune + one writer append. State is O(W·N), independent of M."""

    def __init__(
        self, conf: LdConf, num_samples: int, stats_fn, writer, registry=None
    ):
        self.conf = conf
        self.n = int(num_samples)
        self.W = int(conf.ld_window_sites)
        self.stats_fn = stats_fn
        self.writer = writer
        self.rows = np.zeros((self.W, self.n), dtype=np.uint8)
        self.positions = np.zeros(self.W, dtype=np.int64)
        self.fill = 0
        self.contig: Optional[str] = None
        self.sites_tested = 0
        self.sites_kept = 0
        # Live progress gauges (heartbeat's "analysis kept K/T" segment):
        # a whole-genome prune reports its kept ratio per window, not after
        # hours of silence. None-tolerant so oracle tests can run bare.
        self._tested_gauge = self._kept_gauge = None
        if registry is not None:
            from spark_examples_tpu.obs.metrics import (
                ANALYSIS_SITES_KEPT,
                ANALYSIS_SITES_TESTED,
                well_known_gauge,
            )

            self._tested_gauge = well_known_gauge(
                registry, ANALYSIS_SITES_TESTED
            )
            self._kept_gauge = well_known_gauge(registry, ANALYSIS_SITES_KEPT)

    def add_block(self, contig: str, block: Dict[str, np.ndarray]) -> None:
        if contig != self.contig:
            # Contig boundary: the prune is contig-ordered by contract —
            # flush the tail window before the next contig's sites enter.
            self.flush()
            self.contig = contig
        hv = np.asarray(block["has_variation"], dtype=np.uint8)
        positions = np.asarray(block["positions"], dtype=np.int64)
        offset = 0
        while offset < hv.shape[0]:
            take = min(self.W - self.fill, hv.shape[0] - offset)
            self.rows[self.fill : self.fill + take] = hv[
                offset : offset + take
            ]
            self.positions[self.fill : self.fill + take] = positions[
                offset : offset + take
            ]
            self.fill += take
            offset += take
            if self.fill == self.W:
                self.flush()

    def flush(self) -> None:
        """Process the current (possibly partial) window."""
        if self.fill == 0:
            return
        import jax

        fill = self.fill
        # Tail windows ride the same compiled program: padding rows are
        # all-zero (k = 0, zero variance), so the r² guard keeps them
        # inert — and `valid` excludes them from the output/counters.
        C, k = self.stats_fn(self.rows)
        C = np.asarray(jax.device_get(C))  # graftcheck: disable=GC001 -- deliberate per-window fetch: the greedy prune is host-sequential by design, and the window (not the block) is the bounded unit of device work
        k = np.asarray(jax.device_get(k))  # graftcheck: disable=GC001 -- same per-window fetch as C above
        valid = np.zeros(self.W, dtype=bool)
        valid[:fill] = True
        kept = greedy_prune(
            C, k, self.n, self.conf.ld_r2_threshold, valid=valid
        )
        if self.writer is not None:
            contig = self.contig
            self.writer.write_rows(
                (contig, int(self.positions[i]), int(kept[i]))
                for i in range(fill)
            )
        self.sites_tested += fill
        self.sites_kept += int(kept[:fill].sum())
        if self._tested_gauge is not None:
            self._tested_gauge.set(self.sites_tested)
            self._kept_gauge.set(self.sites_kept)
        self.rows[:fill] = 0
        self.fill = 0


def ld_prune_reference(
    windows: Sequence[Tuple[np.ndarray, np.ndarray]],
    num_samples: int,
    r2_threshold: float,
) -> List[Tuple[int, bool]]:
    """Host NumPy oracle of the windowed prune: ``windows`` is the
    contig-partitioned, window-chunked site stream as ``(positions,
    rows)`` pairs; returns ``(position, kept)`` in stream order."""
    from spark_examples_tpu.ops.ld import ld_window_stats_reference

    out: List[Tuple[int, bool]] = []
    for positions, rows in windows:
        C, k = ld_window_stats_reference(rows)
        kept = greedy_prune(C, k, num_samples, r2_threshold)
        out.extend(
            (int(p), bool(m)) for p, m in zip(positions, kept)
        )
    return out


def run_ld_pipeline(conf: LdConf) -> LdResult:
    """The LD-prune core, CLI-free: conf in, kept-mask + manifest out."""
    from spark_examples_tpu.utils.tracing import StageTimes

    ctx = AnalysisContext(conf, "ld")
    times = StageTimes(recorder=ctx.spans)
    # --pca-backend host runs the window statistics as the NumPy oracle —
    # no mesh, no compiled program — the same host escape hatch GRM and
    # assoc honor.
    host_oracle = conf.pca_backend == "host"
    mesh = None if host_oracle else ctx.make_mesh()
    if mesh is not None:
        from spark_examples_tpu.parallel.mesh import SAMPLES_AXIS

        samples_axis = mesh.shape.get(SAMPLES_AXIS, 1)
        if samples_axis >= 2 and ctx.num_samples % samples_axis:
            # Mirrored by `graftcheck plan --analysis ld`
            # (ld-cohort-not-divisible): the window kernel shards sample
            # columns without padding.
            raise ValueError(
                f"--num-samples {ctx.num_samples} does not divide over "
                f"the mesh samples axis ({samples_axis}); choose a mesh "
                "whose samples axis divides the cohort"
            )
    if host_oracle:
        from spark_examples_tpu.ops.ld import ld_window_stats_reference

        stats_fn = ld_window_stats_reference
    else:
        stats_fn = build_ld_window_stats(mesh)
    writer = None
    if conf.ld_out:
        from spark_examples_tpu.pipeline.sitewriter import SiteOutputWriter

        writer = SiteOutputWriter(
            conf.ld_out, header=("contig", "pos", "kept")
        )
    heartbeat = None
    if getattr(conf, "heartbeat_seconds", 0) and conf.heartbeat_seconds > 0:
        from spark_examples_tpu.obs.heartbeat import Heartbeat

        heartbeat = Heartbeat(conf.heartbeat_seconds, ctx.registry).start()
    pruner = _WindowedPruner(
        conf, ctx.num_samples, stats_fn, writer, registry=ctx.registry
    )
    try:
        with times.stage("ingest+ld-prune"):
            for contig, block in ctx.blocks():
                pruner.add_block(contig, block)
            pruner.flush()
    except BaseException:
        if writer is not None:
            writer.abort()
        raise
    finally:
        if heartbeat is not None:
            heartbeat.stop()
    if writer is not None:
        writer.close()
        print(f"Kept-site mask written to {conf.ld_out}.")
    print(
        f"LD prune (r² > {conf.ld_r2_threshold} pruned, window "
        f"{conf.ld_window_sites}): kept {pruner.sites_kept} / "
        f"{pruner.sites_tested} sites."
    )
    print(str(ctx.io_stats))
    if conf.profile_dir:
        print(str(times))
    manifest, manifest_path, _ = finish_analysis_run(
        conf,
        "ld",
        ctx.spans,
        ctx.registry,
        ctx.io_stats,
        sites_tested=pruner.sites_tested,
        sites_kept=pruner.sites_kept,
    )
    return LdResult(
        sites_tested=pruner.sites_tested,
        sites_kept=pruner.sites_kept,
        out_path=conf.ld_out,
        manifest=manifest,
        manifest_path=manifest_path,
    )


def run(argv: Sequence[str]) -> LdResult:
    """The ``ld-prune`` CLI verb."""
    conf = LdConf.parse(argv)
    conf.init_distributed()
    return run_ld_pipeline(conf)


__all__ = [
    "LdResult",
    "ld_prune_reference",
    "run",
    "run_ld_pipeline",
]

"""CLI configuration: the reference's flag grammar, preserved.

``GenomicsConf`` mirrors ``GenomicsConf.scala:29-64`` and ``PcaConf`` mirrors
``GenomicsConf.scala:66-98``. The flag surface is the API contract
(``BASELINE.md``): names, defaults, and the ``--references`` grammar
(``ref:start:end,...`` — one list per variant set) are identical. TPU-specific
additions are kept separate and optional:

- ``--source {synthetic,rest}``: which genomics backend to stream from (the
  reference always hit the live Google Genomics API, which no longer exists);
- ``--pca-backend {tpu,host}``: device pipeline vs. pure-NumPy reference
  implementation (the BASELINE.json north-star flag);
- ``--mesh-shape``: devices for the data×samples mesh; by analogy with the
  reference, ``--num-reduce-partitions`` bounds the data-axis size when
  ``--mesh-shape`` is not given (BASELINE.json maps the Spark reduce
  parallelism onto the device mesh).
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from spark_examples_tpu.constants import GoogleGenomicsPublicData
from spark_examples_tpu.sharding.contig import (
    BRCA1,
    DEFAULT_BASES_PER_SHARD,
    Contig,
    SexChromosomeFilter,
    parse_contigs,
)


def _num_samples_value(text: str) -> str:
    """Validate ``--num-samples`` (an int, or a comma list of ints) at parse
    time so malformed input gets argparse's usage error, not a traceback."""
    values = [v for v in text.split(",") if v.strip()]
    if not values:
        raise argparse.ArgumentTypeError("needs at least one value")
    for v in values:
        try:
            int(v)
        except ValueError:
            raise argparse.ArgumentTypeError(f"invalid int value: {v!r}")
    return text


def _build_base_parser(parser: argparse.ArgumentParser) -> argparse.ArgumentParser:
    parser.add_argument(
        "--bases-per-partition",
        type=int,
        default=DEFAULT_BASES_PER_SHARD,
        help="Partition each reference using a fixed number of bases",
    )
    parser.add_argument("--client-secrets", default="client_secrets.json")
    parser.add_argument("--input-path", default=None)
    parser.add_argument(
        "--num-reduce-partitions",
        type=int,
        default=10,
        help=(
            "Set it to a number greater than the number of cores, to achieve "
            "maximum throughput. Maps onto the device-mesh data axis."
        ),
    )
    parser.add_argument("--output-path", default=None)
    parser.add_argument(
        "--references",
        default=BRCA1,
        help=(
            "Comma separated tuples of reference:start:end,... one list of "
            "tuples should be specified per variantset in the corresponding "
            "order (lists separated by ';')."
        ),
    )
    parser.add_argument(
        "--spark-master",
        default=None,
        help="Accepted for flag compatibility with the reference; unused.",
    )
    parser.add_argument(
        "--variant-set-id",
        default=GoogleGenomicsPublicData.THOUSAND_GENOMES_PHASE_1,
        help="Comma-separated list of VariantSetIds to use in the analysis.",
    )
    # TPU-native additions.
    parser.add_argument(
        "--source",
        choices=["synthetic", "rest", "file"],
        default="synthetic",
        help="Genomics backend to stream from.",
    )
    parser.add_argument(
        "--input-files",
        default=None,
        help=(
            "Comma-separated input files for --source file: .vcf[.gz] / "
            ".jsonl[.gz] variants (or a checkpoint directory), .sam reads. "
            "Each file becomes one variant set whose id is its sanitized "
            "stem; --variant-set-id defaults to all of them in order."
        ),
    )
    parser.add_argument(
        "--stream-chunk-bytes",
        type=int,
        default=None,
        help=(
            "Bounded-memory streaming ingest for --source file VCF inputs: "
            "parse in chunks of this many decompressed bytes instead of "
            "loading the file (one pass, coordinate-sorted VCFs only). "
            "Unset = automatic (streams when the file exceeds the size "
            "threshold); 0 = never stream; N > 0 = always stream with "
            "N-byte chunks."
        ),
    )
    parser.add_argument(
        "--ingest-workers",
        type=int,
        default=None,
        help=(
            "Parse threads for the chunk-parallel file ingest engine "
            "(--source file VCF inputs): the decompressed text is split "
            "into line-aligned chunks parsed concurrently through the "
            "GIL-releasing native parser, with an order-preserving merge. "
            "Default: min(8, cpu_count). 0 = the serial oracle path "
            "(byte-identical output, kept as the parity reference)."
        ),
    )
    parser.add_argument(
        "--num-samples",
        type=_num_samples_value,
        default="2504",
        help=(
            "Synthetic-source cohort size (1KG phase 1 has 2,504 samples). "
            "A comma-separated list gives per-variant-set cohort sizes, "
            "zipped positionally with --variant-set-id (e.g. '2504,17' for "
            "the 1KG × Platinum joint-cohort scenario); sets beyond the "
            "list use the first value."
        ),
    )
    parser.add_argument(
        "--seed", type=int, default=42, help="Synthetic-source base seed."
    )
    # Observability (obs/): background progress heartbeat + machine-readable
    # run manifest. Both default off, so stdout/stderr are byte-identical to
    # telemetry-free runs unless asked for.
    parser.add_argument(
        "--heartbeat-seconds",
        type=float,
        default=0.0,
        help=(
            "Emit a progress line to stderr every N seconds during the run "
            "(sites scanned + rate, partition progress with ETA, prefetch "
            "queue occupancy, dispatch pipeline depth, device memory when "
            "the backend reports it — obs/heartbeat.py). 0 = off (default)."
        ),
    )
    parser.add_argument(
        "--metrics-json",
        default=None,
        metavar="PATH",
        help=(
            "Write the schema-versioned end-of-run manifest here: config "
            "echo, hierarchical stage spans, every registry metric, I/O "
            "stats, and ingest-overlap accounting (obs/manifest.py). The "
            "numbers match the printed epilogue exactly; bench.py and CI "
            "consume this instead of scraping stdout."
        ),
    )
    parser.add_argument(
        "--trace-dir",
        default=None,
        metavar="DIR",
        help=(
            "Record crash-durable per-stage flight-recorder events under "
            "DIR/trace (obs/recorder.py): one append-only segment per "
            "process, so a multi-process run's stage timelines merge into "
            "ONE Chrome trace with `python -m spark_examples_tpu trace "
            "export --run-dir DIR` (obs/trace.py). Off by default."
        ),
    )
    # Robustness (pipeline/checkpoint.py): crash-consistent Gramian
    # checkpointing + resume. The Gramian is additive over variants, so a
    # preempted/killed analysis pass resumes at O(remaining) device cost
    # with byte-identical results (the graftcheck-ranges exactness
    # contracts make this exact, not approximate).
    parser.add_argument(
        "--gramian-checkpoint-dir",
        default=None,
        metavar="DIR",
        help=(
            "Periodically persist the device accumulator state (partial "
            "Gramian + dtype-ladder position + site cursor + conf "
            "fingerprint) as one atomically-published artifact under DIR, "
            "so a killed run can resume without restarting from zero "
            "(host-fed ingest paths: packed/wire; --pca-backend tpu)."
        ),
    )
    parser.add_argument(
        "--checkpoint-every-sites",
        type=int,
        default=None,
        metavar="N",
        help=(
            "Snapshot cadence for --gramian-checkpoint-dir: one atomic "
            "checkpoint per N accumulated sites (each costs one "
            "accumulator sync + one O(N^2) host fetch + write). Default: "
            "~18 snapshots across a whole genome "
            "(pipeline/checkpoint.py:DEFAULT_CHECKPOINT_EVERY_SITES)."
        ),
    )
    parser.add_argument(
        "--resume-from",
        default=None,
        metavar="DIR",
        help=(
            "Resume an interrupted analysis pass from the newest complete "
            "Gramian checkpoint in DIR: the artifact's conf fingerprint "
            "must match this run's flags (CheckpointMismatchError "
            "otherwise), the persisted partial merges into a fresh "
            "accumulator, and ingest fast-forwards to the saved cursor. "
            "No complete artifact yet = start from zero. Point it at the "
            "same directory as --gramian-checkpoint-dir to keep "
            "checkpointing while resumed."
        ),
    )
    # Robustness (utils/faults.py): a deterministic fault plan for chaos
    # testing. Normally injected via the SPARK_EXAMPLES_TPU_FAULTS env var
    # (subprocess harnesses); the flag form serves interactive repros.
    parser.add_argument(
        "--fault-plan",
        default=None,
        metavar="SPEC",
        help=(
            "Deterministic fault-injection plan (testing): comma-separated "
            "action@site[#nth][=arg] entries fired at registered "
            "kill-points and IO boundaries (utils/faults.py:KILL_POINTS/"
            "IO_POINTS). Equivalent to the SPARK_EXAMPLES_TPU_FAULTS "
            "environment variable; the flag wins when both are set."
        ),
    )
    # Multi-host initialization (jax.distributed) — the analog of pointing
    # the reference at a Spark cluster master (GenomicsConf.scala:50-57).
    # With these set, jax.devices() spans all hosts and the device mesh
    # (and therefore data-parallel ingest + the finalize psum) runs
    # multi-controller SPMD over ICI/DCN.
    parser.add_argument("--coordinator-address", default=None)
    parser.add_argument("--num-processes", type=int, default=None)
    parser.add_argument("--process-id", type=int, default=None)
    return parser


@dataclass
class GenomicsConf:
    """Parsed base flags (``GenomicsConf.scala:29-64``)."""

    bases_per_partition: int = DEFAULT_BASES_PER_SHARD
    client_secrets: str = "client_secrets.json"
    input_path: Optional[str] = None
    num_reduce_partitions: int = 10
    output_path: Optional[str] = None
    references: str = BRCA1
    spark_master: Optional[str] = None
    variant_set_id: List[str] = field(
        default_factory=lambda: [GoogleGenomicsPublicData.THOUSAND_GENOMES_PHASE_1]
    )
    source: str = "synthetic"
    input_files: Optional[List[str]] = None
    stream_chunk_bytes: Optional[int] = None
    ingest_workers: Optional[int] = None
    num_samples: int = 2504
    num_samples_per_set: Optional[List[int]] = None
    seed: int = 42
    heartbeat_seconds: float = 0.0
    metrics_json: Optional[str] = None
    trace_dir: Optional[str] = None
    gramian_checkpoint_dir: Optional[str] = None
    checkpoint_every_sites: Optional[int] = None
    resume_from: Optional[str] = None
    fault_plan: Optional[str] = None
    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None

    @classmethod
    def parse(cls, argv: Sequence[str]) -> "GenomicsConf":
        parser = _build_base_parser(argparse.ArgumentParser())
        ns = parser.parse_args(list(argv))
        return cls._from_namespace(ns)

    def init_distributed(self) -> None:
        """Initialize multi-host JAX when the cluster flags are set (no-op
        otherwise) — call before any device use."""
        from spark_examples_tpu.parallel.mesh import distributed_init

        distributed_init(
            coordinator_address=self.coordinator_address,
            num_processes=self.num_processes,
            process_id=self.process_id,
        )

    @classmethod
    def _from_namespace(cls, ns: argparse.Namespace) -> "GenomicsConf":
        conf = cls()
        for f in conf.__dataclass_fields__:
            if hasattr(ns, f):
                setattr(conf, f, getattr(ns, f))
        if isinstance(conf.variant_set_id, str):
            conf.variant_set_id = [
                v for v in conf.variant_set_id.split(",") if v.strip()
            ]
        if isinstance(conf.input_files, str):
            conf.input_files = [
                p.strip() for p in conf.input_files.split(",") if p.strip()
            ]
        if isinstance(conf.num_samples, str):
            sizes = [
                int(s) for s in conf.num_samples.split(",") if s.strip()
            ]
            if not sizes:
                raise ValueError("--num-samples needs at least one value")
            conf.num_samples = sizes[0]
            conf.num_samples_per_set = sizes if len(sizes) > 1 else None
        if conf.heartbeat_seconds < 0:
            raise ValueError(
                f"--heartbeat-seconds must be >= 0 (0 = off), got "
                f"{conf.heartbeat_seconds}"
            )
        if conf.ingest_workers is not None and conf.ingest_workers < 0:
            raise ValueError(
                f"--ingest-workers must be >= 0 (0 = serial oracle path), "
                f"got {conf.ingest_workers}"
            )
        if (
            conf.checkpoint_every_sites is not None
            and conf.checkpoint_every_sites < 1
        ):
            raise ValueError(
                f"--checkpoint-every-sites must be >= 1, got "
                f"{conf.checkpoint_every_sites} (omit the flag for the "
                "default cadence)"
            )
        if conf.fault_plan is not None:
            # Fail at parse time with the grammar error, not mid-run: a
            # typo'd site name must not cost a whole ingest pass. Pure
            # stdlib (utils/faults.py imports no jax).
            from spark_examples_tpu.utils.faults import parse_plan

            parse_plan(conf.fault_plan)
        # --blocks-per-dispatch is PcaConf-only; validated here so every
        # parse path shares it. An explicit value must be positive: 0 is not
        # a documented auto spelling (leave the flag unset for auto), and
        # treating it as falsy-auto silently ignored the user's input.
        bpd = getattr(conf, "blocks_per_dispatch", None)
        if bpd is not None and bpd <= 0:
            raise ValueError(
                f"--blocks-per-dispatch must be a positive dispatch-group "
                f"length, got {bpd} (omit the flag for the auto rule)"
            )
        if conf.num_samples_per_set:
            if conf.source != "synthetic":
                # Cohort sizing only exists for the synthetic source; files
                # and APIs carry their own cohorts — silently ignoring the
                # flag would let users believe they sized the run.
                raise ValueError(
                    "per-set --num-samples is synthetic-source-only "
                    f"(--source {conf.source} reads its cohorts from the data)"
                )
            if len(set(conf.variant_set_id)) != len(conf.variant_set_id):
                # Per-set sizes are keyed by set id downstream; duplicate ids
                # would silently collapse to one size instead of the
                # positional sizes the flag documents.
                raise ValueError(
                    "per-set --num-samples requires distinct --variant-set-id "
                    "values (duplicate ids share one cohort)"
                )
        if conf.source == "file":
            if not conf.input_files:
                raise ValueError("--source file requires --input-files")
            from spark_examples_tpu.sources.files import file_set_ids

            ids = file_set_ids(conf.input_files)
            if conf.variant_set_id == [
                GoogleGenomicsPublicData.THOUSAND_GENOMES_PHASE_1
            ]:
                # The untouched default: every input file is one variant set.
                conf.variant_set_id = ids
            elif not set(conf.variant_set_id) <= set(ids):
                # An explicit id that matches no input must fail loudly, not
                # silently widen the run back to every file.
                raise ValueError(
                    f"--variant-set-id {conf.variant_set_id} not among the "
                    f"file-derived set ids {ids}"
                )
        return conf

    def get_references(self) -> List[List[Contig]]:
        """One contig list per variant set (``GenomicsConf.scala:59-63``).

        The reference passes one ``--references`` list per variant set in
        order; we use ';' to separate the per-variantset lists and ',' within
        a list, mirroring the documented grammar.
        """
        return [parse_contigs(spec) for spec in self.references.split(";")]


def build_pca_parser(
    parser: Optional[argparse.ArgumentParser] = None,
) -> argparse.ArgumentParser:
    """The full PCA flag surface on one parser — shared by
    :meth:`PcaConf.parse` and the device-free plan validator
    (``check/plan.py``), so ``graftcheck plan`` validates exactly the
    grammar the real run parses, never a drifted copy."""
    parser = _build_base_parser(parser or argparse.ArgumentParser())
    parser.add_argument(
        "--all-references",
        action="store_true",
        help=(
            "Use all references (except X and Y) to compute PCA "
            "(overrides --references)."
        ),
    )
    parser.add_argument("--debug-datasets", action="store_true")
    parser.add_argument("--min-allele-frequency", type=float, default=None)
    parser.add_argument("--num-pc", type=int, default=2)
    parser.add_argument(
        "--pca-backend",
        choices=["tpu", "host"],
        default="tpu",
        help="Similarity/PCA compute path: device pipeline or NumPy host path.",
    )
    parser.add_argument(
        "--mesh-shape",
        default=None,
        help="Device mesh as 'data,samples' (e.g. '4,2'). Default: all "
        "devices on the data axis, capped by --num-reduce-partitions.",
    )
    parser.add_argument(
        "--block-size",
        type=int,
        default=1024,
        help="Variants per device block in the Gramian accumulation.",
    )
    parser.add_argument(
        "--ingest",
        choices=["auto", "device", "packed", "wire"],
        default="auto",
        help=(
            "Genotype ingest path: 'device' generates the synthetic data "
            "plane on the TPU fused with the Gramian (fastest; synthetic "
            "source only), 'packed' builds dense blocks on host, 'wire' "
            "streams full JSON records through the dataset layer. 'auto' "
            "picks the fastest path valid for the configuration."
        ),
    )
    parser.add_argument(
        "--fused-jobs",
        type=int,
        default=None,
        metavar="K",
        help=(
            "Validate/audit the configuration as one lane of a K-job "
            "fused batch group (the serving daemon's stacked device "
            "program; ops/batched.py): `graftcheck plan` charges HBM "
            "for K stacked accumulators and rejects over-budget groups, "
            "and `graftcheck ir`/`ranges` audit the stacked kernel. "
            "Plan-time only — a batch run ignores it."
        ),
    )
    parser.add_argument(
        "--blocks-per-dispatch",
        type=int,
        default=None,
        help=(
            "Device-ingest blocks fused per dispatch (lax.scan length); "
            "higher amortizes per-dispatch overhead on remote-attached "
            "backends. Default: auto — constant device work per "
            "dispatch, so small cohorts get longer scans "
            "(ops/devicegen.py:auto_blocks_per_dispatch)."
        ),
    )
    parser.add_argument(
        "--ring-pack-bits",
        choices=["auto", "on", "off"],
        default="auto",
        help=(
            "Sharded-ring wire format: circulate BIT-PACKED sample-column "
            "tiles over ICI (8 genotypes/byte — 8x less ring and "
            "host-to-device traffic) and unpack on device per ring step; "
            "the cohort pads to a multiple of 8x the samples axis (padded "
            "columns are all-zero and trimmed). 'off' keeps the unpacked "
            "uint8 wire as the bit-exact parity oracle; 'auto' (default) "
            "currently equals 'on'. Count-valued blocks (same-set joins) "
            "always ride the unpacked kernel regardless."
        ),
    )
    parser.add_argument(
        "--reduce-schedule",
        choices=["auto", "flat", "hier"],
        default="auto",
        help=(
            "Sharded-ring reduction schedule: 'flat' circulates tiles "
            "around ONE ring over the whole samples axis; 'hier' runs the "
            "two-level schedule (packed intra-host ring over ICI, "
            "inter-host ring over DCN — one DCN hop hides behind a whole "
            "inner ring) over the host-major factorization of the samples "
            "axis. 'auto' (default) = hier iff the samples axis spans "
            "more than one host. Same bytes, same results (byte-identical"
            ", CI-asserted); the split of bytes across link classes is "
            "what `graftcheck sched` proves per topology."
        ),
    )
    parser.add_argument(
        "--check-ranges",
        action="store_true",
        help=(
            "DEBUG: sample the max |accumulator entry| after every Gramian "
            "flush (one device fetch per flush — slow by design) into the "
            "gramian_entry_max gauge, next to the statically-projected "
            "gramian_static_entry_bound; the run manifest records the pair "
            "and CI asserts measured <= proven — the runtime half of the "
            "`graftcheck ranges` exactness contract. Host-fed accumulators "
            "only (packed/wire ingest); the fused device-generation path "
            "has no host flush to instrument."
        ),
    )
    parser.add_argument(
        "--exact-similarity",
        action="store_true",
        help=(
            "Force integer (int8xint8->int32) Gramian accumulation. By "
            "default the f32-accumulation MXU path is used unless the "
            "projected per-entry count approaches f32's 2^24 exact-integer "
            "limit, in which case the integer path is auto-selected."
        ),
    )
    parser.add_argument(
        "--similarity-strategy",
        choices=["auto", "dense", "sharded"],
        default="auto",
        help=(
            "Similarity accumulation strategy: 'dense' replicates the NxN "
            "Gramian per data-parallel device (VariantsPca.scala:210-231); "
            "'sharded' row-tile-shards it over the mesh samples axis (the "
            "memory-bounded analog of getSimilarityMatrixStream, "
            ":288-319). 'auto' picks by cohort size."
        ),
    )
    parser.add_argument(
        "--num-workers",
        type=int,
        default=8,
        help="Host threads for parallel shard streaming.",
    )
    parser.add_argument(
        "--profile-dir",
        default=None,
        help=(
            "Write a jax.profiler device trace (TensorBoard-loadable) "
            "here and print per-stage wall-clock timings — the Spark-UI "
            "stand-in (utils/tracing.py)."
        ),
    )
    parser.add_argument(
        "--save-variants",
        default=None,
        metavar="PATH",
        help=(
            "Materialize the ingested variants as a checkpoint directory "
            "at PATH while the analysis streams (one part file per "
            "shard), for later --input-path resume without re-ingesting. "
            "Wire ingest, single variant set (the writer the reference's "
            "objectFile resume never had, VariantsPca.scala:112-113)."
        ),
    )
    return parser


@dataclass
class PcaConf(GenomicsConf):
    """PCA flags (``GenomicsConf.scala:70-98``)."""

    all_references: bool = False
    debug_datasets: bool = False
    min_allele_frequency: Optional[float] = None
    num_pc: int = 2
    pca_backend: str = "tpu"
    mesh_shape: Optional[str] = None
    block_size: int = 1024
    ingest: str = "auto"
    fused_jobs: Optional[int] = None
    blocks_per_dispatch: Optional[int] = None
    ring_pack_bits: str = "auto"
    reduce_schedule: str = "auto"
    check_ranges: bool = False
    exact_similarity: bool = False
    similarity_strategy: str = "auto"
    num_workers: int = 8
    profile_dir: Optional[str] = None
    save_variants: Optional[str] = None

    EXCLUDE_XY = SexChromosomeFilter.EXCLUDE_XY

    @classmethod
    def parse(cls, argv: Sequence[str]) -> "PcaConf":
        ns = build_pca_parser().parse_args(list(argv))
        return cls._from_namespace(ns)

    def get_contigs(self, source, variant_set_ids: Sequence[str]) -> List[Contig]:
        """Contigs for all datasets (``GenomicsConf.scala:83-97``).

        ``--all-references`` asks the source for every contig in each variant
        set, excluding X/Y; otherwise the per-variantset ``--references``
        lists are parsed positionally.
        """
        print(f"Running PCA on {len(variant_set_ids)} datasets.")
        contigs: List[Contig] = []
        if self.all_references:
            for variant_set_id in variant_set_ids:
                print(f"Variantset: {variant_set_id}; All refs, exclude XY")
                contigs.extend(
                    source.get_contigs(variant_set_id, SexChromosomeFilter.EXCLUDE_XY)
                )
        else:
            # Scala zip semantics (``GenomicsConf.scala:91-95``): the
            # variantset list is zipped with the per-set reference lists and
            # TRUNCATED to the shorter — one --references list with two
            # variant sets contributes its contigs once, not per set.
            reference_lists = self.references.split(";")
            for variant_set_id, spec in zip(variant_set_ids, reference_lists):
                print(f"Variantset: {variant_set_id}; Refs: {spec}")
                contigs.extend(parse_contigs(spec))
        return contigs


# --------------------------------------------------------------------------
# Population-genetics analyses (analyses/): one conf per CLI verb, each a
# thin extension of the PCA flag surface — the analyses ride the same
# sources/mesh/block/telemetry flags, so everything the plan validator and
# the serve admission path already know keeps applying. The shared base
# parser means `graftcheck plan --analysis grm|ld|assoc` validates EXACTLY
# the grammar the real verbs parse, never a drifted copy.
# --------------------------------------------------------------------------


def build_grm_parser(
    parser: Optional[argparse.ArgumentParser] = None,
) -> argparse.ArgumentParser:
    """``grm`` verb flags: the PCA surface plus the kinship output path."""
    parser = build_pca_parser(parser)
    parser.add_argument(
        "--grm-out",
        default=None,
        metavar="PATH",
        help=(
            "Write the N×N VanRaden kinship matrix as a TSV (one row per "
            "sample: name, then N float64 values; atomic publish). Unset: "
            "only the summary is printed — the matrix never needs to "
            "leave the device path for summaries."
        ),
    )
    return parser


def build_ld_parser(
    parser: Optional[argparse.ArgumentParser] = None,
) -> argparse.ArgumentParser:
    """``ld-prune`` verb flags: windowed r² pruning over contig-ordered
    sites."""
    parser = build_pca_parser(parser)
    parser.add_argument(
        "--ld-r2-threshold",
        type=float,
        default=0.2,
        help=(
            "Prune a site whose r² with any previously-kept site in its "
            "window is STRICTLY greater than this (greedy, contig order; "
            "must be in [0, 1])."
        ),
    )
    parser.add_argument(
        "--ld-window-sites",
        type=int,
        default=256,
        help=(
            "Sites per pruning window (>= 2). Windows are contig-ordered "
            "and independent; the device computes one W×W co-carrier "
            "matrix per window, so host and HBM cost is O(W²), never O(M)."
        ),
    )
    parser.add_argument(
        "--ld-out",
        default=None,
        metavar="PATH",
        help=(
            "Write the per-site kept mask as a TSV (contig, pos, kept "
            "0/1), streamed window by window (bounded host memory, atomic "
            "publish). Unset: only the kept/tested counts are printed."
        ),
    )
    return parser


def build_assoc_parser(
    parser: Optional[argparse.ArgumentParser] = None,
) -> argparse.ArgumentParser:
    """``assoc-scan`` verb flags: per-site case/control chi-square."""
    parser = build_pca_parser(parser)
    parser.add_argument(
        "--phenotypes",
        default=None,
        metavar="TSV",
        help=(
            "REQUIRED: two-column TSV (sample name, status 0=control/"
            "1=case; '#' comment lines skipped) covering every cohort "
            "sample by its callset name."
        ),
    )
    parser.add_argument(
        "--assoc-out",
        default=None,
        metavar="PATH",
        help=(
            "Write the per-site scan as a TSV (contig, pos, case "
            "carriers, total carriers, chi2), streamed block by block "
            "(bounded host memory, atomic publish). Unset: only the "
            "top-ranked sites are printed."
        ),
    )
    parser.add_argument(
        "--assoc-top",
        type=int,
        default=10,
        help=(
            "How many top-chi² sites to print (and return) — a bounded "
            "heap, so the ranking never holds O(M) rows on host."
        ),
    )
    return parser


@dataclass
class GrmConf(PcaConf):
    """``grm`` flags: allele-frequency-standardized kinship (VanRaden)."""

    grm_out: Optional[str] = None

    @classmethod
    def parse(cls, argv: Sequence[str]) -> "GrmConf":
        ns = build_grm_parser().parse_args(list(argv))
        return cls._from_namespace(ns)


@dataclass
class LdConf(PcaConf):
    """``ld-prune`` flags: windowed LD r² pruning."""

    ld_r2_threshold: float = 0.2
    ld_window_sites: int = 256
    ld_out: Optional[str] = None

    @classmethod
    def parse(cls, argv: Sequence[str]) -> "LdConf":
        ns = build_ld_parser().parse_args(list(argv))
        return cls._from_namespace(ns)

    @classmethod
    def _from_namespace(cls, ns: argparse.Namespace) -> "LdConf":
        conf = super()._from_namespace(ns)
        # Parse-time rejects (the plan validator repeats these for
        # programmatic confs): a threshold outside [0,1] silently keeps or
        # prunes everything, a window below 2 has nothing to correlate.
        if not (0.0 <= conf.ld_r2_threshold <= 1.0):
            raise ValueError(
                f"--ld-r2-threshold must be in [0, 1], got "
                f"{conf.ld_r2_threshold}"
            )
        if conf.ld_window_sites < 2:
            raise ValueError(
                f"--ld-window-sites must be >= 2, got {conf.ld_window_sites}"
            )
        return conf


@dataclass
class AssocConf(PcaConf):
    """``assoc-scan`` flags: per-site case/control chi-square."""

    phenotypes: Optional[str] = None
    assoc_out: Optional[str] = None
    assoc_top: int = 10

    @classmethod
    def parse(cls, argv: Sequence[str]) -> "AssocConf":
        ns = build_assoc_parser().parse_args(list(argv))
        return cls._from_namespace(ns)

    @classmethod
    def _from_namespace(cls, ns: argparse.Namespace) -> "AssocConf":
        conf = super()._from_namespace(ns)
        if conf.assoc_top < 1:
            raise ValueError(
                f"--assoc-top must be >= 1, got {conf.assoc_top}"
            )
        return conf


__all__ = [
    "AssocConf",
    "GenomicsConf",
    "GrmConf",
    "LdConf",
    "PcaConf",
    "build_assoc_parser",
    "build_grm_parser",
    "build_ld_parser",
    "build_pca_parser",
]

"""The graftcheck rule catalogue.

Each rule is one silent-failure class of this codebase's hot paths: the
linter (``linter.py``) walks the package AST and anchors findings to these
IDs. Scope globs keep repo-tuned rules out of code where the pattern is
legitimate (e.g. host-sync calls are fine in tests and the host oracle).

Adding a rule (see DESIGN.md §"graftcheck"):

1. register a :class:`Rule` here with a fresh ``GCnnn`` id;
2. implement its visitor hook in ``linter.py:_LintVisitor`` (emit via
   ``self.emit(RULE_ID, node, detail)``);
3. add a violation fixture + a clean fixture to
   ``tests/test_graftcheck.py`` asserting the id and line number.

Every rule honors the escape hatch::

    something_flagged()  # graftcheck: disable=GC001  -- justification

on the finding's line, or ``# graftcheck: disable-file=GC001`` anywhere in
the file (comma-separate multiple ids; ``disable=all`` silences the line).
"""

from __future__ import annotations

import fnmatch
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple


#: Directories (package-relative glob prefixes) that are "hot path" for
#: device-sync rules: per-block work that runs once per genotype block or
#: per shard, where one stray sync serializes the pipeline. ``analyses/*``
#: joined with the population-genetics subsystem: its per-window/per-block
#: device fetches are deliberate (host-sequential prune/chi-square) and
#: carry justified GC001 disables — new ones must justify themselves too.
HOT_PATH_GLOBS = ("ops/*", "pipeline/*", "analyses/*")

#: Ingest-concurrency scope: modules where threads share parse state, so
#: bare lock creation must carry the documented lock-ordering idiom
#: (a ``# lock order:`` comment on or just above the creation line).
#: ``serve/*`` joined when the resident service landed: its admission
#: queue, job table, and HTTP threads share state across the worker.
INGEST_GLOBS = (
    "sources/*",
    "pipeline/datasets.py",
    "utils/native.py",
    "serve/*",
    "analyses/*",
)

#: Telemetry scope: pipeline code whose counters must flow through the
#: metrics registry (``obs/metrics.py``) via the owning object's methods —
#: a bare ``stats.x += n`` bypasses both the lock and the manifest. The
#: service's control plane (``serve/*``) and the analyses layer
#: (``analyses/*``) carry the same obligation.
TELEMETRY_GLOBS = ("ops/*", "pipeline/*", "sources/*", "serve/*", "analyses/*")


@dataclass(frozen=True)
class Rule:
    """One lint rule: identity, scope, and the one-line rationale."""

    id: str
    name: str
    summary: str
    #: Package-relative path globs the rule applies to; empty = everywhere.
    scope: Tuple[str, ...] = ()

    def applies_to(self, relpath: str) -> bool:
        if not self.scope:
            return True
        return any(fnmatch.fnmatch(relpath, g) for g in self.scope)


RULES: Dict[str, Rule] = {
    rule.id: rule
    for rule in [
        Rule(
            "GC000",
            "unparseable-file",
            "The file does not parse as Python; the linter cannot vouch "
            "for it (and neither can the interpreter).",
        ),
        Rule(
            "GC001",
            "host-sync-in-hot-path",
            "Implicit device→host sync (.item()/float()/int()/np.asarray on "
            "a jnp value) inside per-block hot-path code stalls the dispatch "
            "pipeline once per call.",
            scope=HOT_PATH_GLOBS,
        ),
        Rule(
            "GC002",
            "python-branch-on-traced",
            "Python if/while on a traced value inside a jitted function "
            "raises TracerBoolConversionError at runtime (or silently "
            "specializes); use lax.cond/lax.while_loop or mark the argument "
            "static.",
        ),
        Rule(
            "GC003",
            "jit-in-loop",
            "jax.jit constructed inside a loop builds a fresh cache entry "
            "per iteration — a recompilation storm; hoist the jit (or "
            "functools.partial it) out of the loop.",
        ),
        Rule(
            "GC004",
            "jnp-at-import-time",
            "jnp.* executed at module import time initializes the backend "
            "(and can allocate device memory) as a side effect of `import`; "
            "move it into a function or use numpy for module constants.",
        ),
        Rule(
            "GC005",
            "accumulator-update-without-donation",
            "A jitted accumulator update without donate_argnums holds two "
            "live copies of the accumulator per step; donate the buffer or "
            "document why not (e.g. measured pipelining win).",
            scope=("ops/*",),
        ),
        Rule(
            "GC006",
            "undocumented-lock-in-ingest",
            "A bare threading lock in ingest code without the documented "
            "lock-ordering idiom (`# lock order:` comment) — the "
            "GIL-released parse pool makes ordering violations real "
            "deadlocks, not theoretical ones.",
            scope=INGEST_GLOBS,
        ),
        Rule(
            "GC007",
            "sync-inside-loop",
            "block_until_ready inside a loop syncs every iteration, "
            "serializing dispatch against compute; sync once after the "
            "loop, or bound the in-flight window instead.",
            scope=HOT_PATH_GLOBS,
        ),
        Rule(
            "GC008",
            "print-under-jit",
            "print() inside a jitted function runs at trace time only "
            "(once per compilation, with tracers, not values); use "
            "jax.debug.print for runtime values.",
        ),
        Rule(
            "GC009",
            "ad-hoc-stats-mutation",
            "Direct augmented assignment on a stats/counters object "
            "(`io_stats.requests += n`, `self.counters.x += 1`) bypasses "
            "the owner's accounting methods — and with them the lock and "
            "the metrics registry, so the mutation races concurrent "
            "workers and never reaches the run manifest; route it through "
            "an add_*() method.",
            scope=TELEMETRY_GLOBS,
        ),
        Rule(
            "GC011",
            "unjustified-narrowing-cast",
            "A narrowing astype/convert_element_type (int8/uint8/int16/"
            "uint16/int32/uint32/float16/bfloat16/float32 target) in ops/ "
            "without a range-justifying `# range:` comment or contract "
            "reference — the Gramian dtype ladder's exactness rests on "
            "every narrowing cast's operand range being an explicit, "
            "checkable claim (ops/contracts.py), not an unstated "
            "assumption graftcheck ranges cannot see.",
            scope=("ops/*",),
        ),
        Rule(
            "GC012",
            "raw-file-iteration-outside-stream",
            "A read-mode file handle (open/gzip.open/bz2.open/lzma.open) "
            "is iterated or .read*()-consumed directly in ingest/pipeline "
            "code instead of through the one windowed stream abstraction "
            "(sources/stream.py: iter_byte_windows/iter_text_lines/"
            "open_binary) — a raw handle is exactly where O(file) staging "
            "regrows; route the read through sources/stream.py so the "
            "hostmem totality proof keeps covering it.",
            scope=("sources/*", "pipeline/*"),
        ),
        Rule(
            "GC013",
            "journal-record-outside-journal",
            "A journal protocol record (a dict literal with an `event` "
            "key naming accepted/began/terminal/lease) is constructed — "
            "or a journal appender's `_append` is called — outside "
            "serve/journal.py. The record constructors there are the "
            "protocol's ONLY writers: `graftcheck proto` proves the "
            "coordination protocol against exactly those shapes, so a "
            "hand-rolled record elsewhere is a write the proof does not "
            "cover. Route it through journal.accepted_record/"
            "began_record/terminal_record/lease_record (or the JobJournal "
            "methods).",
        ),
        Rule(
            "GC010",
            "host-numpy-under-jit",
            "A host `np.*` call inside a jit/shard_map-decorated kernel "
            "either crashes on tracers (TracerArrayConversionError) or "
            "silently runs once at trace time on the host, baking its "
            "result into the compiled program; use the jnp equivalent "
            "(or hoist the host computation out of the kernel).",
            scope=("ops/*",),
        ),
    ]
}


#: ``graftcheck ir`` rule catalogue (``check/ir.py``): audits of the TRACED
#: jaxpr of the real Gramian kernels — contracts the AST layer cannot see.
#: GI findings anchor to a kernel name, not a source line, so their
#: ``path`` is the kernel's audit name and ``line`` is 0; justification
#: happens through the cross-checked GC005 AST disables (GI002), not
#: per-line escape hatches.
IR_RULES: Dict[str, Rule] = {
    rule.id: rule
    for rule in [
        Rule(
            "GI000",
            "kernel-trace-failure",
            "The kernel fails to trace to a jaxpr at all under the audit "
            "geometry; none of its IR contracts can be vouched for.",
        ),
        Rule(
            "GI001",
            "ring-overlap-broken",
            "A ring step's ppermute and that step's dot_general share a "
            "data dependency, so XLA must serialize the ICI transfer "
            "against the MXU matmul — the communication/compute overlap "
            "the double-buffered ring exists for silently vanishes.",
        ),
        Rule(
            "GI002",
            "accumulator-donation-contract",
            "A jitted accumulator update neither donates the accumulator "
            "buffer nor carries the justified GC005 AST disable (or "
            "carries a disable that no longer matches the traced "
            "donation) — the IR and AST layers have drifted.",
        ),
        Rule(
            "GI003",
            "packed-wire-upcast",
            "A bit-packed uint8 wire tile is widened or consumed by "
            "compute before the designated unpack "
            "(shift-and-mask), so the ring/PCIe wire silently loses its "
            "8-genotypes-per-byte format — 8x the traffic, or wrong math.",
        ),
        Rule(
            "GI004",
            "f64-in-kernel",
            "A float64 value appears inside a device kernel: some input "
            "promoted through a silent weak-type/x64 rule. f64 halves MXU "
            "throughput and doubles HBM; every kernel dtype is an "
            "explicit f32/int32/uint8 contract.",
        ),
        Rule(
            "GI005",
            "ring-traffic-mismatch",
            "The ICI bytes the traced jaxpr actually moves (ppermute "
            "operand bytes x scan trip counts x devices) disagree with "
            "the audited formula parallel/mesh.py:ring_traffic_bytes — "
            "the telemetry/plan numbers no longer describe the kernel.",
        ),
        Rule(
            "GI006",
            "ring-permute-count",
            "A ring pass does not execute exactly samples_axis - 1 "
            "ppermutes; an extra permute (the old return-to-owner step) "
            "wastes one full tile circulation per block, a missing one "
            "drops a device's columns.",
        ),
    ]
}


#: ``graftcheck ranges`` rule catalogue (``check/ranges.py``): an abstract
#: interpreter over the TRACED kernel jaxprs with an interval × exact-in-
#: dtype lattice, seeded from the declared input contracts
#: (``ops/contracts.py``) — the machine proof of the Gramian dtype ladder's
#: exactness chain (bf16×bf16→f32 partials exact < 2^24, int8×int8→int32
#: exact < 2^31, lossless conversion point). GR findings anchor to kernel
#: audit names (line 0), like the GI rules.
RANGES_RULES: Dict[str, Rule] = {
    rule.id: rule
    for rule in [
        Rule(
            "GR000",
            "kernel-range-trace-failure",
            "The kernel fails to trace to a jaxpr under the audit "
            "geometry; none of its range/exactness contracts can be "
            "vouched for.",
        ),
        Rule(
            "GR001",
            "int32-accumulator-overflow",
            "The int32 accumulator can overflow for the declared max "
            "geometry: declared rows x max_count² exceeds int32's 2^31-1 "
            "window, and the ladder has no wider in-accumulator rung — "
            "shrink the geometry contract or split the accumulation.",
        ),
        Rule(
            "GR002",
            "f32-partial-past-exact-window",
            "A per-dispatch f32 partial (a dot_general's output interval, "
            "derived from the declared input contracts) can exceed the "
            "2^24 exact-integer window BEFORE the accumulator conversion "
            "point ever sees it — the bf16/f32 path's exactness claim is "
            "false for this geometry.",
        ),
        Rule(
            "GR003",
            "lossy-narrowing-cast",
            "A convert_element_type whose inferred operand range is wider "
            "than the destination dtype's exact-integer window: integer "
            "values would round or wrap, silently corrupting the count "
            "semantics the dtype ladder promises to preserve.",
        ),
        Rule(
            "GR004",
            "uncontracted-dot-input",
            "A kernel input with no declared range contract "
            "(ops/contracts.py) reaches a dot_general: the prover has no "
            "interval to propagate, so no exactness claim about this "
            "kernel's partials or accumulator can be made at all.",
        ),
        Rule(
            "GR005",
            "conversion-trigger-not-conservative",
            "The runtime conversion trigger's projected per-flush "
            "increment (ops/contracts.py:flush_entry_increment, fed to "
            "_maybe_switch_accumulator) is SMALLER than the per-dispatch "
            "entry increment proven from the traced jaxpr — the f32→int32 "
            "conversion could fire after an entry already left the exact "
            "window.",
        ),
    ]
}


#: ``graftcheck hostmem`` scope: the host-staging layers whose ingest and
#: consume paths must be provably bounded-window (or carry a justified
#: ``hostmem(unbounded)`` declaration) — the host-RAM analog of the
#: HBM/ring-traffic bounds the plan validator already proves. ``serve/*``
#: joined with the resident service: a daemon that buffers request bodies
#: or job backlogs unboundedly would OOM exactly like an O(file) ingest.
#: ``analyses/*`` joined with the population-genetics subsystem: its
#: per-site (M-sized) outputs are exactly the shape an accidental O(M)
#: host list would silently break — the windowed writer discipline is
#: machine-checked from birth.
HOSTMEM_GLOBS = ("sources/*", "pipeline/*", "ops/*", "serve/*", "analyses/*")

#: ``graftcheck hostmem`` rule catalogue (``check/hostmem.py``): an AST
#: dataflow audit classifying every host ingest/consume path as
#: bounded-window or O(file). The audit is a TOTALITY proof: the
#: ``hostmem(unbounded)`` escape hatch that used to DECLARE a site::
#:
#:     raw = f.read()  # graftcheck: hostmem(unbounded) -- why this path is honestly O(file)
#:
#: is itself a finding now (GH006) — the declared inventory hit zero when
#: every source moved onto the windowed stream abstraction
#: (``sources/stream.py``), and the tree must PROVE boundedness, not
#: declare its absence. A hatch still routes its underlying GH00x finding
#: into the report's ``declared_unbounded`` inventory (so the report says
#: WHAT the hatch hides), but the hatch line fails the audit regardless.
HOSTMEM_RULES: Dict[str, Rule] = {
    rule.id: rule
    for rule in [
        Rule(
            "GH001",
            "whole-file-read",
            "A no-size .read()/.readlines() on a file handle stages the "
            "entire file in host RAM at once; read a bounded window in a "
            "loop, or declare the site hostmem(unbounded) with its "
            "justification.",
            scope=HOSTMEM_GLOBS,
        ),
        Rule(
            "GH002",
            "unbounded-stream-accumulation",
            "A list/buffer accumulates file- or stream-derived items "
            "inside the read loop, so peak host memory grows with the "
            "input instead of the window; consume per window, or declare "
            "the site hostmem(unbounded).",
            scope=HOSTMEM_GLOBS,
        ),
        Rule(
            "GH003",
            "stream-materialization",
            "list()/tuple() over a file handle or a streaming block "
            "producer materializes the whole stream the producer exists "
            "to keep windowed; iterate it, or declare the site "
            "hostmem(unbounded).",
            scope=HOSTMEM_GLOBS,
        ),
        Rule(
            "GH004",
            "whole-buffer-decompress",
            "A one-shot decompress (gzip/zlib/bz2/lzma .decompress) holds "
            "compressed AND decompressed copies of the payload at once; "
            "stream through the module's file interface (e.g. gzip.open "
            "windowed reads), or declare the site hostmem(unbounded).",
            scope=HOSTMEM_GLOBS,
        ),
        Rule(
            "GH005",
            "whole-buffer-numpy-staging",
            "np.frombuffer/np.packbits/np.concatenate/np.stack over a "
            "whole-file buffer (or a stream-accumulated list) stages an "
            "O(file) array on host; stage per chunk/block, or declare the "
            "site hostmem(unbounded).",
            scope=HOSTMEM_GLOBS,
        ),
        Rule(
            "GH006",
            "declared-unbounded-forbidden",
            "A `# graftcheck: hostmem(unbounded)` escape hatch — the "
            "declared-inventory era ended when the last O(file) site "
            "moved onto the windowed stream abstraction "
            "(sources/stream.py); the tree proves boundedness now, and a "
            "hatch (justified or not) is a finding, not a declaration. "
            "Refactor the site through "
            "iter_byte_windows/iter_text_lines/SpooledRecordTable/"
            "ChunkedArrayBuilder instead.",
            scope=HOSTMEM_GLOBS,
        ),
    ]
}


#: ``graftcheck sched`` rule catalogue (``check/sched.py``): schedule-level
#: audits of the collective reduction on a DECLARED topology
#: (``parallel/mesh.py:Topology`` — hosts x devices_per_host + per-link
#: bandwidths, proven against before the pod exists). The schedule is
#: extracted from the TRACED kernel jaxprs (every ppermute site with its
#: bytes, trip counts, mesh axis, and overlap flag) and simulated per link
#: class. GS findings anchor to a schedule subject name (line 0), like
#: the GI rules.
SCHED_RULES: Dict[str, Rule] = {
    rule.id: rule
    for rule in [
        Rule(
            "GS001",
            "flat-ring-on-dcn",
            "A flat ring is SELECTED on a multi-host topology: a ppermute "
            "over one flat mesh axis carries no host-boundary structure, "
            "so no hop is provably intra-host and the whole circulation "
            "rides the slow inter-host link — past the hierarchical "
            "schedule's proven DCN bound. Use --reduce-schedule hier (or "
            "auto) when the samples axis spans hosts.",
        ),
        Rule(
            "GS002",
            "schedule-formula-mismatch",
            "The per-level traffic simulated from the traced kernel's "
            "schedule disagrees with the audited closed forms "
            "(parallel/mesh.py:ring_traffic_bytes / "
            "hierarchical_traffic_bytes) — telemetry, the manifest's "
            "schedule block, and the plan validator no longer describe "
            "the schedule the kernel executes.",
        ),
        Rule(
            "GS003",
            "overlap-hole",
            "A link-bound schedule step has no concurrent compute proven "
            "dependency-free of it in the jaxpr: the transfer adds to the "
            "critical path instead of hiding behind the MXU — the "
            "schedule-level generalization of GI001, applied to BOTH "
            "levels of the hierarchical ring.",
        ),
        Rule(
            "GS004",
            "schedule-liveness-past-hbm",
            "The schedule's static per-device peak liveness (buffer-"
            "lifetime walk over the per-device shard_map body) exceeds "
            "the HBM fraction budget — the schedule cannot run at this "
            "geometry regardless of its traffic profile.",
        ),
        Rule(
            "GS005",
            "critical-path-past-budget",
            "The predicted schedule-limited critical path (per-level link "
            "time over the declared topology's bandwidths, overlap-aware) "
            "exceeds the declared --sched-budget-seconds — the plan "
            "cannot be proven to fit its time budget on this topology.",
        ),
    ]
}


#: ``graftcheck lockgraph`` rule catalogue (``check/lockgraph.py``): static
#: lock-acquisition-order analysis of the threaded ingest/telemetry layer.
#: GL findings anchor to real source lines, so the standard
#: ``# graftcheck: disable=GLnnn -- why`` escape hatch applies.
LOCK_RULES: Dict[str, Rule] = {
    rule.id: rule
    for rule in [
        Rule(
            "GL001",
            "lock-order-cycle",
            "The static lock-acquisition graph contains a cycle: two "
            "threads taking the member locks in opposite orders deadlock. "
            "Break the cycle or document a single global order.",
        ),
        Rule(
            "GL002",
            "device-sync-under-lock",
            "A lock is held across block_until_ready: every thread "
            "needing the lock stalls behind a device round-trip (seconds "
            "on remote-attached backends). Sync first, then take the "
            "lock.",
        ),
        Rule(
            "GL003",
            "blocking-queue-op-under-lock",
            "A lock is held across a blocking queue put/get: if the "
            "consumer that would drain the queue needs the same lock, the "
            "backpressure becomes a deadlock. Move the queue op outside "
            "the critical section (or use the _nowait form).",
        ),
        Rule(
            "GL004",
            "self-reacquire",
            "A non-reentrant threading.Lock is (possibly) acquired while "
            "already held on the same call path — an immediate "
            "self-deadlock. Use RLock only if the recursion is "
            "intentional; otherwise split the critical section.",
        ),
    ]
}


#: ``graftcheck proto`` rule catalogue (``check/proto.py``): invariants of
#: the replica coordination protocol, checked by exhaustive explicit-state
#: exploration with the SHIPPED serve/journal.py fold and lease arbitration
#: as the transition oracle. GP findings anchor to a witness trace (a
#: concrete crash/steal/append history), not a source line, so their
#: ``path`` is the protocol model's name and ``line`` is 0. There is no
#: escape hatch for a GP finding: a protocol counterexample is fixed, not
#: justified.
PROTO_RULES: Dict[str, Rule] = {
    rule.id: rule
    for rule in [
        Rule(
            "GP001",
            "double-effective-terminal",
            "One job reaches two terminal records that BOTH survive the "
            "fold's epoch fencing (or two replicas both publish its "
            "result): the journal's truth about the job's outcome is "
            "ambiguous — a deposed replica's late write settled a job "
            "its stealer also settled.",
        ),
        Rule(
            "GP002",
            "device-began-reexecution",
            "A job whose `began` record is journaled executes device "
            "work a second time in a later replica life: the "
            "requeue-once boundary is violated — device state under a "
            "crashed update cannot be trusted for a silent retry.",
        ),
        Rule(
            "GP003",
            "acked-job-lost",
            "A job whose admission was acknowledged (202 sent after the "
            "durable accepted record) becomes invisible: no journal "
            "record folds it as pending, no effective terminal exists, "
            "and no replica holds it in memory — after every crash is "
            "recovered, nobody will ever settle it.",
        ),
        Rule(
            "GP004",
            "lease-epoch-reissued",
            "A journaled lease record re-issues the job's highest "
            "already-journaled lease epoch under a DIFFERENT replica "
            "(the min-epoch claim guard failed): fold fencing cannot "
            "order same-epoch writers, so a zombie terminal would "
            "survive fencing. A lower-than-max straggler append is "
            "benign — the max-fold absorbs it.",
        ),
        Rule(
            "GP005",
            "steal-of-live-owner",
            "A replica successfully link-claims a fencing epoch over a "
            "lease that is still live — or expired but within the grace "
            "window — while its owner is alive: the grace asymmetry "
            "(owners abandon at expiry, stealers wait past expiry+grace) "
            "is violated and owner and stealer can run concurrently.",
        ),
        Rule(
            "GP006",
            "uncovered-crash-transition",
            "The model reaches a crash transition in a protocol window "
            "that no registered utils/faults.py KILL_POINT covers: the "
            "chaos matrix cannot rehearse this crash, so its recovery "
            "story is proven only in the model, never on the real "
            "daemon. Register a kill-point for the window (and enroll "
            "it in the chaos matrix) in the same change.",
        ),
    ]
}


#: Every rule id any graftcheck layer can emit, for Finding.rule lookup.
ALL_RULES: Dict[str, Rule] = {
    **RULES,
    **IR_RULES,
    **RANGES_RULES,
    **SCHED_RULES,
    **LOCK_RULES,
    **HOSTMEM_RULES,
    **PROTO_RULES,
}


@dataclass
class Finding:
    """One lint finding, JSON-serializable for the machine report."""

    rule_id: str
    path: str
    line: int
    col: int
    detail: str

    @property
    def rule(self) -> Rule:
        return ALL_RULES[self.rule_id]

    def format(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule_id} "
            f"[{self.rule.name}] {self.detail}"
        )

    def to_json(self) -> Dict:
        return {
            "rule": self.rule_id,
            "name": self.rule.name,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "detail": self.detail,
        }


def parse_disables(
    source: str,
) -> Tuple[Dict[int, set], set]:
    """Extract the escape hatches from source text.

    Returns ``(per_line, whole_file)``: ``per_line`` maps 1-based line
    numbers to the set of rule ids disabled on that line (``{"all"}``
    disables every rule), ``whole_file`` is the set disabled for the file.
    Comment grammar::

        # graftcheck: disable=GC001,GC006  -- optional justification
        # graftcheck: disable-file=GC004   -- optional justification
    """
    per_line: Dict[int, set] = {}
    whole_file: set = set()
    for lineno, line in enumerate(source.splitlines(), start=1):
        marker = "# graftcheck:"
        at = line.find(marker)
        if at < 0:
            continue
        directive = line[at + len(marker) :].strip()
        for key, sink in (("disable-file=", whole_file), ("disable=", None)):
            if directive.startswith(key):
                ids = directive[len(key) :].split("--")[0]
                parsed = {
                    token.strip()
                    for token in ids.split(",")
                    if token.strip()
                }
                if sink is None:
                    per_line.setdefault(lineno, set()).update(parsed)
                else:
                    sink.update(parsed)
                break
    return per_line, whole_file


def apply_disables(
    findings: Sequence[Finding],
    per_line: Dict[int, set],
    whole_file: set,
) -> List[Finding]:
    """Drop findings silenced by an escape hatch."""

    def silenced(f: Finding) -> bool:
        if "all" in whole_file or f.rule_id in whole_file:
            return True
        ids = per_line.get(f.line, ())
        return "all" in ids or f.rule_id in ids

    return [f for f in findings if not silenced(f)]


__all__ = [
    "Rule",
    "Finding",
    "RULES",
    "IR_RULES",
    "RANGES_RULES",
    "SCHED_RULES",
    "LOCK_RULES",
    "HOSTMEM_RULES",
    "PROTO_RULES",
    "ALL_RULES",
    "HOT_PATH_GLOBS",
    "HOSTMEM_GLOBS",
    "INGEST_GLOBS",
    "TELEMETRY_GLOBS",
    "parse_disables",
    "apply_disables",
]

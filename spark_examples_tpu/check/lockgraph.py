"""Static lock-acquisition-order analysis (``graftcheck lockgraph``).

The chunk-parallel ingest engine runs real concurrency: parse-pool workers
(``sources/files.py``), the prefetch producer (``pipeline/datasets.py``),
the heartbeat daemon (``obs/heartbeat.py``) and the driver thread all share
the parsed-table caches, the metrics registry and the span recorder. The
AST linter's GC006 makes every lock *declare* its ordering contract in a
``# lock order:`` comment; this pass goes further, in the
thread-sanitizer-by-construction style: it builds the static
lock-acquisition graph the code can actually execute and rejects the three
shapes that turn the GIL-released parse pool's concurrency into a hang:

- **GL001** — a cycle in the acquisition-order graph (two threads taking
  the member locks in opposite orders deadlock);
- **GL002** — a lock held across ``block_until_ready`` (every contending
  thread stalls behind a device round-trip);
- **GL003** — a lock held across a blocking queue ``put``/``get`` (if the
  draining thread needs the same lock, backpressure becomes deadlock);
- **GL004** — a possible re-acquisition of a non-reentrant
  ``threading.Lock`` already held on the same call path.

The analysis is deliberately syntactic-plus-one-call-graph: per function it
records ``with <lock>:`` nesting and the calls made while holding, then
propagates acquired-lock/blocking-op summaries through the intra-package
call graph to a fixpoint. Attribute calls on untyped receivers resolve
only when the method name is unique (and not a generic stdlib name) across
the analyzed tree — a documented over/under-approximation: property
accesses that take locks (``Gauge.value``) and locks inside the stdlib
(``queue.Queue``'s internal mutex) are invisible, while branch-insensitive
merging may hold locks slightly longer than runtime does. Escape hatch:
``# graftcheck: disable=GLnnn -- why`` on the reported line.

The graph itself is emitted as a DOT artifact (``--dot``), one node per
lock (``relpath::Class.attr``), one edge per observed acquisition order —
CI archives it next to the run manifests so the ordering contract is a
reviewable artifact, not tribal knowledge.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from spark_examples_tpu.check.linter import (
    _LOCK_CTORS,
    _collect_aliases,
    _dotted,
    _iter_py_files,
)
from spark_examples_tpu.check.rules import Finding, apply_disables, parse_disables

#: Attribute-call names never resolved through the unique-method heuristic:
#: too generic — they name stdlib/container methods far more often than a
#: package method, and a wrong edge is worse than a missing one.
_GENERIC_METHOD_NAMES = {
    "get",
    "put",
    "items",
    "keys",
    "values",
    "append",
    "extend",
    "pop",
    "add",
    "close",
    "read",
    "write",
    "join",
    "start",
    "run",
    "result",
    "submit",
    "acquire",
    "release",
    "update",
    "copy",
    "clear",
    "format",
    "split",
    "strip",
    "encode",
    "decode",
    "flush",
    "send",
    "recv",
    "next",
    "sort",
    "index",
    "count",
    "setdefault",
}

#: Max call-graph propagation rounds (the package call graph is shallow;
#: this bounds pathological recursion, not expected depth).
_FIXPOINT_ROUNDS = 30


# --------------------------------------------------------------------------
# Event model.
# --------------------------------------------------------------------------


@dataclass
class _Acquire:
    ref: Tuple  # unresolved lock reference
    line: int
    inner: List[object]


@dataclass
class _Call:
    ref: Tuple  # unresolved callee reference
    line: int
    label: str


@dataclass
class _Blocking:
    kind: str  # "sync" | "queue"
    line: int
    detail: str


@dataclass(frozen=True)
class LockNode:
    key: str
    relpath: str
    line: int
    ctor: str
    reentrant: bool


@dataclass(frozen=True)
class LockEdge:
    src: str
    dst: str
    relpath: str
    line: int
    via: str


@dataclass
class _ClassInfo:
    name: str
    bases: List[str]
    lock_attrs: Dict[str, str]  # attr -> lock key


@dataclass
class _FunctionInfo:
    fkey: Tuple[str, str]  # (relpath, qualname)
    events: List[object]
    cls: Optional[str]


@dataclass
class _Module:
    relpath: str
    classes: Dict[str, _ClassInfo]
    functions: Dict[str, _FunctionInfo]
    source: str


# --------------------------------------------------------------------------
# Per-module extraction.
# --------------------------------------------------------------------------


class _ModuleScanner:
    def __init__(self, relpath: str, tree: ast.Module, source: str):
        self.relpath = relpath
        self.alias = _collect_aliases(tree)
        self.classes: Dict[str, _ClassInfo] = {}
        self.functions: Dict[str, _FunctionInfo] = {}
        self.module_locks: Dict[str, str] = {}  # module-level name -> key
        self.lock_nodes: List[LockNode] = []
        self._scan_module(tree)
        self.module = _Module(relpath, self.classes, self.functions, source)

    # ------------------------------------------------------------ discovery

    def _lock_ctor(self, node: ast.expr) -> Optional[str]:
        if isinstance(node, ast.Call):
            name = _dotted(node.func, self.alias)
            if name in _LOCK_CTORS:
                return name
        return None

    def _register_lock(
        self, key: str, line: int, ctor: str
    ) -> None:
        self.lock_nodes.append(
            LockNode(key, self.relpath, line, ctor, ctor == "threading.RLock")
        )

    @staticmethod
    def _assign_targets(node: ast.stmt) -> Tuple[Optional[ast.expr], List[ast.expr]]:
        """``(value, targets)`` of a plain or annotated assignment —
        ``x: Lock = threading.Lock()`` must register exactly like the
        unannotated form (the strict-typing promotion makes annotations
        the norm, and an invisible lock disables every GL rule for it)."""
        if isinstance(node, ast.Assign):
            return node.value, list(node.targets)
        if isinstance(node, ast.AnnAssign) and node.value is not None:
            return node.value, [node.target]
        return None, []

    def _scan_module(self, tree: ast.Module) -> None:
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                self._scan_class(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_function(node, cls=None, prefix="")
            else:
                value, targets = self._assign_targets(node)
                ctor = self._lock_ctor(value) if value is not None else None
                if ctor:
                    for target in targets:
                        if isinstance(target, ast.Name):
                            key = f"{self.relpath}::{target.id}"
                            self.module_locks[target.id] = key
                            self._register_lock(key, node.lineno, ctor)

    def _scan_class(self, node: ast.ClassDef) -> None:
        bases = [
            b.id for b in node.bases if isinstance(b, ast.Name)
        ]
        info = _ClassInfo(node.name, bases, {})
        self.classes[node.name] = info
        for item in node.body:
            # Class-body lock attributes (shared class-level locks),
            # plain or annotated.
            value, targets = self._assign_targets(item)
            ctor = self._lock_ctor(value) if value is not None else None
            if ctor:
                for target in targets:
                    if isinstance(target, ast.Name):
                        key = f"{self.relpath}::{node.name}.{target.id}"
                        info.lock_attrs[target.id] = key
                        self._register_lock(key, item.lineno, ctor)
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # Lock attribute definitions: self.X = threading.Lock(),
                # plain or annotated.
                for sub in ast.walk(item):
                    value, targets = self._assign_targets(sub)
                    ctor = self._lock_ctor(value) if value is not None else None
                    if not ctor:
                        continue
                    for target in targets:
                        if (
                            isinstance(target, ast.Attribute)
                            and isinstance(target.value, ast.Name)
                            and target.value.id == "self"
                        ):
                            key = (
                                f"{self.relpath}::{node.name}."
                                f"{target.attr}"
                            )
                            info.lock_attrs[target.attr] = key
                            self._register_lock(key, sub.lineno, ctor)
                self._scan_function(item, cls=node.name, prefix=f"{node.name}.")

    def _scan_function(
        self,
        node,
        cls: Optional[str],
        prefix: str,
    ) -> None:
        qualname = prefix + node.name
        events = self._events_of_body(node.body, cls)
        self.functions[qualname] = _FunctionInfo(
            (self.relpath, qualname), events, cls
        )
        # Nested defs become separately-callable entries (closures the
        # enclosing function hands to pools/threads).
        for item in ast.walk(node):
            if (
                isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
                and item is not node
            ):
                inner_q = f"{qualname}.{item.name}"
                if inner_q not in self.functions:
                    self.functions[inner_q] = _FunctionInfo(
                        (self.relpath, inner_q),
                        self._events_of_body(item.body, cls),
                        cls,
                    )

    # --------------------------------------------------------------- events

    def _resolve_lock_ref(
        self, node: ast.expr, cls: Optional[str]
    ) -> Optional[Tuple]:
        """A lock *reference* at a use site, resolved later against the
        global table: ``self.X`` -> ("self", relpath, cls, X); bare module
        name -> ("module", relpath, name); anything else dotted ->
        ("attr", last_segment)."""
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and cls is not None
        ):
            return ("self", self.relpath, cls, node.attr)
        if isinstance(node, ast.Name):
            if node.id in self.module_locks:
                return ("module", self.relpath, node.id)
            return None
        if isinstance(node, ast.Attribute):
            return ("attr", node.attr)
        return None

    def _looks_like_lock(self, node: ast.expr, cls: Optional[str]) -> bool:
        """Whether a with-item plausibly names a lock: self.X where X is a
        known lock attr of this module, a module-level lock name, or any
        name/attr whose last segment contains 'lock'/'mutex'."""
        ref = self._resolve_lock_ref(node, cls)
        if ref is None:
            return False
        if ref[0] == "module":
            return True
        if ref[0] == "self":
            attr = ref[3]
            for info in self.classes.values():
                if attr in info.lock_attrs:
                    return True
            return "lock" in attr.lower() or "mutex" in attr.lower()
        return "lock" in ref[1].lower() or "mutex" in ref[1].lower()

    def _call_event(
        self, node: ast.Call, cls: Optional[str]
    ) -> Optional[object]:
        func = node.func
        # Blocking ops first — they are findings, not call-graph edges.
        if isinstance(func, ast.Attribute):
            if func.attr == "block_until_ready":
                return _Blocking("sync", node.lineno, ".block_until_ready()")
            if func.attr in ("put", "get"):
                receiver = _dotted(func.value, self.alias) or ""
                last = receiver.rsplit(".", 1)[-1].lower()
                if "queue" in receiver.lower() or last in ("q", "jobs"):
                    nonblocking = any(
                        kw.arg == "block"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value is False
                        for kw in node.keywords
                    )
                    if not nonblocking:
                        return _Blocking(
                            "queue",
                            node.lineno,
                            f"{receiver}.{func.attr}()",
                        )
                return None
        name = _dotted(func, self.alias)
        if name == "jax.block_until_ready":
            return _Blocking("sync", node.lineno, "jax.block_until_ready()")
        if isinstance(func, ast.Attribute) and isinstance(
            func.value, ast.Name
        ):
            if func.value.id == "self" and cls is not None:
                return _Call(
                    ("self_method", self.relpath, cls, func.attr),
                    node.lineno,
                    f"self.{func.attr}",
                )
        if name is not None:
            head = name.split(".")[0]
            if "." not in name:
                return _Call(
                    ("local", self.relpath, name), node.lineno, name
                )
            if head not in ("self",):
                return _Call(("dotted", name), node.lineno, name)
        if isinstance(func, ast.Attribute):
            if func.attr not in _GENERIC_METHOD_NAMES:
                return _Call(
                    ("method", func.attr), node.lineno, f".{func.attr}"
                )
        return None

    def _expr_events(self, node: ast.AST, cls: Optional[str]) -> List[object]:
        events: List[object] = []
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                # `lock.acquire()` as an expression: modeled by the caller
                # (statement walker); other calls become events here.
                ev = self._call_event(sub, cls)
                if ev is not None:
                    events.append(ev)
        return events

    def _acquire_call(
        self, stmt: ast.stmt, cls: Optional[str]
    ) -> Optional[Tuple[Tuple, int]]:
        """`X.acquire()` statement -> (lock ref, line)."""
        node = stmt.value if isinstance(stmt, ast.Expr) else None
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "acquire"
        ):
            ref = self._resolve_lock_ref(node.func.value, cls)
            if ref is not None and self._looks_like_lock(node.func.value, cls):
                return ref, node.lineno
        return None

    def _events_of_body(
        self, stmts: Sequence[ast.stmt], cls: Optional[str]
    ) -> List[object]:
        events: List[object] = []
        for i, stmt in enumerate(stmts):
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                held_here: List[Tuple[Tuple, int]] = []
                for item in stmt.items:
                    ctx = item.context_expr
                    if self._looks_like_lock(ctx, cls):
                        ref = self._resolve_lock_ref(ctx, cls)
                        if ref is not None:
                            held_here.append((ref, ctx.lineno))
                            continue
                    events.extend(self._expr_events(ctx, cls))
                body = self._events_of_body(stmt.body, cls)
                for ref, line in reversed(held_here):
                    body = [_Acquire(ref, line, body)]
                events.extend(body)
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                events.extend(self._expr_events(getattr(stmt, "iter", stmt), cls))
                events.extend(self._events_of_body(stmt.body, cls))
                events.extend(self._events_of_body(stmt.orelse, cls))
            elif isinstance(stmt, ast.If):
                events.extend(self._expr_events(stmt.test, cls))
                events.extend(self._events_of_body(stmt.body, cls))
                events.extend(self._events_of_body(stmt.orelse, cls))
            elif isinstance(stmt, ast.Try):
                events.extend(self._events_of_body(stmt.body, cls))
                for handler in stmt.handlers:
                    events.extend(self._events_of_body(handler.body, cls))
                events.extend(self._events_of_body(stmt.orelse, cls))
                events.extend(self._events_of_body(stmt.finalbody, cls))
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # separate entry via _scan_function
            else:
                acq = self._acquire_call(stmt, cls)
                if acq is not None:
                    # `.acquire()` without `with`: conservatively held for
                    # the remainder of this suite (release() is ignored).
                    rest = self._events_of_body(stmts[i + 1 :], cls)
                    events.append(_Acquire(acq[0], acq[1], rest))
                    break
                events.extend(self._expr_events(stmt, cls))
        return events


# --------------------------------------------------------------------------
# Global resolution + fixpoint.
# --------------------------------------------------------------------------


class LockGraph:
    """The resolved graph plus every GL finding."""

    def __init__(self) -> None:
        self.nodes: Dict[str, LockNode] = {}
        self.edges: Dict[Tuple[str, str], LockEdge] = {}
        self.findings: List[Finding] = []

    @property
    def ok(self) -> bool:
        return not self.findings

    def cycles(self) -> List[List[str]]:
        """Cycles in the acquisition-order graph (each reported once)."""
        adjacency: Dict[str, List[str]] = {}
        for (src, dst) in self.edges:
            adjacency.setdefault(src, []).append(dst)
        seen_cycles: List[List[str]] = []
        state: Dict[str, int] = {}  # 0 unvisited, 1 on stack, 2 done
        stack: List[str] = []

        def dfs(node: str) -> None:
            state[node] = 1
            stack.append(node)
            for nxt in adjacency.get(node, ()):
                if state.get(nxt, 0) == 0:
                    dfs(nxt)
                elif state.get(nxt) == 1:
                    cycle = stack[stack.index(nxt) :] + [nxt]
                    normalized = sorted(set(cycle))
                    if normalized not in [
                        sorted(set(c)) for c in seen_cycles
                    ]:
                        seen_cycles.append(cycle)
            stack.pop()
            state[node] = 2

        for node in list(adjacency):
            if state.get(node, 0) == 0:
                dfs(node)
        return seen_cycles

    def to_dot(self) -> str:
        lines = [
            "digraph lock_order {",
            "  rankdir=LR;",
            '  node [shape=box, fontname="monospace", fontsize=10];',
        ]
        for key in sorted(self.nodes):
            node = self.nodes[key]
            shape = "box" if not node.reentrant else "ellipse"
            lines.append(
                f'  "{key}" [shape={shape}, label="{key}\\n'
                f'{node.ctor} @ {node.relpath}:{node.line}"];'
            )
        for (src, dst), edge in sorted(self.edges.items()):
            lines.append(
                f'  "{src}" -> "{dst}" '
                f'[label="{edge.relpath}:{edge.line}{edge.via}"];'
            )
        lines.append("}")
        return "\n".join(lines) + "\n"

    def to_json(self) -> str:
        return json.dumps(
            {
                "tool": "graftcheck-lockgraph",
                "ok": self.ok,
                "locks": [
                    {
                        "key": n.key,
                        "path": n.relpath,
                        "line": n.line,
                        "ctor": n.ctor,
                        "reentrant": n.reentrant,
                    }
                    for n in sorted(self.nodes.values(), key=lambda n: n.key)
                ],
                "edges": [
                    {
                        "src": e.src,
                        "dst": e.dst,
                        "path": e.relpath,
                        "line": e.line,
                        "via": e.via,
                    }
                    for e in sorted(
                        self.edges.values(), key=lambda e: (e.src, e.dst)
                    )
                ],
                "finding_count": len(self.findings),
                "findings": [f.to_json() for f in self.findings],
            },
            indent=2,
        )

    def format(self) -> str:
        lines = [
            f"  locks: {len(self.nodes)}, acquisition-order edges: "
            f"{len(self.edges)}"
        ]
        for (src, dst), edge in sorted(self.edges.items()):
            lines.append(f"  order: {src} -> {dst}  ({edge.relpath}:{edge.line})")
        for f in self.findings:
            lines.append(f"  {f.format()}")
        verdict = (
            "acyclic, clean"
            if self.ok
            else f"{len(self.findings)} finding(s)"
        )
        lines.append(f"graftcheck lockgraph: {verdict}")
        return "\n".join(lines)


def _resolve_lock(
    ref: Tuple,
    modules: Dict[str, _ModuleScanner],
    all_locks: Dict[str, LockNode],
) -> Optional[str]:
    kind = ref[0]
    if kind == "module":
        _, relpath, name = ref
        return modules[relpath].module_locks.get(name)
    if kind == "self":
        _, relpath, cls, attr = ref
        scanner = modules[relpath]
        seen: Set[str] = set()
        frontier = [cls]
        while frontier:
            cname = frontier.pop()
            if cname in seen:
                continue
            seen.add(cname)
            info = scanner.classes.get(cname)
            if info is None:
                continue
            if attr in info.lock_attrs:
                return info.lock_attrs[attr]
            frontier.extend(info.bases)
        # Fall through: unique attr-name match across the tree.
        kind, attr = "attr", attr
    if kind == "attr":
        attr = ref[-1]
        # Strip the module prefix BEFORE taking the attribute tail, or the
        # '.py' in 'mod.py::global_lock' eats the split and module-level
        # locks never match.
        candidates = [
            k
            for k in all_locks
            if k.split("::", 1)[-1].rsplit(".", 1)[-1] == attr
        ]
        if len(candidates) == 1:
            return candidates[0]
    return None


def _method_index(
    modules: Dict[str, _ModuleScanner],
) -> Dict[str, List[Tuple[str, str]]]:
    index: Dict[str, List[Tuple[str, str]]] = {}
    for relpath, scanner in modules.items():
        for qualname in scanner.functions:
            short = qualname.rsplit(".", 1)[-1]
            index.setdefault(short, []).append((relpath, qualname))
    return index


def _module_relpath_for(dotted: str, modules: Dict[str, _ModuleScanner]) -> Optional[Tuple[str, str]]:
    """``spark_examples_tpu.obs.metrics.foo`` -> (relpath, "foo") when that
    module is in the analyzed set."""
    parts = dotted.split(".")
    if parts[0] != "spark_examples_tpu" or len(parts) < 3:
        return None
    mod_rel = "/".join(parts[1:-1]) + ".py"
    if mod_rel in modules:
        return mod_rel, parts[-1]
    return None


def _resolve_call(
    ref: Tuple,
    modules: Dict[str, _ModuleScanner],
    method_index: Dict[str, List[Tuple[str, str]]],
    caller: Optional[Tuple[str, str]] = None,
) -> Optional[Tuple[str, str]]:
    kind = ref[0]
    if kind == "self_method":
        _, relpath, cls, name = ref
        scanner = modules[relpath]
        seen: Set[str] = set()
        frontier = [cls]
        while frontier:
            cname = frontier.pop()
            if cname in seen:
                continue
            seen.add(cname)
            info = scanner.classes.get(cname)
            if info is None:
                continue
            qual = f"{cname}.{name}"
            if qual in scanner.functions:
                return (relpath, qual)
            frontier.extend(info.bases)
        return None
    if kind == "local":
        _, relpath, name = ref
        scanner = modules[relpath]
        # A bare call from inside a function first binds to a nested def
        # (closures the enclosing function hands to pools/threads) at any
        # enclosing level, then the module scope — mirror that.
        if caller is not None and caller[0] == relpath:
            parts = caller[1].split(".")
            for depth in range(len(parts), 0, -1):
                nested = ".".join(parts[:depth] + [name])
                if nested in scanner.functions:
                    return (relpath, nested)
        if name in scanner.functions:
            return (relpath, name)
        if name in scanner.classes:
            init = f"{name}.__init__"
            if init in scanner.functions:
                return (relpath, init)
        return None
    if kind == "dotted":
        dotted = ref[1]
        resolved = _module_relpath_for(dotted, modules)
        if resolved is not None:
            relpath, name = resolved
            scanner = modules[relpath]
            if name in scanner.functions:
                return (relpath, name)
            if name in scanner.classes:
                init = f"{name}.__init__"
                if init in scanner.functions:
                    return (relpath, init)
        # A class imported by name: `_Family(...)` resolves as local above;
        # `metrics._Family(...)` lands here with the class's dotted name.
        return None
    if kind == "method":
        name = ref[1]
        if name in _GENERIC_METHOD_NAMES:
            return None
        hits = method_index.get(name, [])
        # Unique across the tree, counting the bare and Class.name forms as
        # distinct candidates only when they live in different classes.
        if len(hits) == 1:
            return hits[0]
        return None
    return None


def build_lock_graph(paths: Sequence[str]) -> LockGraph:
    """Analyze ``paths`` (files or package trees) into a :class:`LockGraph`."""
    graph = LockGraph()
    modules: Dict[str, _ModuleScanner] = {}
    raw_findings: Dict[str, List[Finding]] = {}

    for root in paths:
        for full, relpath in _iter_py_files(root):
            with open(full, "r", encoding="utf-8") as f:
                source = f.read()
            try:
                tree = ast.parse(source, filename=relpath)
            except SyntaxError:
                continue  # GC000 is the linter's finding, not ours
            modules[relpath] = _ModuleScanner(relpath, tree, source)

    all_locks: Dict[str, LockNode] = {}
    for scanner in modules.values():
        for node in scanner.lock_nodes:
            all_locks[node.key] = node
    graph.nodes = all_locks
    method_index = _method_index(modules)

    # ------------------------------------------------- per-function summary
    acquires: Dict[Tuple[str, str], Set[str]] = {}
    blocking: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
    fn_table: Dict[Tuple[str, str], _FunctionInfo] = {}
    for relpath, scanner in modules.items():
        for qualname, info in scanner.functions.items():
            fn_table[(relpath, qualname)] = info
            acquires[(relpath, qualname)] = set()
            blocking[(relpath, qualname)] = set()

    def direct_pass(info: _FunctionInfo) -> Tuple[Set[str], Set[Tuple[str, str]], Set[Tuple[str, str]]]:
        acq: Set[str] = set()
        blk: Set[Tuple[str, str]] = set()
        calls: Set[Tuple[str, str]] = set()

        def walk(events: List[object]) -> None:
            for ev in events:
                if isinstance(ev, _Acquire):
                    key = _resolve_lock(ev.ref, modules, all_locks)
                    if key is not None:
                        acq.add(key)
                    walk(ev.inner)
                elif isinstance(ev, _Call):
                    fk = _resolve_call(
                        ev.ref, modules, method_index, caller=info.fkey
                    )
                    if fk is not None:
                        calls.add(fk)
                elif isinstance(ev, _Blocking):
                    blk.add((ev.kind, ev.detail))

        walk(info.events)
        return acq, blk, calls

    call_edges: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
    for fkey, info in fn_table.items():
        acq, blk, calls = direct_pass(info)
        acquires[fkey] = acq
        blocking[fkey] = blk
        call_edges[fkey] = calls

    for _ in range(_FIXPOINT_ROUNDS):
        changed = False
        for fkey, calls in call_edges.items():
            for callee in calls:
                if not acquires[callee] <= acquires[fkey]:
                    acquires[fkey] |= acquires[callee]
                    changed = True
                if not blocking[callee] <= blocking[fkey]:
                    blocking[fkey] |= blocking[callee]
                    changed = True
        if not changed:
            break

    # ------------------------------------------------- held-set final pass
    def emit(rule_id: str, relpath: str, line: int, detail: str) -> None:
        raw_findings.setdefault(relpath, []).append(
            Finding(rule_id, relpath, line, 1, detail)
        )

    def add_edge(src: str, dst: str, relpath: str, line: int, via: str) -> None:
        if src == dst:
            return
        self_key = (src, dst)
        if self_key not in graph.edges:
            graph.edges[self_key] = LockEdge(src, dst, relpath, line, via)

    def final_walk(
        info: _FunctionInfo, events: List[object], held: Tuple[str, ...]
    ) -> None:
        relpath = info.fkey[0]
        for ev in events:
            if isinstance(ev, _Acquire):
                key = _resolve_lock(ev.ref, modules, all_locks)
                if key is None:
                    final_walk(info, ev.inner, held)
                    continue
                for h in held:
                    add_edge(h, key, relpath, ev.line, "")
                if key in held and not all_locks[key].reentrant:
                    emit(
                        "GL004",
                        relpath,
                        ev.line,
                        f"non-reentrant {key} re-acquired while already "
                        "held on this call path — self-deadlock",
                    )
                final_walk(info, ev.inner, held + (key,))
            elif isinstance(ev, _Call):
                if not held:
                    continue
                fk = _resolve_call(
                    ev.ref, modules, method_index, caller=info.fkey
                )
                if fk is None:
                    continue
                for lock_key in acquires.get(fk, ()):
                    for h in held:
                        add_edge(
                            h, lock_key, relpath, ev.line, f" via {ev.label}"
                        )
                    if lock_key in held and not all_locks[lock_key].reentrant:
                        emit(
                            "GL004",
                            relpath,
                            ev.line,
                            f"call to {ev.label} may re-acquire "
                            f"non-reentrant {lock_key} already held here",
                        )
                for kind, detail in blocking.get(fk, ()):
                    rule = "GL002" if kind == "sync" else "GL003"
                    emit(
                        rule,
                        relpath,
                        ev.line,
                        f"lock(s) {', '.join(held)} held across {detail} "
                        f"(via {ev.label})",
                    )
            elif isinstance(ev, _Blocking):
                if not held:
                    continue
                rule = "GL002" if ev.kind == "sync" else "GL003"
                what = (
                    "a device sync"
                    if ev.kind == "sync"
                    else "a blocking queue op"
                )
                emit(
                    rule,
                    relpath,
                    ev.line,
                    f"lock(s) {', '.join(held)} held across {what}: "
                    f"{ev.detail}",
                )

    for fkey, info in fn_table.items():
        final_walk(info, info.events, ())

    # ---------------------------------------------------------- GL001 cycles
    for cycle in graph.cycles():
        first_edge = graph.edges.get((cycle[0], cycle[1]))
        relpath = first_edge.relpath if first_edge else cycle[0].split("::")[0]
        line = first_edge.line if first_edge else 0
        emit(
            "GL001",
            relpath,
            line,
            "lock-acquisition-order cycle: " + " -> ".join(cycle),
        )

    # -------------------------------------------------------- escape hatches
    for relpath, found in raw_findings.items():
        scanner = modules.get(relpath)
        if scanner is None:
            graph.findings.extend(found)
            continue
        per_line, whole_file = parse_disables(scanner.module.source)
        graph.findings.extend(apply_disables(found, per_line, whole_file))
    graph.findings.sort(key=lambda f: (f.path, f.line, f.rule_id))
    return graph


def default_lock_paths() -> List[str]:
    """The package tree (locks only exist in the ingest/obs layers, but
    scanning everything keeps new locks covered by default)."""
    import spark_examples_tpu

    return [os.path.dirname(os.path.abspath(spark_examples_tpu.__file__))]


__all__ = [
    "LockEdge",
    "LockGraph",
    "LockNode",
    "build_lock_graph",
    "default_lock_paths",
]

"""Static host-memory bound auditing (``graftcheck hostmem``).

ROADMAP item 1's asterisk: bounded-memory streaming is a single-path
feature, not a proven global invariant. This module is the proof half —
an AST dataflow pass over the host-staging layers (``sources/``,
``pipeline/``, ``ops/``) that classifies every ingest/consume path as
**bounded-window** or **O(file)**, the way ``graftcheck ir`` proves the
ring-traffic formula against the traced kernels:

- a per-function *taint* analysis marks values derived from file handles
  (``open``/``gzip.open``/``_open_text`` results), whole-file reads, and
  streaming block producers;
- five rules (GH001-GH005, ``check/rules.py``) flag the O(file) staging
  shapes: whole-file ``.read()``, unbounded accumulation of stream items
  inside the read loop, ``list()`` materialization of a block stream,
  one-shot ``*.decompress``, and whole-buffer numpy staging.

The audit is a **totality proof** (DESIGN.md §8.6): since every source
moved onto the windowed stream abstraction (``sources/stream.py``), the
``hostmem(unbounded)`` escape hatch that used to *declare* an O(file)
site::

    raw = f.read()  # graftcheck: hostmem(unbounded) -- packed whole-file parse needs the contiguous buffer

is itself a finding (GH006, *declared-unbounded-forbidden*) — the tree
must prove boundedness, not declare its absence. A justified hatch still
routes its underlying GH00x finding into the report's
``declared_unbounded`` inventory (so the report says what the hatch
hides), but the hatch line fails the audit regardless; the shipped tree
carries none.

The formula half lives in ``parallel/mesh.py:host_peak_bytes`` (the
sibling of ``ring_traffic_bytes``); :func:`conf_host_peak_bytes` resolves
one parsed configuration into that closed form — TOTAL over the conf
surface: a finite bound for every (source kind x ingest mode x analysis
x serve job kind), never ``None`` — shared by ``graftcheck plan
--host-mem-budget`` and the driver's ``host_static_bound_bytes`` gauge,
so the budget the validator enforces and the bound the manifest records
can never drift. The loop closes at runtime: the budgeted accumulators
(``sources/stream.py``) enforce the same row bounds the formula charges
(``StreamBudgetError`` past capacity), the manifest's ``host_memory``
block carries measured peak RSS next to this bound, and CI asserts
measured <= static on every build.

Exit contract (``check/cli.py``): 0 = clean, 1 = findings (an escape
hatch now counts as one).
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from spark_examples_tpu.check.linter import (
    _collect_aliases,
    _dotted,
    _iter_py_files,
)
from spark_examples_tpu.check.rules import HOSTMEM_RULES, Finding

#: Callables whose result is a file handle (taint root; `with ... as f`
#: or assignment binds the handle name).
_FILE_OPENERS = frozenset(
    {
        "open",
        "io.open",
        "gzip.open",
        "bz2.open",
        "lzma.open",
        "_open_text",
        "spark_examples_tpu.sources.files._open_text",
    }
)

#: One-shot whole-buffer decompressors (GH004).
_DECOMPRESSORS = frozenset(
    {
        "gzip.decompress",
        "zlib.decompress",
        "bz2.decompress",
        "lzma.decompress",
    }
)

#: Streaming block producers: iterating one is the bounded-window idiom;
#: accumulating its items (GH002) or materializing it whole (GH003) is
#: exactly the O(file) regression the audit exists to catch. Matched on
#: the final attribute/name segment so `self.iter_chunk_arrays()` and
#: `source.stream_genotype_blocks(...)` both resolve.
_STREAM_PRODUCERS = frozenset(
    {
        "_iter_vcf_chunks",
        "iter_chunk_arrays",
        "stream_blocks",
        "stream_genotype_blocks",
        "genotype_blocks",
        "iter_shards",
        "iter_part",
        # sources/stream.py — the one windowed abstraction (its consumers
        # are everywhere; accumulating its items is exactly the O(file)
        # regression this audit exists to catch).
        "iter_byte_windows",
        "iter_text_lines",
        "windowed",
        "iter_records",
        "merge_join",
        "_iter_jsonl_lines",
    }
)

#: numpy staging calls GH005 audits when fed a whole-file buffer.
_NP_STAGING = frozenset(
    {
        "numpy.frombuffer",
        "numpy.packbits",
        "numpy.concatenate",
        "numpy.stack",
        "numpy.vstack",
        "numpy.hstack",
    }
)

#: Scalar extractors whose result does not carry the input's memory
#: footprint — they break taint propagation (``n += len(chunk)`` is
#: accounting, not accumulation).
_SCALAR_EXTRACTORS = frozenset(
    {"len", "int", "float", "bool", "min", "max", "sum", "ord", "hash"}
)

_HATCH_RE = re.compile(
    r"#\s*graftcheck:\s*hostmem\(unbounded\)\s*(?:--\s*(?P<why>.*\S))?\s*$"
)


def iter_hatch_comments(source: str) -> List[Tuple[int, int]]:
    """``(line, col)`` of every ``hostmem(unbounded)`` hatch comment,
    justified or not — GH006's subjects: the hatch SYNTAX is forbidden
    now that the declared inventory hit zero."""
    out: List[Tuple[int, int]] = []
    for lineno, line in enumerate(source.splitlines(), start=1):
        m = _HATCH_RE.search(line)
        if m is not None:
            out.append((lineno, m.start() + 1))
    return out


def parse_hostmem_hatches(source: str) -> Dict[int, str]:
    """``{line: justification}`` for every JUSTIFIED hostmem(unbounded)
    hatch; a hatch with no ``-- why`` text is ignored (declaring a site
    without saying why it is allowed to be O(file) declares nothing).

    A trailing hatch declares its own line; a comment-ONLY hatch line
    declares the next line (justifications routinely outgrow the code
    line — the same layout the ``# lock order:`` idiom uses).

    Note the hatch no longer PASSES anything: it routes the underlying
    GH00x finding into the report's ``declared_unbounded`` inventory for
    context, while GH006 flags the hatch line itself (see
    :func:`audit_source`)."""
    hatches: Dict[int, str] = {}
    for lineno, line in enumerate(source.splitlines(), start=1):
        m = _HATCH_RE.search(line)
        if m is None or not m.group("why"):
            continue
        why = m.group("why").strip()
        if line[: m.start()].strip() == "":
            hatches[lineno + 1] = why
        else:
            hatches[lineno] = why
    return hatches


@dataclass
class DeclaredSite:
    """One justified ``hostmem(unbounded)`` site: an O(file) path the tree
    acknowledges, inventoried for the streaming refactor."""

    rule_id: str
    path: str
    line: int
    detail: str
    justification: str

    def to_json(self) -> Dict[str, Any]:
        return {
            "rule": self.rule_id,
            "path": self.path,
            "line": self.line,
            "detail": self.detail,
            "justification": self.justification,
        }


@dataclass
class HostmemReport:
    """Audit result: undeclared findings fail; declared sites are listed."""

    findings: List[Finding] = field(default_factory=list)
    declared: List[DeclaredSite] = field(default_factory=list)
    checked_files: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_json(self) -> str:
        return json.dumps(
            {
                "tool": "graftcheck-hostmem",
                "ok": self.ok,
                "checked_files": self.checked_files,
                "finding_count": len(self.findings),
                "findings": [f.to_json() for f in self.findings],
                "declared_unbounded": [d.to_json() for d in self.declared],
            },
            indent=2,
        )

    def format(self) -> str:
        lines = [f.format() for f in self.findings]
        if self.declared:
            lines.append(
                f"declared hostmem(unbounded) sites "
                f"({len(self.declared)} — the streaming-refactor worklist):"
            )
            for d in self.declared:
                lines.append(
                    f"  {d.path}:{d.line}: {d.rule_id} -- {d.justification}"
                )
        verdict = (
            "clean" if self.ok else f"{len(self.findings)} undeclared finding(s)"
        )
        lines.append(
            f"graftcheck hostmem: {self.checked_files} file(s), {verdict}"
        )
        return "\n".join(lines)


def _call_name(node: ast.expr, alias: Dict[str, str]) -> Optional[str]:
    """Canonical dotted name of a call's callee, else None."""
    if isinstance(node, ast.Call):
        return _dotted(node.func, alias)
    return None


def _last_segment(name: Optional[str]) -> Optional[str]:
    return name.rsplit(".", 1)[-1] if name else None


def _attr_tail(node: ast.expr, alias: Dict[str, str]) -> Optional[str]:
    """Final attribute/name segment of a call's callee (``self.x.stream_blocks``
    → ``stream_blocks``) — _dotted rejects chains rooted at calls/subscripts,
    so producers reached through them still resolve."""
    if not isinstance(node, ast.Call):
        return None
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return alias.get(func.id, func.id).rsplit(".", 1)[-1]
    return None


class _FunctionScope:
    """Per-function taint state (the analysis never crosses function
    boundaries: taint enters where a file is opened/read, and a function
    receiving a whole buffer as a parameter audits at its caller)."""

    def __init__(self) -> None:
        self.handles: Set[str] = set()
        #: names carrying ANY file/stream-derived data (a bounded window
        #: counts: accumulating windows is how O(file) creeps back in).
        self.tainted: Set[str] = set()
        #: names carrying WHOLE-INPUT buffers (no-size reads, decompress
        #: results) — the only tier GH005's numpy-staging rule fires on;
        #: staging one bounded chunk is the windowed idiom, not a finding.
        self.whole: Set[str] = set()
        #: list names that accumulated stream items (GH005's second source).
        self.accumulated: Set[str] = set()


class _HostmemVisitor(ast.NodeVisitor):
    def __init__(self, relpath: str, alias: Dict[str, str]):
        self.relpath = relpath
        self.alias = alias
        self.findings: List[Finding] = []
        self._scopes: List[_FunctionScope] = [_FunctionScope()]
        self._loop_depth = 0

    # ------------------------------------------------------------- plumbing

    @property
    def scope(self) -> _FunctionScope:
        return self._scopes[-1]

    def emit(self, rule_id: str, node: ast.AST, detail: str) -> None:
        rule = HOSTMEM_RULES[rule_id]
        if not rule.applies_to(self.relpath):
            return
        self.findings.append(
            Finding(
                rule_id,
                self.relpath,
                getattr(node, "lineno", 0),
                getattr(node, "col_offset", 0) + 1,
                detail,
            )
        )

    def _is_tainted(self, node: ast.expr) -> bool:
        """Whether an expression carries file/stream-derived data. Scalar
        extractor calls launder taint (their results are O(1))."""
        name = _call_name(node, self.alias)
        if name in _SCALAR_EXTRACTORS:
            return False
        if self._is_taint_source(node):
            return True
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and (
                sub.id in self.scope.tainted
                or sub.id in self.scope.handles
                or sub.id in self.scope.accumulated
            ):
                return True
            if isinstance(sub, ast.Call) and self._is_taint_source(sub):
                return True
        return False

    def _is_taint_source(self, node: ast.expr) -> bool:
        """Calls whose RESULT is file/stream data regardless of arguments:
        handle reads and whole-buffer decompressors."""
        if not isinstance(node, ast.Call):
            return False
        name = _call_name(node, self.alias)
        if name in _DECOMPRESSORS:
            return True
        func = node.func
        if (
            isinstance(func, ast.Attribute)
            and func.attr in ("read", "read1", "readline", "readlines")
            and isinstance(func.value, ast.Name)
            and func.value.id in self.scope.handles
        ):
            return True
        return False

    def _is_whole_source(self, node: ast.expr) -> bool:
        """Calls whose result is a WHOLE-input buffer: no-size reads,
        readlines, and one-shot decompressors."""
        if not isinstance(node, ast.Call):
            return False
        if _call_name(node, self.alias) in _DECOMPRESSORS:
            return True
        func = node.func
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id in self.scope.handles
        ):
            if func.attr == "read" and not node.args and not node.keywords:
                return True
            if func.attr == "readlines":
                return True
        return False

    def _is_whole(self, node: ast.expr) -> bool:
        """Whether an expression carries a whole-input buffer (GH005's
        trigger tier)."""
        if _call_name(node, self.alias) in _SCALAR_EXTRACTORS:
            return False
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and (
                sub.id in self.scope.whole
                or sub.id in self.scope.accumulated
            ):
                return True
            if isinstance(sub, ast.Call) and self._is_whole_source(sub):
                return True
        return False

    def _is_stream_iterable(self, node: ast.expr) -> bool:
        """Whether a for-loop iterable is a file handle or a streaming
        block producer (its items are then tainted window data)."""
        if isinstance(node, ast.Name) and node.id in self.scope.handles:
            return True
        tail = _attr_tail(node, self.alias)
        if tail in _STREAM_PRODUCERS:
            return True
        # Transparent iterator wrappers: ``enumerate(f)``, ``zip(a, f)``.
        if isinstance(node, ast.Call) and _call_name(node, self.alias) in (
            "enumerate",
            "zip",
            "iter",
            "reversed",
        ):
            return any(self._is_stream_iterable(arg) for arg in node.args)
        # Generator-expression shells over a stream, e.g.
        # ``(v for _, v in dataset.iter_shards())``.
        if isinstance(node, ast.GeneratorExp):
            return any(
                self._is_stream_iterable(gen.iter) for gen in node.generators
            )
        return False

    def _taint_target(self, target: ast.expr, whole: bool = False) -> None:
        if isinstance(target, ast.Name):
            self.scope.tainted.add(target.id)
            if whole:
                self.scope.whole.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for element in target.elts:
                self._taint_target(element, whole=whole)

    # ------------------------------------------------------------ functions

    def _visit_function(self, node: Any) -> None:
        self._scopes.append(_FunctionScope())
        outer_depth, self._loop_depth = self._loop_depth, 0
        self.generic_visit(node)
        self._loop_depth = outer_depth
        self._scopes.pop()

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function
    visit_Lambda = _visit_function

    # ---------------------------------------------------------------- binds

    def visit_With(self, node: ast.With) -> None:
        for item in node.items:
            opener = _call_name(item.context_expr, self.alias)
            if opener in _FILE_OPENERS and isinstance(
                item.optional_vars, ast.Name
            ):
                self.scope.handles.add(item.optional_vars.id)
        self.generic_visit(node)

    visit_AsyncWith = visit_With  # type: ignore[assignment]

    def visit_Assign(self, node: ast.Assign) -> None:
        opener = _call_name(node.value, self.alias)
        if opener in _FILE_OPENERS:
            for target in node.targets:
                if isinstance(target, ast.Name):
                    self.scope.handles.add(target.id)
        elif self._is_tainted(node.value):
            whole = self._is_whole(node.value)
            for target in node.targets:
                self._taint_target(target, whole=whole)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        # ``buf += chunk`` inside the read loop is GH002's byte-buffer
        # spelling of unbounded accumulation.
        if (
            self._loop_depth > 0
            and isinstance(node.op, ast.Add)
            and isinstance(node.target, ast.Name)
            and self._is_tainted(node.value)
        ):
            self.emit(
                "GH002",
                node,
                f"`{node.target.id} += ...` accumulates stream-derived data "
                "inside the read loop — peak host memory grows with the "
                "input, not the window",
            )
            self.scope.tainted.add(node.target.id)
            self.scope.accumulated.add(node.target.id)
        self.generic_visit(node)

    # ---------------------------------------------------------------- loops

    def _visit_loop(self, node: Any) -> None:
        if isinstance(node, (ast.For, ast.AsyncFor)) and self._is_stream_iterable(
            node.iter
        ):
            self._taint_target(node.target)
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop
    visit_While = _visit_loop

    # ---------------------------------------------------------------- calls

    def visit_Call(self, node: ast.Call) -> None:
        name = _call_name(node, self.alias)
        func = node.func

        # GH001: whole-file read on a known handle.
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id in self.scope.handles
        ):
            if func.attr == "read" and not node.args and not node.keywords:
                self.emit(
                    "GH001",
                    node,
                    f"`{func.value.id}.read()` with no size stages the whole "
                    "file in host RAM; read a bounded window in a loop",
                )
            elif func.attr == "readlines":
                self.emit(
                    "GH001",
                    node,
                    f"`{func.value.id}.readlines()` materializes every line "
                    "at once; iterate the handle instead",
                )

        # GH002: accumulation of stream-derived items inside a loop.
        if (
            self._loop_depth > 0
            and isinstance(func, ast.Attribute)
            and func.attr in ("append", "extend", "appendleft")
            and node.args
            and self._is_tainted(node.args[0])
        ):
            self.emit(
                "GH002",
                node,
                f".{func.attr}() of file/stream-derived data inside the "
                "read loop accumulates the whole input on host; consume "
                "per window instead",
            )
            if isinstance(func.value, ast.Name):
                self.scope.accumulated.add(func.value.id)
                self.scope.tainted.add(func.value.id)

        # GH003: whole-stream materialization.
        if (
            name in ("list", "tuple")
            and len(node.args) == 1
            and not node.keywords
            and self._is_stream_iterable(node.args[0])
        ):
            what = (
                "a file handle"
                if isinstance(node.args[0], ast.Name)
                else f"streaming producer "
                f"{_attr_tail(node.args[0], self.alias)!r}"
            )
            self.emit(
                "GH003",
                node,
                f"{name}() over {what} materializes the whole stream the "
                "producer keeps windowed",
            )

        # GH004: one-shot whole-buffer decompress.
        if name in _DECOMPRESSORS:
            self.emit(
                "GH004",
                node,
                f"{name}() holds compressed and decompressed copies of the "
                "payload simultaneously; stream through the module's file "
                "interface with windowed reads",
            )

        # GH005: numpy staging over a whole-file buffer (bounded-window
        # chunks are the staging idiom and stay clean — only the whole
        # tier and stream-accumulated lists fire).
        if name in _NP_STAGING and node.args and self._is_whole(node.args[0]):
            self.emit(
                "GH005",
                node,
                f"{name.replace('numpy', 'np')}() over a whole-file buffer "
                "(or stream-accumulated list) stages an O(input) host "
                "array; stage per chunk/block",
            )

        self.generic_visit(node)


def audit_source(
    source: str, relpath: str
) -> Tuple[List[Finding], List[DeclaredSite]]:
    """Audit one file's text. Returns ``(findings, declared sites)``.

    A finding on a line carrying a justified ``hostmem(unbounded)`` hatch
    moves to the declared inventory — the report still says WHAT a hatch
    hides — but the hatch itself fires GH006 (*declared-unbounded-
    forbidden*): with the inventory at zero and every source on the
    windowed stream abstraction, the hatch syntax is a finding, not a
    declaration, so the audit can never pass with one present."""
    tree = ast.parse(source, filename=relpath)
    alias = _collect_aliases(tree)
    visitor = _HostmemVisitor(relpath, alias)
    visitor.visit(tree)
    hatches = parse_hostmem_hatches(source)
    findings: List[Finding] = []
    declared: List[DeclaredSite] = []
    for f in sorted(visitor.findings, key=lambda f: (f.line, f.rule_id, f.col)):
        why = hatches.get(f.line)
        if why is not None:
            declared.append(
                DeclaredSite(f.rule_id, f.path, f.line, f.detail, why)
            )
        else:
            findings.append(f)
    gh006 = HOSTMEM_RULES["GH006"]
    if gh006.applies_to(relpath):
        for lineno, col in iter_hatch_comments(source):
            findings.append(
                Finding(
                    "GH006",
                    relpath,
                    lineno,
                    col,
                    "hostmem(unbounded) escape hatch: the declared-"
                    "inventory era is over — refactor the site through "
                    "the windowed stream abstraction (sources/stream.py) "
                    "instead of declaring it O(file)",
                )
            )
        findings.sort(key=lambda f: (f.line, f.rule_id, f.col))
    return findings, declared


def default_hostmem_paths() -> List[str]:
    """The audited host-staging layers of the installed package (kept in
    lockstep with ``check/rules.py:HOSTMEM_GLOBS``): the ingest stack,
    the resident service's control plane (``serve/``), and the
    population-genetics analyses (``analyses/`` — the per-site output
    layer whose boundedness is the whole point)."""
    import spark_examples_tpu

    package_dir = os.path.dirname(os.path.abspath(spark_examples_tpu.__file__))
    return [
        os.path.join(package_dir, sub)
        for sub in ("sources", "pipeline", "ops", "serve", "analyses")
    ]


def audit_paths(paths: Sequence[str]) -> HostmemReport:
    """Audit files/trees (``graftcheck hostmem`` engine)."""
    report = HostmemReport()
    seen: Set[str] = set()
    for root in paths:
        for full, relpath in _iter_files_scoped(root):
            if full in seen:
                continue
            seen.add(full)
            with open(full, "r", encoding="utf-8") as f:
                source = f.read()
            try:
                findings, declared = audit_source(source, relpath)
            except SyntaxError as e:
                report.findings.append(
                    Finding(
                        "GH001",
                        relpath,
                        e.lineno or 0,
                        e.offset or 0,
                        f"file does not parse; the audit cannot vouch for "
                        f"it: {e.msg}",
                    )
                )
                report.checked_files += 1
                continue
            report.findings.extend(findings)
            report.declared.extend(declared)
            report.checked_files += 1
    report.findings.sort(key=lambda f: (f.path, f.line, f.rule_id))
    report.declared.sort(key=lambda d: (d.path, d.line, d.rule_id))
    return report


def _iter_files_scoped(root: str) -> Iterable[Tuple[str, str]]:
    """(abs_path, package-relative path) pairs, through the linter's shared
    package-root resolution so scope globs match regardless of the path the
    CLI was handed (a subdirectory, a single file, or the package root)."""
    from spark_examples_tpu.check.linter import _package_relpath

    if os.path.isfile(root):
        yield root, _package_relpath(root)
        return
    for full, _rel in _iter_py_files(root):
        yield full, _package_relpath(full)


# --------------------------------------------------------------------------
# The configuration-level budget resolver (formula in parallel/mesh.py).
# --------------------------------------------------------------------------


def conf_mesh_axes(conf: Any, device_count: Optional[int]) -> Tuple[int, int]:
    """(data, samples) a run of ``conf`` would build — the same resolution
    ``check/plan.py`` and ``pca_driver._make_mesh`` apply, shared here so
    the budget formula's geometry inputs cannot drift from either."""
    from spark_examples_tpu.parallel.mesh import parse_mesh_shape

    mesh_shape = getattr(conf, "mesh_shape", None)
    if mesh_shape:
        shape = parse_mesh_shape(mesh_shape)
        return shape["data"], shape["samples"]
    devices = device_count if device_count is not None else 1
    data = max(1, min(devices, int(conf.num_reduce_partitions)))
    return data, 1


def _streamable_vcf_input(conf: Any) -> bool:
    """Whether the configured file ingest is the packed-streaming shape
    (``FileGenomicsSource.wants_streaming``'s static mirror): a single
    variant set whose selected input is a ``.vcf[.gz]`` file. Everything
    else (JSONL/SAM, checkpoint directories, multi-set configs) takes the
    wire-table path, which is bounded by its own closed-form term now —
    this predicate picks the FORMULA, it no longer gates provability."""
    input_files = list(getattr(conf, "input_files", None) or [])
    set_ids = list(getattr(conf, "variant_set_id", None) or [])
    if not input_files or len(set_ids) != 1:
        return False
    from spark_examples_tpu.sources.files import file_set_ids

    by_id = dict(zip(file_set_ids(input_files), input_files))
    path = by_id.get(set_ids[0])
    if path is None or os.path.isdir(path):
        return False
    lowered = path[:-3] if path.endswith(".gz") else path
    return lowered.endswith(".vcf")


def _selected_paths(conf: Any) -> List[str]:
    """The input paths a file-source run of ``conf`` would actually read:
    ``--input-files`` filtered to the selected ``--variant-set-id``s (the
    same id derivation ``sources/files.py:file_set_ids`` applies), all of
    them when no set filter is configured or an id fails to resolve."""
    input_files = [str(p) for p in (getattr(conf, "input_files", None) or [])]
    set_ids = list(getattr(conf, "variant_set_id", None) or [])
    if not input_files:
        return []
    if not set_ids:
        return input_files
    from spark_examples_tpu.sources.files import file_set_ids

    by_id = dict(zip(file_set_ids(input_files), input_files))
    selected = [by_id[s] for s in set_ids if s in by_id]
    return selected if selected else input_files


def _wire_record_bytes(num_samples: int) -> int:
    """Conservative host bytes of ONE wire/JSONL/SAM record object: a
    fixed per-record envelope (dict + key strings + position/id scalars)
    plus the per-sample call payload (one small int/str cell per sample
    after decode). 128 bytes/sample dominates any decoded call cell
    CPython allocates; 256 dominates the envelope."""
    return 256 + 128 * int(num_samples)


def _rows_bound_or_contract(path: str) -> int:
    """Total candidate rows one input path can yield, from the bytes on
    disk (``stream.wire_rows_bound``: min-line-width over the decompressed
    size bound), falling back to the DECLARED production geometry ceiling
    (``ops/contracts.py:DECLARED_MAX_SITES``) for paths that cannot be
    statted (plan-time validation of a path that does not exist yet) or
    directories with nothing listable. Always finite, never raises."""
    from spark_examples_tpu.ops.contracts import DECLARED_MAX_SITES
    from spark_examples_tpu.sources.stream import wire_rows_bound

    try:
        if os.path.isdir(path):
            rows = 0
            for name in sorted(os.listdir(path)):
                full = os.path.join(path, name)
                if os.path.isfile(full):
                    rows += wire_rows_bound(full)
            return rows if rows > 0 else DECLARED_MAX_SITES
        if os.path.isfile(path):
            rows = wire_rows_bound(path)
            return rows if rows > 0 else DECLARED_MAX_SITES
    except OSError:
        pass
    return DECLARED_MAX_SITES


def _wire_table_term(rows: int, num_samples: int) -> int:
    """Host-resident bytes of one wire-ingest table of ``rows`` records:
    the spool index (``SPOOL_INDEX_BYTES_PER_ROW`` per row) plus — taken
    conservatively as fully co-resident — every decoded record, plus four
    stream windows (reader carry + decode + spool write-behind)."""
    from spark_examples_tpu.sources.stream import (
        DEFAULT_WINDOW_BYTES,
        SPOOL_INDEX_BYTES_PER_ROW,
    )

    per_row = SPOOL_INDEX_BYTES_PER_ROW + _wire_record_bytes(num_samples)
    return int(rows) * per_row + 4 * DEFAULT_WINDOW_BYTES


def conf_host_peak_bytes(
    conf: Any,
    device_count: Optional[int] = None,
    num_samples: Optional[int] = None,
    num_hosts: int = 1,
) -> int:
    """``host_peak_bytes`` for one parsed configuration. TOTAL: every
    (source kind x ingest mode x analysis x serve job kind) resolves to a
    finite closed-form bound — there is no ``None`` arm left, because
    every ingest path now runs through the windowed stream abstraction
    (``sources/stream.py``) whose residency is a formula, not the file.

    ``num_samples`` overrides the flag value with the DISCOVERED cohort
    width (file sources carry their cohort in the data; the driver passes
    its resolved matrix size, the static plan validator the declared flag).
    ``num_hosts > 1`` charges the host-sharded ingest merge term — a
    PER-HOST bound (the driver passes ``jax.process_count()``; offline
    validation stays at 1).

    Per-path terms, all monotone in the cohort width:

    - synthetic: the device-generation path stages nothing whole-file;
      only the runtime baseline and analysis terms apply.
    - file, single ``.vcf[.gz]`` set, packed/auto ingest: one streamed
      pass (O(workers x chunk) parse staging at the explicit
      ``--stream-chunk-bytes`` or the ``sources/files.py`` default) plus
      the packed columns' build/hand-off co-residency,
      ``2 x rows x (N + 48)`` (int8 genotype row + per-site metadata,
      builder AND final array alive across the final copy).
    - file wire / JSONL / SAM / multi-set: the wire-table term per
      selected input (spool index + conservatively co-resident decoded
      records + stream windows), plus a merge-join term
      ``n_sets x 64 x record_bytes`` when joining (64 = the per-stream
      tracked-group ceiling ``stream.merge_join`` accounts against).
    - REST: one wire table at the declared geometry ceiling
      (``DECLARED_MAX_SITES`` rows — the pagination protocol carries no
      size upfront, so the production contract is the bound).
    - checkpoint resume (``--input-path``): the wire-table term over the
      journal directory's parts (sizes from disk when statable, the
      geometry ceiling otherwise).
    """
    from spark_examples_tpu.parallel.mesh import host_peak_bytes
    from spark_examples_tpu.sources.files import _resolve_ingest_workers

    if num_samples is None:
        num_samples = int(conf.num_samples)
    n = int(num_samples)
    source = getattr(conf, "source", "synthetic")
    stream_chunk = getattr(conf, "stream_chunk_bytes", None)
    ingest = getattr(conf, "ingest", "auto")
    chunk_bytes = 0
    wire_table_bytes = 0
    merge_join_bytes = 0
    input_path = getattr(conf, "input_path", None)
    if input_path:
        # Checkpoint resume replays journal parts through the windowed
        # JSONL reader into one wire table; charge it like any wire input.
        wire_table_bytes = _wire_table_term(
            _rows_bound_or_contract(str(input_path)), n
        )
    elif source == "file":
        if ingest != "wire" and _streamable_vcf_input(conf):
            from spark_examples_tpu.sources.files import STREAM_CHUNK_BYTES

            chunk_bytes = (
                int(stream_chunk)
                if stream_chunk and stream_chunk > 0
                else STREAM_CHUNK_BYTES
            )
            rows = _rows_bound_or_contract(_selected_paths(conf)[0])
            wire_table_bytes = 2 * rows * (n + 48)
        else:
            paths = _selected_paths(conf)
            wire_table_bytes = sum(
                _wire_table_term(_rows_bound_or_contract(p), n)
                for p in paths
            )
            if len(paths) > 1:
                merge_join_bytes = (
                    len(paths) * 64 * _wire_record_bytes(n)
                )
    elif source == "rest":
        from spark_examples_tpu.ops.contracts import DECLARED_MAX_SITES

        set_ids = list(getattr(conf, "variant_set_id", None) or [None])
        wire_table_bytes = len(set_ids) * _wire_table_term(
            DECLARED_MAX_SITES, n
        )
        if len(set_ids) > 1:
            merge_join_bytes = len(set_ids) * 64 * _wire_record_bytes(n)
    workers = _resolve_ingest_workers(getattr(conf, "ingest_workers", None))
    data, _samples = conf_mesh_axes(conf, device_count)
    # Mirrors pipeline/pca_driver._similarity_stage: a depth-2
    # PrefetchIterator and the double-buffered device feed exist whenever
    # parse workers do (any packed-path source). The pure device-generation
    # path has neither, so for it these terms only make the bound more
    # conservative — never smaller than reality.
    prefetch_depth = 2 if workers > 0 else 0
    pipeline_depth = 2 if workers > 0 else 0
    host_backend = getattr(conf, "pca_backend", "tpu") == "host"
    from spark_examples_tpu.config import AssocConf, GrmConf, LdConf

    return host_peak_bytes(
        num_samples=n,
        block_size=int(conf.block_size),
        data_axis=data,
        ingest_workers=workers,
        chunk_bytes=chunk_bytes,
        prefetch_depth=prefetch_depth,
        pipeline_depth=pipeline_depth,
        # The host-oracle N×N accumulator exists only where the run
        # builds a Gramian (PCA, and GRM whose device work IS the
        # Gramian); LD/assoc under --pca-backend host run O(window)
        # NumPy oracles and must not be charged for a matrix they
        # never allocate.
        host_accumulator=(
            host_backend and not isinstance(conf, (LdConf, AssocConf))
        ),
        # The GRM finalize's N×N host matrices and the LD prune's W×W
        # per-flush working set are costs the PCA path never pays — the
        # plan budget, the driver's gauge, and the manifest's host_memory
        # block all resolve through here, so the terms cannot drift
        # between prover and runtime.
        grm_finalize=isinstance(conf, GrmConf),
        ld_window_sites=(
            int(getattr(conf, "ld_window_sites", 0) or 0)
            if isinstance(conf, LdConf)
            else 0
        ),
        num_hosts=int(num_hosts),
        wire_table_bytes=wire_table_bytes,
        merge_join_bytes=merge_join_bytes,
    )


__all__ = [
    "DeclaredSite",
    "HostmemReport",
    "audit_paths",
    "audit_source",
    "conf_host_peak_bytes",
    "conf_mesh_axes",
    "default_hostmem_paths",
    "iter_hatch_comments",
    "parse_hostmem_hatches",
]

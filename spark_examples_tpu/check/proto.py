"""graftcheck proto — exhaustive model checking of the replica
coordination protocol, with the shipped fold as the oracle.

An explicit-state model checker over the N-replica, crash-anywhere
state space of the serve-tier coordination protocol (shared fsync'd
journal + epoch-fenced lease files, ``serve/journal.py``). The crucial
property: every protocol DECISION in the model is made by the SHIPPED
code — :func:`~spark_examples_tpu.serve.journal.fold_records`,
:func:`~spark_examples_tpu.serve.journal.arbitrate_claim`,
:func:`~spark_examples_tpu.serve.journal.owner_valid`,
:func:`~spark_examples_tpu.serve.journal.revalidate_pending`,
:func:`~spark_examples_tpu.serve.journal.adoption_action`,
:func:`~spark_examples_tpu.serve.journal.steal_candidates`,
:func:`~spark_examples_tpu.serve.journal.compacted_records` — run
unchanged against an in-memory journal/lease model. Only the file
primitives (append, fsync, link, unlink, crash) are modeled, so what
the checker proves is what the fleet ships.

The model, in brief:

- **Journal** — an append-ordered tuple of compact records, expanded
  through the shipped record constructors before every oracle call. An
  fsync'd append makes EVERY earlier record durable (page-cache
  semantics); the non-durable tail is exactly the records
  :func:`~spark_examples_tpu.serve.journal.terminal_fsync` says may
  skip fsync. Crashes come in two flavors: a PROCESS crash erases one
  replica's memory and loses nothing (a dead process's page cache is
  still the OS's to flush), while a HOST crash kills every replica at
  once and branches over every prefix of the non-durable tail
  surviving — the only record-dropping transition, because a live peer
  observing a page-cache rollback is not physically realizable.
- **Leases** — one view per job: ``(replica, epoch, age)`` with a
  three-point abstract clock: ``live`` (unexpired), ``lapsed``
  (expired, within the grace window) and ``stale`` (expired past
  grace). Ages are concretized to ``expires_unix`` values just before
  each oracle call, so the shipped arbitration sees real numbers.
  Aging steps consume the ``stalls`` budget.
- **Replicas** — each holds in-memory jobs as ``(phase, epoch)``:
  ``accepted → claimed → queued → running → published`` (submit path),
  or ``adopting``/``stealing`` on the recovery paths. A crash erases
  memory; the journal and lease files survive.

Timing assumption (documented, load-bearing): the ownership fence and
the action it guards (begin dispatch, result publication) are atomic —
a replica cannot stall between checking :func:`owner_valid` and acting.
The one window deliberately left OPEN is publish → terminal-append: the
terminal write is unguarded, which is precisely the zombie window the
fold's epoch fencing exists to absorb. Clean runs therefore DO reach
fenced terminals — the fencing is exercised, not assumed.

Invariants (rule catalogue in ``check/rules.py:PROTO_RULES``):

- **GP001** double-effective-terminal (or two replicas publishing one
  job's result);
- **GP002** device-began re-execution (requeue-once violated);
- **GP003** accepted-and-acked job lost (no record, no memory, nobody
  will ever settle it);
- **GP004** a journaled lease record re-issues the highest journaled
  epoch under a different replica (fencing ambiguous);
- **GP005** successful steal of a live / within-grace lease;
- **GP006** reachable crash window with no registered
  ``utils/faults.py`` kill-point (the chaos matrix could never
  rehearse it).

Symmetry reduction canonicalizes each state as the minimum over all
replica and job renamings, so the declared bounds (replicas <= 3,
jobs <= 2, crash budget <= 2) stay explorable on CPU.

The mutation harness (:data:`MUTATIONS`) re-runs the exploration with
single-decision bugs planted in the model's use of the oracles —
fencing skipped, fold epoch-blind, steals graceless, the min-epoch
guard dropped — and requires each to trip its matching GP rule: the
checker is itself checked. Mutation runs stop at the first expected
finding (a witness is a witness); only the clean run must drain the
frontier.

Historical note: the first clean run of this checker was NOT clean — it
found the submit-path race now fenced by ``revalidate_pending`` in
``serve/daemon.py:submit`` (an accepter that stalls after its lease
claim while a restarting peer adopts and settles the job would have
re-enqueued and re-run it). The fix landed with the checker.
"""

from __future__ import annotations

import itertools
import json
import time
from collections import deque
from dataclasses import dataclass, replace
from typing import Any, Callable, Dict, Iterator, List, Optional, Set, Tuple

from spark_examples_tpu.check.rules import Finding
from spark_examples_tpu.serve.journal import (
    LeaseView,
    PendingJob,
    accepted_record,
    adoption_action,
    arbitrate_claim,
    began_record,
    compacted_records,
    fold_records,
    foreign_expired,
    lease_record,
    owner_valid,
    protocol_summary,
    revalidate_pending,
    steal_candidates,
    terminal_fsync,
    terminal_record,
)
from spark_examples_tpu.utils.faults import registered_kill_points

__all__ = [
    "MODEL_PATH",
    "Mutations",
    "Mutation",
    "MutationOutcome",
    "MUTATIONS",
    "ProtoReport",
    "check_protocol",
    "run_mutation_harness",
]


#: Finding anchor: GP findings attach to a witness trace, not a source
#: line, so their path names the model and their line is 0.
MODEL_PATH = "proto:replica-coordination"

#: The abstract clock, concretized at every oracle call. NOW never
#: advances — lease AGE carries all timing truth.
_NOW = 0.0
_GRACE = 10.0
_EXPIRES: Dict[str, float] = {"live": 100.0, "lapsed": -5.0, "stale": -100.0}
_NEXT_AGE: Dict[str, str] = {"live": "lapsed", "lapsed": "stale"}

#: Token-safe names (no name matches the fold's ``job-`` sequence
#: grammar) so symmetry renaming is a per-field substitution.
_REPLICA_NAMES = ("repA", "repB", "repC")
_JOB_NAMES = ("jobA", "jobB")

#: In-memory phase -> the registered kill-point that must cover a crash
#: there (GP006's ground truth).
_PHASE_WINDOW: Dict[str, str] = {
    "accepted": "serve.submit.post-accept",
    "claimed": "serve.lease.post-claim",
    "adopting": "serve.lease.post-claim",
    "stealing": "serve.lease.post-claim",
    "queued": "serve.worker.claim",
    "running": "serve.worker.mid-job",
    "published": "serve.worker.mid-job",
}


@dataclass(frozen=True)
class Mutations:
    """Single-decision bugs planted into the model's USE of the shipped
    oracles — each field corresponds to deleting or lobotomizing one
    line of the real protocol. All ``False`` = the shipped protocol."""

    #: begin/publish skip the :func:`owner_valid` fence.
    skip_owner_fence: bool = False
    #: the fold ignores terminal epochs (fencing lobotomized).
    epoch_blind_fold: bool = False
    #: the fold ignores ``began`` records (requeue-once lobotomized).
    began_blind_fold: bool = False
    #: steals use grace 0 (the asymmetric window deleted).
    graceless_steal: bool = False
    #: claims pass ``min_epoch=0`` (the stale-fold guard deleted).
    skip_min_epoch: bool = False
    #: submit skips the post-claim ``revalidate_pending`` fence (the
    #: race the checker originally FOUND in the shipped submit path).
    skip_submit_revalidate: bool = False
    #: compaction skips the inode re-check: concurrent appenders keep
    #: writing the replaced file and their records vanish.
    skip_inode_recheck: bool = False
    #: ``serve.lease.post-claim`` deleted from the kill-point registry.
    unregistered_crash_site: bool = False


#: Compact journal records — expanded via the shipped constructors at
#: oracle time (see ``_Explorer._to_dict``):
#:   ("accepted", job, replica)
#:   ("began",    job, replica, epoch)
#:   ("lease",    job, replica, epoch, stolen)
#:   ("terminal", job, replica, epoch, status)
_Rec = Tuple[Any, ...]

#: One replica's in-memory jobs: (job, phase, epoch), sorted.
_Jobs = Tuple[Tuple[str, str, int], ...]


@dataclass(frozen=True, slots=True)
class _State:
    """One explored protocol state. Collections are sorted tuples so
    renaming + re-sorting yields a canonical form (the journal alone
    keeps append order — order IS its meaning)."""

    journal: Tuple[_Rec, ...]
    #: Prefix length of ``journal`` known durable.
    durable: int
    #: (job, replica, epoch, age) — at most one lease view per job.
    leases: Tuple[Tuple[str, str, int, str], ...]
    #: (name, alive, jobs) per replica.
    replicas: Tuple[Tuple[str, bool, _Jobs], ...]
    unsubmitted: Tuple[str, ...]
    #: Jobs whose 202 went out (after the accepted fsync).
    acked: Tuple[str, ...]
    #: Jobs compaction dropped as settled (their records are GONE from
    #: the journal by design — GP003 must not count them as lost).
    settled_compacted: Tuple[str, ...]
    #: Jobs whose ``began`` record was ever fsync'd (GP002's raw truth,
    #: immune to fold mutations and compaction).
    began_ever: Tuple[str, ...]
    #: (job, replica) result publications ever made (GP001's raw truth).
    published_by: Tuple[Tuple[str, str], ...]
    #: Replicas holding a stale journal fd (skip_inode_recheck only).
    stale: Tuple[str, ...]
    crashes: int
    stalls: int


def _add(items: Tuple[str, ...], item: str) -> Tuple[str, ...]:
    return items if item in items else tuple(sorted(items + (item,)))


def _drop(items: Tuple[str, ...], item: str) -> Tuple[str, ...]:
    return tuple(i for i in items if i != item)


@dataclass
class ProtoReport:
    """The ``graftcheck proto`` result: declared bounds, exploration
    counts, and every invariant finding with its witness trace."""

    bounds: Dict[str, int]
    states: int
    transitions: int
    elapsed_seconds: float
    #: True iff the frontier drained within ``max_states``.
    exhausted: bool
    findings: List[Finding]
    #: Every crash window the model reached, and the uncovered subset.
    crash_windows: List[str]
    uncovered_windows: List[str]

    @property
    def ok(self) -> bool:
        return self.exhausted and not self.findings

    def to_json(self) -> str:
        return json.dumps(
            {
                "tool": "graftcheck-proto",
                "ok": self.ok,
                "bounds": dict(self.bounds),
                "states": self.states,
                "transitions": self.transitions,
                "elapsed_seconds": round(self.elapsed_seconds, 3),
                "exhausted": self.exhausted,
                "crash_windows": list(self.crash_windows),
                "uncovered_windows": list(self.uncovered_windows),
                "findings": [f.to_json() for f in self.findings],
            },
            indent=2,
        )

    def format(self) -> str:
        bounds = ", ".join(f"{k}={v}" for k, v in sorted(self.bounds.items()))
        lines = [
            f"graftcheck proto: bounds [{bounds}]",
            (
                f"explored {self.states} state(s), {self.transitions} "
                f"transition(s) in {self.elapsed_seconds:.2f}s "
                f"({'exhaustive' if self.exhausted else 'stopped early'})"
            ),
            (
                f"crash windows reached: "
                f"{', '.join(self.crash_windows) or '(none)'}"
            ),
        ]
        for finding in self.findings:
            lines.append(finding.format())
        lines.append(
            "clean: every reachable state satisfies GP001-GP006"
            if self.ok
            else f"{len(self.findings)} protocol finding(s)"
        )
        return "\n".join(lines)


def _journal_sort_key(rec: _Rec) -> str:
    """Canonical journal order groups records by job id only: stable
    sort, so the per-job subsequence (the order the fold can actually
    distinguish) is preserved verbatim."""
    return str(rec[1])


class _Explorer:
    """BFS over the protocol state space with symmetry reduction."""

    def __init__(
        self,
        replicas: int,
        jobs: int,
        crashes: int,
        stalls: int,
        mutations: Mutations,
        max_states: int,
        stop_on_rule: Optional[str] = None,
    ) -> None:
        if not 1 <= replicas <= len(_REPLICA_NAMES):
            raise ValueError(f"replicas must be 1..3, got {replicas}")
        if not 1 <= jobs <= len(_JOB_NAMES):
            raise ValueError(f"jobs must be 1..2, got {jobs}")
        self.replica_names = _REPLICA_NAMES[:replicas]
        self.job_names = _JOB_NAMES[:jobs]
        self.bounds = {
            "replicas": replicas,
            "jobs": jobs,
            "crashes": crashes,
            "stalls": stalls,
        }
        self.mut = mutations
        self.max_states = max_states
        self.stop_on_rule = stop_on_rule
        self._stop = False
        registry = registered_kill_points()
        if mutations.unregistered_crash_site:
            registry.pop("serve.lease.post-claim", None)
        self.registry = registry
        self.crash_windows: Set[str] = set()
        self.uncovered: Set[str] = set()
        self.states = 0
        self.transitions = 0
        self.exhausted = False
        #: identity permutations, precomputed once.
        self._perms = [
            dict(zip(self.replica_names + self.job_names, rperm + jperm))
            for rperm in itertools.permutations(self.replica_names)
            for jperm in itertools.permutations(self.job_names)
        ]
        #: canonical key -> (parent key, transition label)
        self._parent: Dict[Any, Tuple[Any, str]] = {}
        self._findings: Dict[Tuple[str, str], Finding] = {}
        self._dict_cache: Dict[_Rec, Dict[str, Any]] = {}
        self._fold_cache: Dict[
            Tuple[_Rec, ...], Tuple[List[PendingJob], int]
        ] = {}
        self._summary_cache: Dict[Tuple[_Rec, ...], Dict[str, Any]] = {}
        self._canon_cache: Dict[_State, Any] = {}

    # ---------------------------------------------------- oracle plumbing

    def _to_dict(self, rec: _Rec) -> Dict[str, Any]:
        """Expand a compact record through the SHIPPED constructor —
        the oracles only ever see real journal records."""
        cached = self._dict_cache.get(rec)
        if cached is not None:
            return cached
        kind = rec[0]
        if kind == "accepted":
            record = accepted_record(
                rec[1], {"payload": rec[1]}, "default", 0.0, None,
                replica=rec[2],
            )
        elif kind == "began":
            record = began_record(rec[1], replica=rec[2], epoch=rec[3])
        elif kind == "lease":
            record = lease_record(
                rec[1], rec[3], replica=rec[2], stolen=bool(rec[4])
            )
        else:
            record = terminal_record(
                rec[1], rec[4], replica=rec[2], epoch=rec[3]
            )
        self._dict_cache[rec] = record
        return record

    @staticmethod
    def _from_dict(record: Dict[str, Any]) -> _Rec:
        """Re-compact a record emitted by the shipped
        :func:`compacted_records` rewrite."""
        event = record["event"]
        job = record["id"]
        rep = record.get("replica")
        epoch = record.get("epoch")
        if event == "accepted":
            return ("accepted", job, rep)
        if event == "began":
            return ("began", job, rep, epoch)
        if event == "lease":
            return ("lease", job, rep, epoch, bool(record.get("stolen")))
        return ("terminal", job, rep, epoch, record.get("status"))

    def _fold_input(
        self, journal: Tuple[_Rec, ...]
    ) -> List[Dict[str, Any]]:
        """Records as the (possibly mutated) fold sees them. The
        epoch-blind mutation strips terminal epochs — the one-line
        equivalent of ``effective()`` returning True; the began-blind
        mutation drops ``began`` records — ``adoption_action`` never
        sees device work."""
        records = []
        for rec in journal:
            if self.mut.began_blind_fold and rec[0] == "began":
                continue
            if self.mut.epoch_blind_fold and rec[0] == "terminal":
                rec = ("terminal", rec[1], rec[2], None, rec[4])
            records.append(self._to_dict(rec))
        return records

    def _fold(
        self, journal: Tuple[_Rec, ...]
    ) -> Tuple[List[PendingJob], int]:
        cached = self._fold_cache.get(journal)
        if cached is None:
            cached = fold_records(self._fold_input(journal))
            self._fold_cache[journal] = cached
        return cached

    def _summary(self, journal: Tuple[_Rec, ...]) -> Dict[str, Any]:
        cached = self._summary_cache.get(journal)
        if cached is None:
            cached = protocol_summary(self._fold_input(journal))
            self._summary_cache[journal] = cached
        return cached

    def _lease_of(
        self, st: _State, job: str
    ) -> Optional[Tuple[str, str, int, str]]:
        for entry in st.leases:
            if entry[0] == job:
                return entry
        return None

    def _view(self, st: _State, job: str) -> Optional[LeaseView]:
        """Concretize the abstract lease age into the LeaseView the
        shipped arbitration reads."""
        entry = self._lease_of(st, job)
        if entry is None:
            return None
        return LeaseView(
            job_id=job, replica=entry[1], epoch=entry[2],
            expires_unix=_EXPIRES[entry[3]],
        )

    def _min_lease(
        self, pending: List[PendingJob], job: str
    ) -> Tuple[int, Optional[str]]:
        """The folded (min_epoch, min_replica) fencing facts the shipped
        claim paths pass to :func:`arbitrate_claim`."""
        if self.mut.skip_min_epoch:
            return 0, None
        for record in pending:
            if record.job_id == job:
                return record.lease_epoch, record.lease_replica
        return 0, None

    # ------------------------------------------------------ state surgery

    def _set_job(
        self, st: _State, name: str, job: str, phase: str, epoch: int
    ) -> Tuple[Tuple[str, bool, _Jobs], ...]:
        out = []
        for rname, alive, jobs in st.replicas:
            if rname == name:
                kept = tuple(j for j in jobs if j[0] != job)
                jobs = tuple(sorted(kept + ((job, phase, epoch),)))
            out.append((rname, alive, jobs))
        return tuple(out)

    def _drop_job(
        self, st: _State, name: str, job: str
    ) -> Tuple[Tuple[str, bool, _Jobs], ...]:
        return tuple(
            (
                rname,
                alive,
                tuple(j for j in jobs if j[0] != job)
                if rname == name
                else jobs,
            )
            for rname, alive, jobs in st.replicas
        )

    def _set_alive(
        self, st: _State, name: str, alive: bool
    ) -> Tuple[Tuple[str, bool, _Jobs], ...]:
        return tuple(
            (
                rname,
                alive if rname == name else ralive,
                () if rname == name else jobs,
            )
            for rname, ralive, jobs in st.replicas
        )

    def _set_lease(
        self, st: _State, job: str, rep: str, epoch: int, age: str
    ) -> Tuple[Tuple[str, str, int, str], ...]:
        kept = tuple(entry for entry in st.leases if entry[0] != job)
        return tuple(sorted(kept + ((job, rep, epoch, age),)))

    def _release_lease(
        self, st: _State, job: str, rep: str, epoch: int
    ) -> Tuple[Tuple[str, str, int, str], ...]:
        """Unlink our own lease file — a foreign or re-claimed lease is
        left alone (epoch-named files make the unlink self-owned)."""
        return tuple(
            entry
            for entry in st.leases
            if not (entry[0] == job and entry[1] == rep and entry[2] == epoch)
        )

    def _append(
        self,
        journal: Tuple[_Rec, ...],
        durable: int,
        rec: _Rec,
        fsync: bool,
        writer: str,
        stale: Tuple[str, ...],
    ) -> Tuple[Tuple[_Rec, ...], int]:
        """Append a record. A writer holding a stale fd (inode-recheck
        mutation) writes into the void; an fsync'd append makes the
        whole file durable."""
        if writer in stale:
            return journal, durable
        journal = journal + (rec,)
        return journal, len(journal) if fsync else durable

    def _mem(self, st: _State) -> Set[str]:
        return {
            job
            for _name, _alive, jobs in st.replicas
            for job, _phase, _epoch in jobs
        }

    # ------------------------------------------------------- transitions

    _Trans = Tuple[str, "_State", List[Tuple[str, str]]]

    def _transitions(self, st: _State) -> Iterator[_Trans]:
        for name, alive, jobs in st.replicas:
            if not alive:
                yield (
                    f"restart:{name}",
                    replace(st, replicas=self._set_alive(st, name, True)),
                    [],
                )
                continue
            yield from self._submit_transitions(st, name)
            for job, phase, epoch in jobs:
                yield from self._job_transitions(st, name, job, phase, epoch)
            yield from self._recovery_transitions(st, name, jobs)
            yield from self._compact_transition(st, name)
            yield from self._crash_transitions(st, name, jobs)
        yield from self._host_crash_transitions(st)
        if st.stalls > 0:
            for job, rep, epoch, age in st.leases:
                nage = _NEXT_AGE.get(age)
                if nage is None:
                    continue
                yield (
                    f"age:{job}:{nage}",
                    replace(
                        st,
                        stalls=st.stalls - 1,
                        leases=self._set_lease(st, job, rep, epoch, nage),
                    ),
                    [],
                )

    def _submit_transitions(self, st: _State, name: str) -> Iterator[_Trans]:
        for job in st.unsubmitted:
            journal, durable = self._append(
                st.journal, st.durable, ("accepted", job, name), True,
                name, st.stale,
            )
            yield (
                f"submit:{name}:{job}",
                replace(
                    st,
                    journal=journal,
                    durable=durable,
                    unsubmitted=_drop(st.unsubmitted, job),
                    acked=_add(st.acked, job),
                    replicas=self._set_job(st, name, job, "accepted", 0),
                ),
                [],
            )

    def _job_transitions(
        self, st: _State, name: str, job: str, phase: str, epoch: int
    ) -> Iterator[_Trans]:
        if phase == "accepted":
            pending, _seq = self._fold(st.journal)
            min_epoch, min_replica = self._min_lease(pending, job)
            action, e = arbitrate_claim(
                self._view(st, job),
                name,
                _NOW,
                _GRACE,
                steal=False,
                min_epoch=min_epoch,
                min_replica=min_replica,
            )
            if action == "deny":
                # Someone else claimed it meanwhile: the 202 is out and
                # the journal is durable — leave the job to its owner.
                yield (
                    f"claim-deny:{name}:{job}",
                    replace(st, replicas=self._drop_job(st, name, job)),
                    [],
                )
                return
            leases = (
                st.leases
                if action == "adopt"
                else self._set_lease(st, job, name, e, "live")
            )
            yield (
                f"claim:{name}:{job}:e{e}",
                replace(
                    st,
                    leases=leases,
                    replicas=self._set_job(st, name, job, "claimed", e),
                ),
                [],
            )
        elif phase in ("claimed", "adopting", "stealing"):
            yield from self._lease_journal_transition(
                st, name, job, phase, epoch
            )
        elif phase == "queued":
            fenced = self.mut.skip_owner_fence or owner_valid(
                self._view(st, job), name, epoch, _NOW
            )
            if not fenced:
                yield (
                    f"abandon:{name}:{job}",
                    replace(st, replicas=self._drop_job(st, name, job)),
                    [],
                )
                return
            finds: List[Tuple[str, str]] = []
            if job in st.began_ever:
                finds.append(
                    (
                        "GP002",
                        f"{job} begins device work a second time on "
                        f"{name}: its journaled `began` record did not "
                        f"stop re-execution",
                    )
                )
            journal, durable = self._append(
                st.journal, st.durable, ("began", job, name, epoch), True,
                name, st.stale,
            )
            yield (
                f"begin:{name}:{job}",
                replace(
                    st,
                    journal=journal,
                    durable=durable,
                    began_ever=_add(st.began_ever, job),
                    replicas=self._set_job(st, name, job, "running", epoch),
                ),
                finds,
            )
        elif phase == "running":
            fenced = self.mut.skip_owner_fence or owner_valid(
                self._view(st, job), name, epoch, _NOW
            )
            if not fenced:
                yield (
                    f"abandon:{name}:{job}",
                    replace(st, replicas=self._drop_job(st, name, job)),
                    [],
                )
                return
            finds = []
            other = sorted(
                rep for j, rep in st.published_by if j == job and rep != name
            )
            if other:
                finds.append(
                    (
                        "GP001",
                        f"{job} result published by both {other[0]} and "
                        f"{name}",
                    )
                )
            yield (
                f"publish:{name}:{job}",
                replace(
                    st,
                    published_by=tuple(
                        sorted(set(st.published_by) | {(job, name)})
                    ),
                    replicas=self._set_job(st, name, job, "published", epoch),
                ),
                finds,
            )
        elif phase == "published":
            # The zombie window: the terminal append is UNGUARDED —
            # fold fencing, not a fence check, must absorb a deposed
            # owner's late terminal.
            journal, durable = self._append(
                st.journal,
                st.durable,
                ("terminal", job, name, epoch, "done"),
                terminal_fsync("done"),
                name,
                st.stale,
            )
            yield (
                f"settle:{name}:{job}",
                replace(
                    st,
                    journal=journal,
                    durable=durable,
                    leases=self._release_lease(st, job, name, epoch),
                    replicas=self._drop_job(st, name, job),
                ),
                [],
            )

    def _gp004(
        self, st: _State, job: str, epoch: int, name: str
    ) -> List[Tuple[str, str]]:
        """A lease append that RE-ISSUES the highest already-journaled
        epoch under a different replica breaks fencing (the fold cannot
        order same-epoch writers). A lower-than-max append is a stale
        straggler the max-fold absorbs; an equal-epoch re-journal by
        the SAME replica is the legitimate adopt path."""
        max_epoch, max_rep = 0, None
        for rec in st.journal:
            if rec[0] == "lease" and rec[1] == job:
                e = rec[3]
                if isinstance(e, int) and e >= max_epoch:
                    max_epoch, max_rep = e, rec[2]
        if (
            max_epoch > 0
            and epoch == max_epoch
            and max_rep is not None
            and max_rep != name
        ):
            return [
                (
                    "GP004",
                    f"lease record for {job} journaled at epoch {epoch} "
                    f"by {name} re-issues the epoch already journaled by "
                    f"{max_rep}: fencing cannot order their writes",
                )
            ]
        return []

    def _lease_journal_transition(
        self, st: _State, name: str, job: str, phase: str, epoch: int
    ) -> Iterator[_Trans]:
        if phase == "claimed" and not self.mut.skip_submit_revalidate:
            # The submit-path stale-fold fence (shipped in
            # serve/daemon.py:submit; this checker's first clean run is
            # what found it missing): between the accepted append and
            # the lease claim the accepter may have stalled while a
            # restarting peer adopted AND settled the job — re-fold
            # before journaling the lease and enqueueing.
            pending, _seq = self._fold(st.journal)
            if revalidate_pending(pending, job, epoch) is None:
                yield (
                    f"claim-release:{name}:{job}",
                    replace(
                        st,
                        leases=self._release_lease(st, job, name, epoch),
                        replicas=self._drop_job(st, name, job),
                    ),
                    [],
                )
                return
        finds = self._gp004(st, job, epoch, name)
        journal, durable = self._append(
            st.journal,
            st.durable,
            ("lease", job, name, epoch, phase == "stealing"),
            True,
            name,
            st.stale,
        )
        base = replace(st, journal=journal, durable=durable)
        if phase == "claimed":
            yield (
                f"journal-lease:{name}:{job}",
                replace(
                    base,
                    replicas=self._set_job(base, name, job, "queued", epoch),
                ),
                finds,
            )
            return
        # Adopt/steal paths revalidate against a FRESH fold after the
        # claim (the shipped stale-fold fence), then act per
        # adoption_action.
        pending, _seq = self._fold(base.journal)
        record = revalidate_pending(pending, job, epoch)
        if record is None:
            yield (
                f"adopt-release:{name}:{job}",
                replace(
                    base,
                    leases=self._release_lease(base, job, name, epoch),
                    replicas=self._drop_job(base, name, job),
                ),
                finds,
            )
        elif adoption_action(record.device_began) == "fail":
            journal2, durable2 = self._append(
                base.journal,
                base.durable,
                ("terminal", job, name, epoch, "failed"),
                terminal_fsync("failed"),
                name,
                base.stale,
            )
            yield (
                f"adopt-fail:{name}:{job}",
                replace(
                    base,
                    journal=journal2,
                    durable=durable2,
                    leases=self._release_lease(base, job, name, epoch),
                    replicas=self._drop_job(base, name, job),
                ),
                finds,
            )
        else:
            yield (
                f"adopt-requeue:{name}:{job}",
                replace(
                    base,
                    replicas=self._set_job(base, name, job, "queued", epoch),
                ),
                finds,
            )

    def _recovery_transitions(
        self, st: _State, name: str, jobs: _Jobs
    ) -> Iterator[_Trans]:
        mine = {job for job, _phase, _epoch in jobs}
        pending, _seq = self._fold(st.journal)
        # Replay-anytime adoption: a restart may fold the journal at any
        # moment, so adoption is gated only by the shipped arbitration.
        for record in pending:
            job = record.job_id
            if job in mine:
                continue
            min_epoch, min_replica = self._min_lease(pending, job)
            action, e = arbitrate_claim(
                self._view(st, job),
                name,
                _NOW,
                _GRACE,
                steal=False,
                min_epoch=min_epoch,
                min_replica=min_replica,
            )
            if action == "deny":
                continue
            leases = (
                st.leases
                if action == "adopt"
                else self._set_lease(st, job, name, e, "live")
            )
            yield (
                f"adopt:{name}:{job}:e{e}",
                replace(
                    st,
                    leases=leases,
                    replicas=self._set_job(st, name, job, "adopting", e),
                ),
                [],
            )
        # Steal scan: candidates from the SHIPPED selector over the
        # shipped expiry predicate.
        grace = 0.0 if self.mut.graceless_steal else _GRACE
        alive_peers = {
            rname
            for rname, ralive, _jobs in st.replicas
            if ralive and rname != name
        }
        expired = set()
        for job, rep, epoch, age in st.leases:
            view = LeaseView(
                job_id=job, replica=rep, epoch=epoch,
                expires_unix=_EXPIRES[age],
            )
            if foreign_expired(view, name, _NOW, grace):
                expired.add(job)
        lease_jobs = {entry[0] for entry in st.leases}
        present: Callable[[str], bool] = lambda job_id: job_id in lease_jobs
        for record in steal_candidates(
            pending, expired, name, alive_peers, present
        ):
            job = record.job_id
            if job in mine:
                continue
            min_epoch, min_replica = self._min_lease(pending, job)
            action, e = arbitrate_claim(
                self._view(st, job),
                name,
                _NOW,
                grace,
                steal=True,
                min_epoch=min_epoch,
                min_replica=min_replica,
            )
            if action != "claim":
                continue
            finds: List[Tuple[str, str]] = []
            entry = self._lease_of(st, job)
            if entry is not None and entry[3] in ("live", "lapsed"):
                finds.append(
                    (
                        "GP005",
                        f"{name} steals {job} from {entry[1]} whose lease "
                        f"is {entry[3]} (not yet expired past grace): "
                        f"owner and stealer can run concurrently",
                    )
                )
            yield (
                f"steal:{name}:{job}:e{e}",
                replace(
                    st,
                    leases=self._set_lease(st, job, name, e, "live"),
                    replicas=self._set_job(st, name, job, "stealing", e),
                ),
                finds,
            )

    def _compact_transition(self, st: _State, name: str) -> Iterator[_Trans]:
        pending, _seq = self._fold(st.journal)
        compacted = tuple(
            self._from_dict(r) for r in compacted_records(pending)
        )
        if compacted == st.journal:
            return
        summary = self._summary(st.journal)
        settled = st.settled_compacted
        for job, info in summary["jobs"].items():
            if info["settled"]:
                settled = _add(settled, job)
        stale = st.stale
        if self.mut.skip_inode_recheck:
            # The bug: concurrent appenders never learn the file was
            # replaced — their fds now point at the unlinked inode.
            for rname, ralive, _jobs in st.replicas:
                if ralive and rname != name:
                    stale = _add(stale, rname)
        yield (
            f"compact:{name}",
            replace(
                st,
                journal=compacted,
                durable=len(compacted),
                settled_compacted=settled,
                stale=stale,
            ),
            [],
        )

    def _crash_transitions(
        self, st: _State, name: str, jobs: _Jobs
    ) -> Iterator[_Trans]:
        """A PROCESS crash: this replica's memory is gone, but the
        journal is untouched — a dead process loses no page cache; the
        OS still writes it. Suffix loss is a HOST crash
        (:meth:`_host_crash_transitions`), which kills everyone."""
        if st.crashes <= 0:
            return
        finds: List[Tuple[str, str]] = []
        for window in sorted({_PHASE_WINDOW[p] for _job, p, _e in jobs}):
            self.crash_windows.add(window)
            if window not in self.registry:
                self.uncovered.add(window)
                finds.append(
                    (
                        "GP006",
                        f"model-reachable crash in window `{window}` has "
                        f"no registered utils/faults.py kill-point: the "
                        f"chaos matrix cannot rehearse it",
                    )
                )
        yield (
            f"crash:{name}",
            replace(
                st,
                crashes=st.crashes - 1,
                stale=_drop(st.stale, name),
                replicas=self._set_alive(st, name, False),
            ),
            finds,
        )

    def _host_crash_transitions(self, st: _State) -> Iterator[_Trans]:
        """A HOST (power) crash: every replica dies at once AND part of
        the non-durable journal tail may be lost. This is the only
        transition that drops records — a surviving peer observing a
        page-cache rollback is not physically realizable, and modeling
        it would report phantom protocol violations.

        Loss branches over every combination of per-JOB prefixes of
        the tail, not append-order prefixes: page-cache writeback need
        not respect the cross-job append interleaving, and per-job
        prefixes are exactly the granularity the fold can distinguish.
        (This also makes the branch set independent of the
        interleaving, which is what licenses the canonical journal
        ordering in :meth:`_canon`.)"""
        if st.crashes <= 0:
            return
        dead = tuple((name, False, ()) for name, _alive, _jobs in st.replicas)
        tail = st.journal[st.durable :]
        per_job: Dict[str, int] = {}
        for rec in tail:
            per_job[str(rec[1])] = per_job.get(str(rec[1]), 0) + 1
        jobs_in_tail = sorted(per_job)
        for keeps in itertools.product(
            *(range(per_job[j] + 1) for j in jobs_in_tail)
        ):
            budget = dict(zip(jobs_in_tail, keeps))
            kept: List[_Rec] = []
            for rec in tail:
                if budget[str(rec[1])] > 0:
                    budget[str(rec[1])] -= 1
                    kept.append(rec)
            journal = st.journal[: st.durable] + tuple(kept)
            label = "crash:host:keep(%s)" % ",".join(
                f"{j}:{k}" for j, k in zip(jobs_in_tail, keeps)
            )
            yield (
                label,
                replace(
                    st,
                    journal=journal,
                    durable=len(journal),
                    crashes=st.crashes - 1,
                    stale=(),
                    replicas=dead,
                ),
                [],
            )

    # --------------------------------------------------------- detectors

    def _check_state(self, st: _State, key: Any) -> None:
        summary = self._summary(st.journal)
        for job, info in summary["jobs"].items():
            effective = sum(1 for t in info["terminals"] if t["effective"])
            if effective >= 2:
                self._record_finding(
                    "GP001",
                    f"{job} reaches {effective} terminal records that all "
                    f"survive fold fencing",
                    key,
                    None,
                )
        mem = self._mem(st)
        journal_ids = {rec[1] for rec in st.journal}
        for job in st.acked:
            if (
                job in mem
                or job in journal_ids
                or job in st.settled_compacted
            ):
                continue
            self._record_finding(
                "GP003",
                f"{job} was acknowledged (202 after the accepted fsync) "
                f"but no journal record, no replica memory and no settled "
                f"outcome remains: nobody will ever settle it",
                key,
                None,
            )

    def _trace(self, key: Any) -> List[str]:
        labels: List[str] = []
        while True:
            parent, label = self._parent[key]
            if label == "":
                break
            labels.append(label)
            key = parent
        labels.reverse()
        return labels

    def _record_finding(
        self, rule_id: str, detail: str, key: Any, label: Optional[str]
    ) -> None:
        dedupe = (rule_id, detail)
        if dedupe in self._findings:
            return
        witness = self._trace(key)
        if label is not None:
            witness.append(label)
        self._findings[dedupe] = Finding(
            rule_id,
            MODEL_PATH,
            0,
            0,
            f"{detail} [witness: {' -> '.join(witness) or '(initial)'}]",
        )
        if rule_id == self.stop_on_rule:
            self._stop = True

    # ------------------------------------------------------- exploration

    def _canon(self, st: _State) -> Any:
        """Symmetry reduction: the minimum serialization over every
        replica renaming x job renaming.

        The journal is additionally put in a canonical ORDER: the
        durable prefix and the non-durable tail are each stable-sorted
        by renamed job id, preserving every per-job subsequence
        (same-job record order — including cross-replica order — is
        untouched).  Records of different jobs commute — the fold
        keys its state by job id, compaction drops whole jobs, and
        :meth:`_host_crash_transitions` branches over per-job tail
        prefixes rather than append-order prefixes — so cross-job
        append interleavings are bisimilar and collapse to one
        representative.  This is the reduction that tames the 2-job
        bound (interleavings otherwise multiply the space
        combinatorially)."""
        cached = self._canon_cache.get(st)
        if cached is not None:
            return cached
        best: Any = None
        sort_key = _journal_sort_key
        for mapping in self._perms:
            get = mapping.get
            renamed = [
                (rec[0], get(rec[1], rec[1]), get(rec[2], rec[2])) + rec[3:]
                for rec in st.journal
            ]
            serialized = (
                tuple(sorted(renamed[: st.durable], key=sort_key)),
                tuple(sorted(renamed[st.durable :], key=sort_key)),
                tuple(
                    sorted(
                        (mapping[j], mapping[r], e, a)
                        for j, r, e, a in st.leases
                    )
                ),
                tuple(
                    sorted(
                        (
                            mapping[n],
                            alive,
                            tuple(
                                sorted(
                                    (mapping[j], p, e) for j, p, e in jobs
                                )
                            ),
                        )
                        for n, alive, jobs in st.replicas
                    )
                ),
                tuple(sorted(mapping[j] for j in st.unsubmitted)),
                tuple(sorted(mapping[j] for j in st.acked)),
                tuple(sorted(mapping[j] for j in st.settled_compacted)),
                tuple(sorted(mapping[j] for j in st.began_ever)),
                tuple(
                    sorted(
                        (mapping[j], mapping[r]) for j, r in st.published_by
                    )
                ),
                tuple(sorted(mapping[r] for r in st.stale)),
                st.crashes,
                st.stalls,
            )
            if best is None or serialized < best:
                best = serialized
        self._canon_cache[st] = best
        return best

    def explore(self) -> None:
        init = _State(
            journal=(),
            durable=0,
            leases=(),
            replicas=tuple((name, True, ()) for name in self.replica_names),
            unsubmitted=tuple(self.job_names),
            acked=(),
            settled_compacted=(),
            began_ever=(),
            published_by=(),
            stale=(),
            crashes=self.bounds["crashes"],
            stalls=self.bounds["stalls"],
        )
        key = self._canon(init)
        self._parent[key] = (key, "")
        seen = {key}
        queue: deque[Tuple[_State, Any]] = deque([(init, key)])
        while queue:
            if self.states >= self.max_states or self._stop:
                return
            st, key = queue.popleft()
            self.states += 1
            self._check_state(st, key)
            for label, nxt, finds in self._transitions(st):
                self.transitions += 1
                nkey = self._canon(nxt)
                if nkey not in seen:
                    seen.add(nkey)
                    self._parent[nkey] = (key, label)
                    queue.append((nxt, nkey))
                for rule_id, detail in finds:
                    self._record_finding(rule_id, detail, key, label)
                if self._stop:
                    return
        self.exhausted = True

    def findings(self) -> List[Finding]:
        return sorted(
            self._findings.values(), key=lambda f: (f.rule_id, f.detail)
        )


def check_protocol(
    replicas: int = 2,
    jobs: int = 2,
    crashes: int = 2,
    stalls: int = 0,
    mutations: Optional[Mutations] = None,
    max_states: int = 2_000_000,
    stop_on_rule: Optional[str] = None,
) -> ProtoReport:
    """Exhaustively explore the protocol under the declared bounds and
    report every invariant violation with a witness trace.
    ``stop_on_rule`` aborts at the first finding of that rule (the
    mutation harness's fast path).

    The default matrix is the declared 2-replica / 2-job / 2-crash
    bound with ``stalls=0``: the stall dimension (lease-clock aging,
    which unlocks expiry, adoption and steal transitions) multiplies
    the product space past a CI budget when combined with two jobs, so
    the shipped gate covers it with a SECOND exhaustive run at
    ``jobs=1, stalls=2`` — together the two runs reach every
    transition type the model has (``ci.sh`` runs both)."""
    explorer = _Explorer(
        replicas,
        jobs,
        crashes,
        stalls,
        mutations or Mutations(),
        max_states,
        stop_on_rule,
    )
    start = time.monotonic()
    explorer.explore()
    return ProtoReport(
        bounds=dict(explorer.bounds),
        states=explorer.states,
        transitions=explorer.transitions,
        elapsed_seconds=time.monotonic() - start,
        exhausted=explorer.exhausted,
        findings=explorer.findings(),
        crash_windows=sorted(explorer.crash_windows),
        uncovered_windows=sorted(explorer.uncovered),
    )


@dataclass(frozen=True)
class Mutation:
    """One planted single-decision bug and the GP rule that must catch
    it.

    ``bounds`` is the smallest ``(replicas, jobs, crashes, stalls)``
    matrix the bug is known to trip in — the harness runs each mutation
    at ITS witness bounds (not one shared matrix) because the bugs need
    different ingredients: a steal bug needs a fully-expired lease (two
    stall notches), the compaction bug needs a second job to append
    concurrently, and neither should pay for the other's state space."""

    name: str
    expected: str
    description: str
    mutations: Mutations
    bounds: Tuple[int, int, int, int] = (2, 1, 2, 2)


#: The checker's own test suite: every entry must trip its expected
#: rule or the harness (and ci.sh) fails.
MUTATIONS: Tuple[Mutation, ...] = (
    Mutation(
        "skip-owner-fence",
        "GP001",
        "begin/publish skip the owner_valid fence: a deposed owner "
        "publishes alongside its stealer",
        Mutations(skip_owner_fence=True),
    ),
    Mutation(
        "epoch-blind-fold",
        "GP001",
        "the fold ignores terminal epochs: a fenced zombie terminal "
        "becomes effective next to the real one",
        Mutations(epoch_blind_fold=True),
    ),
    Mutation(
        "began-blind-fold",
        "GP002",
        "the fold ignores began records: adoption requeues a job whose "
        "device work already began",
        Mutations(began_blind_fold=True),
    ),
    Mutation(
        "skip-submit-revalidate",
        "GP002",
        "submit skips the post-claim revalidation: an accepter that "
        "stalled across a peer's adopt-and-settle re-runs the job",
        Mutations(skip_submit_revalidate=True),
    ),
    Mutation(
        "stale-compact-handle",
        "GP003",
        "compaction skips the inode re-check: a concurrent accepted "
        "append lands in the unlinked inode and the acked job vanishes",
        Mutations(skip_inode_recheck=True),
        bounds=(2, 2, 1, 0),
    ),
    Mutation(
        "skip-min-epoch-guard",
        "GP004",
        "claims ignore the journaled min-epoch: a crash-dropped "
        "terminal lets a different replica re-issue a journaled epoch",
        Mutations(skip_min_epoch=True),
    ),
    Mutation(
        "graceless-steal",
        "GP005",
        "steals use grace 0: an expired-within-grace lease is stolen "
        "while its owner may still be finishing",
        Mutations(graceless_steal=True),
    ),
    Mutation(
        "unregistered-kill-window",
        "GP006",
        "serve.lease.post-claim deleted from the kill-point registry: "
        "a reachable crash window loses chaos coverage",
        Mutations(unregistered_crash_site=True),
        bounds=(2, 1, 1, 0),
    ),
)


@dataclass
class MutationOutcome:
    """One mutation-harness verdict."""

    name: str
    expected: str
    tripped: List[str]
    caught: bool
    states: int
    bounds: Dict[str, int]

    def to_json(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "expected": self.expected,
            "tripped": list(self.tripped),
            "caught": self.caught,
            "states": self.states,
            "bounds": dict(self.bounds),
        }


def run_mutation_harness(
    replicas: Optional[int] = None,
    jobs: Optional[int] = None,
    crashes: Optional[int] = None,
    stalls: Optional[int] = None,
    max_states: int = 2_000_000,
) -> List[MutationOutcome]:
    """Re-run the exploration once per planted bug; each must trip its
    matching GP rule (other rules tripping too is fine — bugs cascade).
    Each run stops at the first expected finding. ``None`` bounds fall
    back to each mutation's declared witness bounds; an explicit value
    overrides that dimension for EVERY mutation (and may legitimately
    report a miss — e.g. a steal bug cannot trip with ``stalls=0``)."""
    outcomes = []
    for mutation in MUTATIONS:
        w_replicas, w_jobs, w_crashes, w_stalls = mutation.bounds
        report = check_protocol(
            replicas=w_replicas if replicas is None else replicas,
            jobs=w_jobs if jobs is None else jobs,
            crashes=w_crashes if crashes is None else crashes,
            stalls=w_stalls if stalls is None else stalls,
            mutations=mutation.mutations,
            max_states=max_states,
            stop_on_rule=mutation.expected,
        )
        tripped = sorted({f.rule_id for f in report.findings})
        outcomes.append(
            MutationOutcome(
                name=mutation.name,
                expected=mutation.expected,
                tripped=tripped,
                caught=mutation.expected in tripped,
                states=report.states,
                bounds=dict(report.bounds),
            )
        )
    return outcomes

"""Abstract-interpretation overflow & exactness prover (``graftcheck ranges``).

The Gramian dtype ladder's exactness claims — bf16×bf16→f32 partials exact
below 2^24 per entry, int8×int8→int32 accumulation exact below 2^31, the
lossless f32→int32 conversion point (``ops/gramian.py:
_maybe_switch_accumulator``) firing before any entry could leave the f32
exact-integer window — were hand-reasoned prose (DESIGN.md §5) that no
check could see. This module proves them per geometry, the way
``graftcheck ir`` proves the ring schedule and ``hostmem`` proves host RAM:

- kernels are traced device-free through the runtime's OWN constructors
  (``check/ir.py``'s specs over ``ShapeDtypeStruct`` + ``AbstractMesh``);
- an abstract interpreter walks the jaxpr with an **interval ×
  exact-in-dtype lattice**: every value is an interval ``[lo, hi]`` plus an
  integrality bit, seeded from the declared input contracts
  (``ops/contracts.py`` — genotypes ∈ [0,2], has-variation ∈ [0,1],
  count-valued join rows, packed wire bytes ∈ [0,255]) and pushed through
  ``dot_general`` (contraction-size multiplication), ``add``/``mul``,
  ``scan`` (outward widening × trip count), ``convert_element_type``, and
  the pack/unpack shift-and-mask ops;
- a parallel **accumulator-delta** component tracks, for values aliasing
  the accumulator operand, the per-entry increment one kernel call can add.
  The ring kernel's ``dynamic_update_slice`` accumulation is refined by a
  disjoint-slice proof: every update slice's column start is
  ``((axis_index + k) mod D) · n_local`` with ``D · n_local`` spanning the
  accumulator and the ``k`` values pairwise distinct mod D (the scan
  induction counter plus the post-loop constant), so each entry is updated
  at most once per ring pass and the per-dispatch increment is ONE dot
  partial, not D of them. Kernels that do not match the pattern keep the
  conservative trips × growth bound.

Rules (``check/rules.py:RANGES_RULES``): GR001 int32 accumulator overflow
for the declared max geometry; GR002 f32 partial past the 2^24 window
before the conversion point; GR003 lossy narrowing cast (inferred range
wider than the destination's exact window); GR004 an uncontracted input
reaching a dot; GR005 the runtime conversion trigger's projection
(``ops/contracts.py:flush_entry_increment`` — the SAME callable the
accumulators feed ``_maybe_switch_accumulator``) smaller than the proven
per-dispatch increment.

Everything is pure tracing + arithmetic: zero device buffers survive an
audit (test-asserted), and ``graftcheck plan`` reuses the same audit per
configuration to report ``gramian_entry_bound`` / ``exactness_headroom_sites``
facts and reject geometries whose accumulation could leave the exact
window.
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass, field, replace
from typing import (
    Any,
    Callable,
    Dict,
    List,
    Optional,
    Sequence,
    Set,
    Tuple,
)

import numpy as np

from spark_examples_tpu.check.ir import _is_var
from spark_examples_tpu.check.rules import Finding
from spark_examples_tpu.ops.contracts import (
    COUNT_ROW,
    DECLARED_MAX_SITES,
    HAS_VARIATION,
    PACKED_BYTE,
    SITE_INDEX,
    RangeContract,
    exact_int_window,
    exactness_headroom_sites,
    flush_entry_increment,
)

_INF = float("inf")


# --------------------------------------------------------------------------
# The lattice: interval × integrality × optional accumulator delta.
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class AbsVal:
    """One abstract value: every concrete element lies in ``[lo, hi]``;
    ``integer`` asserts all elements are integers; ``delta`` (set only for
    values aliasing the designated accumulator) bounds the per-entry
    increment relative to the accumulator's kernel-entry contents;
    ``contracted`` is provenance — False taints everything derived from an
    input with no declared contract (GR004), even where a dtype range
    re-bounds the interval."""

    lo: float
    hi: float
    integer: bool = True
    delta: Optional[Tuple[float, float]] = None
    contracted: bool = True

    @property
    def bounded(self) -> bool:
        return math.isfinite(self.lo) and math.isfinite(self.hi)

    @property
    def magnitude(self) -> float:
        return max(abs(self.lo), abs(self.hi))

    @property
    def point(self) -> Optional[float]:
        return self.lo if self.lo == self.hi else None


TOP = AbsVal(-_INF, _INF, integer=False, contracted=False)


def _hull(a: AbsVal, b: AbsVal) -> AbsVal:
    delta = None
    if a.delta is not None and b.delta is not None:
        delta = (min(a.delta[0], b.delta[0]), max(a.delta[1], b.delta[1]))
    return AbsVal(
        min(a.lo, b.lo),
        max(a.hi, b.hi),
        a.integer and b.integer,
        delta,
    )


def _mul_bound(a: float, b: float) -> float:
    # Concrete values are finite reals, so 0 × anything is 0 even when the
    # other interval endpoint is ±inf.
    if a == 0.0 or b == 0.0:
        return 0.0
    return a * b


def _mul(a: AbsVal, b: AbsVal) -> AbsVal:
    combos = [
        _mul_bound(a.lo, b.lo),
        _mul_bound(a.lo, b.hi),
        _mul_bound(a.hi, b.lo),
        _mul_bound(a.hi, b.hi),
    ]
    return AbsVal(min(combos), max(combos), a.integer and b.integer)


def _add(a: AbsVal, b: AbsVal) -> AbsVal:
    return AbsVal(a.lo + b.lo, a.hi + b.hi, a.integer and b.integer)


def _sub(a: AbsVal, b: AbsVal) -> AbsVal:
    return AbsVal(a.lo - b.hi, a.hi - b.lo, a.integer and b.integer)


def _from_concrete(value: Any) -> AbsVal:
    """Abstract a trace-time constant (a numpy/jax array or scalar)."""
    arr = np.asarray(value)
    if arr.size == 0:
        return AbsVal(0.0, 0.0, True)
    if arr.dtype.kind in ("i", "u", "b"):
        return AbsVal(float(arr.min()), float(arr.max()), True)
    if arr.dtype.kind == "f":
        lo, hi = float(arr.min()), float(arr.max())
        integer = bool(np.all(arr == np.floor(arr)))
        return AbsVal(lo, hi, integer)
    return TOP


def contract_val(contract: Optional[RangeContract]) -> AbsVal:
    if contract is None:
        return TOP
    return AbsVal(float(contract.lo), float(contract.hi), contract.integral)


# --------------------------------------------------------------------------
# Recorded sites (checked after interpretation).
# --------------------------------------------------------------------------


@dataclass
class DotSite:
    """One ``dot_general`` execution site."""

    out: AbsVal
    out_dtype: str
    operands: Tuple[AbsVal, AbsVal]
    operand_dtypes: Tuple[str, str]
    contraction: int
    trips: int
    uncontracted: bool  # an operand interval is unbounded


@dataclass
class ConvertSite:
    src: AbsVal
    src_dtype: str
    dst_dtype: str
    trips: int


@dataclass
class AddEvent:
    """``add`` of a plain value onto an accumulator alias."""

    out_id: int
    t_lo: float
    t_hi: float
    trips: int


@dataclass
class DusEvent:
    """``dynamic_update_slice`` of ``slice(acc) + t`` back into ``acc``."""

    update_id: int
    t_lo: float
    t_hi: float
    trips: int
    #: The execution count of the enclosing RING PASS (the trips multiplier
    #: OUTSIDE the innermost ring scan): the disjointness proof bounds each
    #: entry at one update per pass, so a proven group still multiplies by
    #: this — an outer scan of length T runs T passes.
    passes: int
    #: (modulus, width, base_key, k_values) when the disjoint-slice
    #: pattern was proven; None → conservative accounting.
    pattern: Optional[Tuple[int, int, Tuple[int, ...], Tuple[int, ...]]]


# --------------------------------------------------------------------------
# The interpreter.
# --------------------------------------------------------------------------

#: Layout/movement ops: one data operand in, same values out.
_PASSTHROUGH = {
    "slice",
    "squeeze",
    "reshape",
    "broadcast_in_dim",
    "transpose",
    "expand_dims",
    "rev",
    "copy",
    "optimization_barrier",
    "pbroadcast",
    "ppermute",
    "dynamic_slice",
    "stop_gradient",
    "reduce_precision",
}

_CMP = {"eq", "ne", "lt", "le", "gt", "ge"}


class _Frame:
    """Per-jaxpr interpretation scope: environment, producer map, and the
    binding of this jaxpr's invars to the enclosing frame's vars (for the
    cross-scope peeling the disjointness proof needs)."""

    def __init__(
        self,
        jaxpr: Any,
        parent: Optional["_Frame"],
        binding: Dict[Any, Any],
    ):
        self.jaxpr = jaxpr
        self.parent = parent
        self.binding = binding  # inner invar -> outer var (or None)
        self.env: Dict[Any, AbsVal] = {}
        self.producers: Dict[Any, Any] = {}  # var -> producing eqn
        #: body invars that are scan induction counters: var -> (init, length)
        self.induction: Dict[Any, Tuple[int, int]] = {}

    def read(self, atom: Any) -> AbsVal:
        if not _is_var(atom):  # Literal
            return _from_concrete(atom.val)
        return self.env.get(atom, TOP)

    def write(self, var: Any, val: AbsVal) -> None:
        self.env[var] = val


class Interpreter:
    """Walks a closed jaxpr once, computing an :class:`AbsVal` per var and
    recording the dot/convert/accumulate sites the GR rules inspect."""

    def __init__(self, axis_sizes: Dict[str, int]):
        self.axis_sizes = dict(axis_sizes)
        self.dots: List[DotSite] = []
        self.converts: List[ConvertSite] = []
        self.adds: List[AddEvent] = []
        self.dus: List[DusEvent] = []
        self.unknown_prims: Set[str] = set()
        #: Trips at entry of the innermost enclosing scan (1 at top level)
        #: — the ring-pass count the disjoint-slice group multiplies by.
        self._passes: int = 1

    # ------------------------------------------------------------- plumbing

    def run(self, closed: Any, in_vals: Sequence[AbsVal]) -> List[AbsVal]:
        return self._eval_jaxpr(
            closed.jaxpr,
            [_from_concrete(c) for c in closed.consts],
            list(in_vals),
            parent=None,
            binding={},
            trips=1,
            collect=True,
        )

    def _eval_jaxpr(
        self,
        jaxpr: Any,
        const_vals: Sequence[AbsVal],
        in_vals: Sequence[AbsVal],
        parent: Optional[_Frame],
        binding: Dict[Any, Any],
        trips: int,
        collect: bool,
    ) -> List[AbsVal]:
        frame = _Frame(jaxpr, parent, binding)
        for var, val in zip(jaxpr.constvars, const_vals):
            frame.write(var, val)
        for var, val in zip(jaxpr.invars, in_vals):
            frame.write(var, val)
        for eqn in jaxpr.eqns:
            for ov in eqn.outvars:
                frame.producers[ov] = eqn
            self._eval_eqn(frame, eqn, trips, collect)
        return [frame.read(v) for v in jaxpr.outvars]

    # ------------------------------------------------------------ equations

    def _eval_eqn(self, frame: _Frame, eqn: Any, trips: int, collect: bool) -> None:
        # Taint provenance: anything derived from an uncontracted input
        # stays uncontracted, re-applied after every handler so dtype-range
        # fallbacks cannot launder the missing-contract fact (GR004).
        tainted = any(
            _is_var(v) and not frame.read(v).contracted for v in eqn.invars
        )
        self._dispatch_eqn(frame, eqn, trips, collect)
        if tainted:
            for ov in eqn.outvars:
                if ov in frame.env:
                    frame.write(ov, replace(frame.env[ov], contracted=False))

    def _dispatch_eqn(
        self, frame: _Frame, eqn: Any, trips: int, collect: bool
    ) -> None:
        name = eqn.primitive.name
        handler = getattr(self, f"_prim_{name}", None)
        if handler is not None:
            handler(frame, eqn, trips, collect)
            return
        if name in _PASSTHROUGH:
            val = frame.read(eqn.invars[0])
            for ov in eqn.outvars:
                frame.write(ov, val)
            return
        if name in _CMP:
            a, b = frame.read(eqn.invars[0]), frame.read(eqn.invars[1])
            frame.write(eqn.outvars[0], self._compare(name, a, b))
            return
        if name in ("pjit", "closed_call", "custom_jvp_call", "custom_vjp_call",
                    "remat", "checkpoint"):
            self._descend(frame, eqn, trips, collect)
            return
        if name == "shard_map":
            self._descend(frame, eqn, trips, collect)
            return
        self.unknown_prims.add(name)
        for ov in eqn.outvars:
            frame.write(ov, TOP)

    def _descend(self, frame: _Frame, eqn: Any, trips: int, collect: bool) -> None:
        sub = eqn.params.get("jaxpr")
        if sub is None:
            for ov in eqn.outvars:
                frame.write(ov, TOP)
            return
        if hasattr(sub, "jaxpr"):  # ClosedJaxpr
            inner, consts = sub.jaxpr, [_from_concrete(c) for c in sub.consts]
        else:
            inner, consts = sub, []
        in_vals = [frame.read(v) for v in eqn.invars]
        binding = {
            iv: (ov if _is_var(ov) else None)
            for iv, ov in zip(inner.invars, eqn.invars)
        }
        outs = self._eval_jaxpr(
            inner, consts, in_vals, frame, binding, trips, collect
        )
        for ov, val in zip(eqn.outvars, outs):
            frame.write(ov, val)

    # ------------------------------------------------------ leaf primitives

    def _prim_add(self, frame: _Frame, eqn: Any, trips: int, collect: bool) -> None:
        a, b = frame.read(eqn.invars[0]), frame.read(eqn.invars[1])
        out = _add(a, b)
        # Accumulator delta: acc + plain → delta grows by the plain interval.
        acc, plain = None, None
        if a.delta is not None and b.delta is None:
            acc, plain = a, b
        elif b.delta is not None and a.delta is None:
            acc, plain = b, a
        if acc is not None and plain is not None:
            out = replace(
                out,
                delta=(acc.delta[0] + plain.lo, acc.delta[1] + plain.hi),
            )
            if collect:
                self.adds.append(
                    AddEvent(
                        id(eqn.outvars[0]),
                        min(plain.lo, 0.0),
                        max(plain.hi, 0.0),
                        trips,
                    )
                )
        elif a.delta is not None and b.delta is not None:
            out = replace(out, delta=None)  # acc + acc: no per-entry claim
        frame.write(eqn.outvars[0], out)

    def _prim_sub(self, frame: _Frame, eqn: Any, trips: int, collect: bool) -> None:
        a, b = frame.read(eqn.invars[0]), frame.read(eqn.invars[1])
        frame.write(eqn.outvars[0], _sub(a, b))

    def _prim_mul(self, frame: _Frame, eqn: Any, trips: int, collect: bool) -> None:
        a, b = frame.read(eqn.invars[0]), frame.read(eqn.invars[1])
        frame.write(eqn.outvars[0], _mul(a, b))

    def _prim_neg(self, frame: _Frame, eqn: Any, trips: int, collect: bool) -> None:
        a = frame.read(eqn.invars[0])
        frame.write(eqn.outvars[0], AbsVal(-a.hi, -a.lo, a.integer))

    def _prim_abs(self, frame: _Frame, eqn: Any, trips: int, collect: bool) -> None:
        a = frame.read(eqn.invars[0])
        lo = 0.0 if a.lo <= 0.0 <= a.hi else min(abs(a.lo), abs(a.hi))
        frame.write(eqn.outvars[0], AbsVal(lo, a.magnitude, a.integer))

    def _prim_max(self, frame: _Frame, eqn: Any, trips: int, collect: bool) -> None:
        a, b = frame.read(eqn.invars[0]), frame.read(eqn.invars[1])
        frame.write(
            eqn.outvars[0],
            AbsVal(max(a.lo, b.lo), max(a.hi, b.hi), a.integer and b.integer),
        )

    def _prim_min(self, frame: _Frame, eqn: Any, trips: int, collect: bool) -> None:
        a, b = frame.read(eqn.invars[0]), frame.read(eqn.invars[1])
        frame.write(
            eqn.outvars[0],
            AbsVal(min(a.lo, b.lo), min(a.hi, b.hi), a.integer and b.integer),
        )

    def _prim_rem(self, frame: _Frame, eqn: Any, trips: int, collect: bool) -> None:
        a, b = frame.read(eqn.invars[0]), frame.read(eqn.invars[1])
        if b.bounded and b.lo > 0:
            m = b.hi - 1
            lo = 0.0 if a.lo >= 0 else -m
            frame.write(eqn.outvars[0], AbsVal(lo, m, a.integer and b.integer))
        else:
            frame.write(eqn.outvars[0], TOP)

    def _prim_div(self, frame: _Frame, eqn: Any, trips: int, collect: bool) -> None:
        a, b = frame.read(eqn.invars[0]), frame.read(eqn.invars[1])
        if b.bounded and (b.lo > 0 or b.hi < 0):
            combos = [a.lo / b.lo, a.lo / b.hi, a.hi / b.lo, a.hi / b.hi]
            frame.write(
                eqn.outvars[0], AbsVal(min(combos), max(combos), False)
            )
        else:
            frame.write(eqn.outvars[0], TOP)

    def _prim_and(self, frame: _Frame, eqn: Any, trips: int, collect: bool) -> None:
        a, b = frame.read(eqn.invars[0]), frame.read(eqn.invars[1])
        if a.lo >= 0 and b.lo >= 0:
            frame.write(
                eqn.outvars[0], AbsVal(0.0, min(a.hi, b.hi), True)
            )
        else:
            frame.write(eqn.outvars[0], self._dtype_range(eqn.outvars[0]))

    def _bits_upper(self, hi: float) -> float:
        if not math.isfinite(hi) or hi < 0:
            return _INF
        bits = int(hi).bit_length()
        return float((1 << bits) - 1)

    def _prim_or(self, frame: _Frame, eqn: Any, trips: int, collect: bool) -> None:
        a, b = frame.read(eqn.invars[0]), frame.read(eqn.invars[1])
        if a.lo >= 0 and b.lo >= 0:
            hi = self._bits_upper(max(a.hi, b.hi))
            frame.write(eqn.outvars[0], AbsVal(0.0, hi, True))
        else:
            frame.write(eqn.outvars[0], self._dtype_range(eqn.outvars[0]))

    _prim_xor = _prim_or

    def _prim_not(self, frame: _Frame, eqn: Any, trips: int, collect: bool) -> None:
        frame.write(eqn.outvars[0], self._dtype_range(eqn.outvars[0]))

    def _prim_shift_right_logical(
        self, frame: _Frame, eqn: Any, trips: int, collect: bool
    ) -> None:
        a = frame.read(eqn.invars[0])
        if a.lo >= 0:
            frame.write(eqn.outvars[0], AbsVal(0.0, a.hi, True))
        else:
            frame.write(eqn.outvars[0], self._dtype_range(eqn.outvars[0]))

    _prim_shift_right_arithmetic = _prim_shift_right_logical

    def _prim_shift_left(
        self, frame: _Frame, eqn: Any, trips: int, collect: bool
    ) -> None:
        a, s = frame.read(eqn.invars[0]), frame.read(eqn.invars[1])
        if a.lo >= 0 and s.bounded and s.lo >= 0:
            hi = a.hi * (2.0 ** s.hi)
            out = AbsVal(0.0, hi, True)
            frame.write(eqn.outvars[0], self._clamp_int(out, eqn.outvars[0]))
        else:
            frame.write(eqn.outvars[0], self._dtype_range(eqn.outvars[0]))

    def _prim_select_n(
        self, frame: _Frame, eqn: Any, trips: int, collect: bool
    ) -> None:
        pred = frame.read(eqn.invars[0])
        cases = [frame.read(v) for v in eqn.invars[1:]]
        pt = pred.point
        if pt is not None and 0 <= int(pt) < len(cases):
            out = cases[int(pt)]
        else:
            out = cases[0]
            for c in cases[1:]:
                out = _hull(out, c)
        frame.write(eqn.outvars[0], out)

    def _prim_iota(self, frame: _Frame, eqn: Any, trips: int, collect: bool) -> None:
        shape = eqn.outvars[0].aval.shape
        dim = eqn.params.get("dimension", 0)
        n = shape[dim] if shape else 1
        frame.write(eqn.outvars[0], AbsVal(0.0, float(max(n - 1, 0)), True))

    def _prim_axis_index(
        self, frame: _Frame, eqn: Any, trips: int, collect: bool
    ) -> None:
        axis = eqn.params.get("axis_name")
        if isinstance(axis, (tuple, list)):
            size = 1
            for a in axis:
                size *= self.axis_sizes.get(a, 0)
        else:
            size = self.axis_sizes.get(axis, 0)
        if size > 0:
            frame.write(eqn.outvars[0], AbsVal(0.0, float(size - 1), True))
        else:
            frame.write(eqn.outvars[0], TOP)

    def _prim_gather(
        self, frame: _Frame, eqn: Any, trips: int, collect: bool
    ) -> None:
        # Every gathered element IS an element of the operand, so the
        # operand's interval carries over verbatim; under FILL mode the
        # out-of-bounds fill value joins the hull (the declared fill when
        # present, the dtype range otherwise). The index operand cannot
        # influence VALUES — only which ones — so it contributes nothing
        # to the interval (taint provenance still flows via _eval_eqn).
        a = frame.read(eqn.invars[0])
        out = AbsVal(a.lo, a.hi, a.integer)
        mode = eqn.params.get("mode")
        if mode is not None and "FILL" in str(mode).upper():
            fill = eqn.params.get("fill_value")
            if fill is not None:
                f = float(fill)
                out = _hull(out, AbsVal(f, f, float(f).is_integer()))
            else:
                out = _hull(out, self._dtype_range(eqn.outvars[0]))
        frame.write(eqn.outvars[0], out)

    def _prim_psum(
        self, frame: _Frame, eqn: Any, trips: int, collect: bool
    ) -> None:
        # A psum over named axes is a sum of ``size`` per-device terms,
        # each inside the operand's interval: [size·lo, size·hi].
        axes = eqn.params.get("axes", ())
        size = 1
        for ax in axes:
            if isinstance(ax, int):
                shape = eqn.invars[0].aval.shape
                size *= int(shape[ax]) if ax < len(shape) else 0
            else:
                size *= self.axis_sizes.get(ax, 0)
        for iv, ov in zip(eqn.invars, eqn.outvars):
            a = frame.read(iv)
            if size > 0:
                frame.write(
                    ov,
                    AbsVal(
                        _mul_bound(float(size), a.lo),
                        _mul_bound(float(size), a.hi),
                        a.integer,
                    ),
                )
            else:
                frame.write(ov, TOP)

    def _prim_convert_element_type(
        self, frame: _Frame, eqn: Any, trips: int, collect: bool
    ) -> None:
        a = frame.read(eqn.invars[0])
        src_dtype = str(getattr(eqn.invars[0].aval, "dtype", "?")) if _is_var(
            eqn.invars[0]
        ) else str(np.asarray(eqn.invars[0].val).dtype)
        dst_dtype = str(eqn.outvars[0].aval.dtype)
        if collect:
            self.converts.append(ConvertSite(a, src_dtype, dst_dtype, trips))
        out = AbsVal(a.lo, a.hi, a.integer or _is_int_dtype(dst_dtype), a.delta)
        frame.write(eqn.outvars[0], self._clamp_int(out, eqn.outvars[0]))

    def _prim_dot_general(
        self, frame: _Frame, eqn: Any, trips: int, collect: bool
    ) -> None:
        a, b = frame.read(eqn.invars[0]), frame.read(eqn.invars[1])
        (lhs_contract, _), _ = eqn.params["dimension_numbers"]
        lhs_shape = eqn.invars[0].aval.shape
        k = 1
        for d in lhs_contract:
            k *= int(lhs_shape[d])
        prod = _mul(a, b)
        # Sum of k products each in [prod.lo, prod.hi]:
        out = AbsVal(
            _mul_bound(float(k), prod.lo),
            _mul_bound(float(k), prod.hi),
            prod.integer,
        )
        if collect:
            self.dots.append(
                DotSite(
                    out,
                    str(eqn.outvars[0].aval.dtype),
                    (a, b),
                    (
                        str(eqn.invars[0].aval.dtype)
                        if _is_var(eqn.invars[0])
                        else "literal",
                        str(eqn.invars[1].aval.dtype)
                        if _is_var(eqn.invars[1])
                        else "literal",
                    ),
                    k,
                    trips,
                    uncontracted=not (
                        a.bounded and b.bounded and a.contracted and b.contracted
                    ),
                )
            )
        frame.write(eqn.outvars[0], out)

    def _prim_reduce_sum(
        self, frame: _Frame, eqn: Any, trips: int, collect: bool
    ) -> None:
        a = frame.read(eqn.invars[0])
        shape = eqn.invars[0].aval.shape
        n = 1
        for ax in eqn.params.get("axes", ()):
            n *= int(shape[ax])
        out = AbsVal(_mul_bound(float(n), a.lo), _mul_bound(float(n), a.hi), a.integer)
        frame.write(eqn.outvars[0], self._clamp_int(out, eqn.outvars[0]))

    def _prim_reduce_max(
        self, frame: _Frame, eqn: Any, trips: int, collect: bool
    ) -> None:
        frame.write(eqn.outvars[0], frame.read(eqn.invars[0]))

    _prim_reduce_min = _prim_reduce_max
    _prim_reduce_and = _prim_reduce_max
    _prim_reduce_or = _prim_reduce_max

    def _prim_concatenate(
        self, frame: _Frame, eqn: Any, trips: int, collect: bool
    ) -> None:
        out = frame.read(eqn.invars[0])
        for v in eqn.invars[1:]:
            out = _hull(out, frame.read(v))
        frame.write(eqn.outvars[0], out)

    def _prim_dynamic_update_slice(
        self, frame: _Frame, eqn: Any, trips: int, collect: bool
    ) -> None:
        operand = frame.read(eqn.invars[0])
        update = frame.read(eqn.invars[1])
        out = _hull(operand, update)
        if operand.delta is not None and update.delta is not None:
            out = replace(
                out,
                delta=(
                    min(operand.delta[0], update.delta[0]),
                    max(operand.delta[1], update.delta[1]),
                ),
            )
            if collect:
                t_lo = min(0.0, update.delta[0] - operand.delta[0])
                t_hi = max(0.0, update.delta[1] - operand.delta[1])
                pattern = self._dus_pattern(frame, eqn)
                self.dus.append(
                    DusEvent(
                        id(eqn.invars[1]),
                        t_lo,
                        t_hi,
                        trips,
                        self._passes,
                        pattern,
                    )
                )
        frame.write(eqn.outvars[0], out)

    def _prim_scan(self, frame: _Frame, eqn: Any, trips: int, collect: bool) -> None:
        closed = eqn.params["jaxpr"]
        body = closed.jaxpr if hasattr(closed, "jaxpr") else closed
        consts_vals = (
            [_from_concrete(c) for c in closed.consts]
            if hasattr(closed, "consts")
            else []
        )
        nc = int(eqn.params.get("num_consts", 0))
        nk = int(eqn.params.get("num_carry", 0))
        length = int(eqn.params.get("length", 1))
        in_vals = [frame.read(v) for v in eqn.invars]
        consts, carry, xs = in_vals[:nc], in_vals[nc : nc + nk], in_vals[nc + nk :]
        binding = {
            iv: (ov if _is_var(ov) else None)
            for iv, ov in zip(body.invars, eqn.invars)
        }

        def run_body(carry_vals: List[AbsVal], do_collect: bool, mult: int):
            # Inside this scan's body, one "pass" = one execution of the
            # scan itself — the trips THIS eqn was evaluated with.
            saved_passes, self._passes = self._passes, trips
            try:
                return self._eval_scan_body(
                    body,
                    consts_vals,
                    consts + carry_vals + xs,
                    frame,
                    binding,
                    mult,
                    do_collect,
                    nc,
                    nk,
                    carry,
                    length,
                )
            finally:
                self._passes = saved_passes

        out1 = run_body(list(carry), False, trips)
        new_carry = out1[:nk]
        widened: List[AbsVal] = []
        for init, out in zip(carry, new_carry):
            g_hi = max(0.0, out.hi - init.hi)
            g_lo = min(0.0, out.lo - init.lo)
            d = init.delta
            if d is not None and out.delta is not None:
                d = (
                    d[0] + length * min(0.0, out.delta[0] - d[0]),
                    d[1] + length * max(0.0, out.delta[1] - d[1]),
                )
            elif out.delta is None:
                d = None
            widened.append(
                AbsVal(
                    init.lo + length * g_lo,
                    init.hi + length * g_hi,
                    init.integer and out.integer,
                    d,
                    contracted=init.contracted and out.contracted,
                )
            )
        # Soundness check: one more step from the widened carry must not
        # outgrow the linear-widening assumption; if it does, give up on
        # that carry (TOP) rather than under-approximate.
        out2 = run_body(list(widened), False, trips)
        for i, (w, o) in enumerate(zip(widened, out2[:nk])):
            g_hi = max(0.0, out1[i].hi - carry[i].hi)
            g_lo = min(0.0, out1[i].lo - carry[i].lo)
            if o.hi > w.hi + g_hi + 1e-9 or o.lo < w.lo + g_lo - 1e-9:
                widened[i] = TOP
        # Final, collecting pass: the carry the body sees spans every trip.
        final = run_body(list(widened), collect, trips * length)
        outs = list(widened) + final[nk:]
        for ov, val in zip(eqn.outvars, outs):
            frame.write(ov, val)

    def _eval_scan_body(
        self,
        body: Any,
        consts_vals: Sequence[AbsVal],
        in_vals: Sequence[AbsVal],
        parent: _Frame,
        binding: Dict[Any, Any],
        trips: int,
        collect: bool,
        nc: int,
        nk: int,
        carry_init: Sequence[AbsVal],
        length: int,
    ) -> List[AbsVal]:
        sub = _Frame(body, parent, binding)
        for var, val in zip(body.constvars, consts_vals):
            sub.write(var, val)
        for var, val in zip(body.invars, in_vals):
            sub.write(var, val)
        # Induction counters: a carry whose body output is carry + 1 and
        # whose initial value is a known point — the k of the ring
        # disjointness proof.
        for i in range(nk):
            iv = body.invars[nc + i]
            ov = body.outvars[i]
            init_pt = carry_init[i].point if i < len(carry_init) else None
            if init_pt is None or not _is_var(ov):
                continue
            for eq in body.eqns:
                if ov in eq.outvars and eq.primitive.name == "add":
                    args = eq.invars
                    if len(args) == 2 and (
                        (args[0] is iv and _lit_value(args[1]) == 1)
                        or (args[1] is iv and _lit_value(args[0]) == 1)
                    ):
                        sub.induction[iv] = (int(init_pt), length)
        for eqn in body.eqns:
            for ov in eqn.outvars:
                sub.producers[ov] = eqn
            self._eval_eqn(sub, eqn, trips, collect)
        return [sub.read(v) for v in body.outvars]

    # -------------------------------------------- disjoint-slice peeling

    def _dus_pattern(
        self, frame: _Frame, eqn: Any
    ) -> Optional[Tuple[int, int, Tuple[int, ...], Tuple[int, ...]]]:
        """Prove the accumulate-into-disjoint-slices idiom for one
        ``dynamic_update_slice``: every start index is either a known point
        or ``((base + k) mod D) · width`` with ``D · width`` spanning that
        accumulator dimension; returns ``(modulus, width, base_key,
        k_values)`` or None. ``k`` must be a scan induction counter or a
        constant — the caller checks distinctness across the event group."""
        operand_shape = eqn.invars[0].aval.shape
        update_shape = eqn.invars[1].aval.shape
        starts = eqn.invars[2:]
        mod_info = None
        for dim, start in enumerate(starts):
            val = frame.read(start) if _is_var(start) else _from_concrete(start.val)
            if val.point is not None:
                continue  # fixed offset in this dim
            peeled = self._peel_mod_mul(frame, start)
            if peeled is None:
                return None
            modulus, width, base_key, k_values = peeled
            if width != int(update_shape[dim]):
                return None
            if modulus * width != int(operand_shape[dim]):
                return None
            if mod_info is not None:
                return None  # more than one varying dim: out of scope
            mod_info = (modulus, width, base_key, k_values)
        return mod_info

    def _peel(self, frame: _Frame, var: Any) -> Tuple[_Frame, Any]:
        """Follow transparent producers (pbroadcast/convert/copy/
        optimization_barrier, interval-decided select_n) and cross-frame
        invar bindings to the semantically-defining (frame, var)."""
        seen = 0
        while seen < 64:
            seen += 1
            if not _is_var(var):
                return frame, var
            if var in frame.binding and var not in frame.producers:
                outer = frame.binding[var]
                if outer is None or frame.parent is None:
                    return frame, var
                frame, var = frame.parent, outer
                continue
            eqn = frame.producers.get(var)
            if eqn is None:
                return frame, var
            name = eqn.primitive.name
            if name in ("pbroadcast", "convert_element_type", "copy",
                        "optimization_barrier", "broadcast_in_dim", "squeeze"):
                var = eqn.invars[0]
                continue
            if name == "select_n":
                pred = frame.read(eqn.invars[0]) if _is_var(eqn.invars[0]) else _from_concrete(eqn.invars[0].val)
                pt = pred.point
                if pt is not None and 0 <= int(pt) < len(eqn.invars) - 1:
                    var = eqn.invars[1 + int(pt)]
                    continue
                return frame, var
            return frame, var
        return frame, var

    def _peel_mod_mul(
        self, frame: _Frame, var: Any
    ) -> Optional[Tuple[int, int, Tuple[int, ...], Tuple[int, ...]]]:
        frame, var = self._peel(frame, var)
        eqn = frame.producers.get(var) if _is_var(var) else None
        if eqn is None or eqn.primitive.name != "mul":
            return None
        width = None
        mod_var = None
        for a, b in ((eqn.invars[0], eqn.invars[1]), (eqn.invars[1], eqn.invars[0])):
            bv = frame.read(b) if _is_var(b) else _from_concrete(b.val)
            if bv.point is not None:
                width = int(bv.point)
                mod_var = a
                break
        if width is None or width <= 0 or mod_var is None:
            return None
        mframe, mvar = self._peel(frame, mod_var)
        meqn = mframe.producers.get(mvar) if _is_var(mvar) else None
        if meqn is None:
            return None
        if meqn.primitive.name == "add":
            # The hierarchical ring's TWO-RADIX owner index:
            # ((h + k) mod H) * D + ((d + j) mod D), multiplied by the tile
            # width outside. Same disjointness structure, two loop levels.
            two_radix = self._peel_two_radix(mframe, meqn)
            if two_radix is None:
                return None
            modulus, base_key, k_values = two_radix
            return modulus, width, base_key, k_values
        single = self._peel_rem(mframe, mvar)
        if single is None:
            return None
        modulus, base_ids, k_values = single
        return modulus, width, tuple(sorted(base_ids)), tuple(sorted(k_values))

    def _peel_rem(
        self, frame: _Frame, var: Any
    ) -> Optional[Tuple[int, Set[int], Set[int]]]:
        """Peel one ``(base + k) mod M`` radix: returns ``(modulus,
        base ids, k values)`` or None. The shared recognizer of the
        single-radix (flat ring) and two-radix (hierarchical ring)
        disjointness patterns."""
        f, v = self._peel(frame, var)
        eqn = f.producers.get(v) if _is_var(v) else None
        if eqn is None:
            return None
        modulus = None
        dividend = None
        if eqn.primitive.name == "rem":
            div = (
                f.read(eqn.invars[1])
                if _is_var(eqn.invars[1])
                else _from_concrete(eqn.invars[1].val)
            )
            if div.point is not None:
                modulus, dividend = int(div.point), eqn.invars[0]
        elif eqn.primitive.name == "pjit" and eqn.params.get("name") in (
            "remainder",
            "mod",
            "floormod",
        ):
            div = (
                f.read(eqn.invars[1])
                if _is_var(eqn.invars[1])
                else _from_concrete(eqn.invars[1].val)
            )
            if div.point is not None:
                modulus, dividend = int(div.point), eqn.invars[0]
        if modulus is None or modulus <= 0 or dividend is None:
            return None
        terms = self._peel_add_terms(f, dividend)
        if terms is None or terms[1] is None:
            return None
        return modulus, set(terms[0]), set(terms[1])

    def _peel_two_radix(
        self, frame: _Frame, add_eqn: Any
    ) -> Optional[Tuple[int, Tuple[int, ...], Tuple[int, ...]]]:
        """Prove the hierarchical owner index ``((h + k) mod H) * D +
        ((d + j) mod D)``: a two-level scan's flat owner, pairwise
        distinct over the (k, j) double loop exactly when the per-level
        residues are. Returns ``(H * D, base_key, flat k values)`` with
        the flat values ``(k mod H) * D + (j mod D)`` — distinct iff the
        (k, j) pairs are, so the group check in ``_refined_increment``
        applies unchanged. A collision WITHIN the site (fewer flat values
        than k x j combinations) means one entry is updated twice per
        pass: the proof fails rather than under-counts."""
        for a, b in (
            (add_eqn.invars[0], add_eqn.invars[1]),
            (add_eqn.invars[1], add_eqn.invars[0]),
        ):
            fa, va = self._peel(frame, a)
            ea = fa.producers.get(va) if _is_var(va) else None
            if ea is None or ea.primitive.name != "mul":
                continue
            low_radix = None
            rem_var = None
            for x, y in (
                (ea.invars[0], ea.invars[1]),
                (ea.invars[1], ea.invars[0]),
            ):
                yv = fa.read(y) if _is_var(y) else _from_concrete(y.val)
                if yv.point is not None:
                    low_radix = int(yv.point)
                    rem_var = x
                    break
            if low_radix is None or low_radix <= 0 or rem_var is None:
                continue
            high = self._peel_rem(fa, rem_var)
            low = self._peel_rem(frame, b)
            if high is None or low is None:
                continue
            h_mod, h_base, h_ks = high
            l_mod, l_base, l_ks = low
            if l_mod != low_radix:
                continue
            flat = {
                (kh % h_mod) * l_mod + (kl % l_mod)
                for kh in h_ks
                for kl in l_ks
            }
            if len(flat) != len(h_ks) * len(l_ks):
                return None
            return (
                h_mod * l_mod,
                tuple(sorted(h_base | l_base)),
                tuple(sorted(flat)),
            )
        return None

    def _peel_add_terms(
        self, frame: _Frame, var: Any
    ) -> Optional[Tuple[Set[int], Optional[Set[int]]]]:
        """Decompose an add chain into (base atoms, k values). Exactly one
        varying term (induction counter or literal) is allowed; every other
        term must be loop-invariant (it becomes part of the base key)."""
        base: Set[int] = set()
        k_values: Optional[Set[int]] = None
        stack = [(frame, var)]
        steps = 0
        while stack:
            steps += 1
            if steps > 64:
                return None
            f, v = stack.pop()
            f, v = self._peel(f, v)
            if not _is_var(v):
                val = _from_concrete(v.val)
                if val.point is None:
                    return None
                if k_values is not None:
                    return None
                k_values = {int(val.point)}
                continue
            if v in f.induction:
                init, length = f.induction[v]
                if k_values is not None:
                    return None
                k_values = set(range(init, init + length))
                continue
            eqn = f.producers.get(v)
            if eqn is not None and eqn.primitive.name == "add":
                stack.append((f, eqn.invars[0]))
                stack.append((f, eqn.invars[1]))
                continue
            val = f.read(v)
            if val.point is not None:
                if k_values is not None:
                    # Two constant terms: fold into one k.
                    k_values = {k + int(val.point) for k in k_values}
                else:
                    k_values = {int(val.point)}
                continue
            base.add(id(v))
        return base, k_values

    # ------------------------------------------------------------- helpers

    def _compare(self, name: str, a: AbsVal, b: AbsVal) -> AbsVal:
        ops = {
            "lt": (lambda: a.hi < b.lo, lambda: a.lo >= b.hi),
            "le": (lambda: a.hi <= b.lo, lambda: a.lo > b.hi),
            "gt": (lambda: a.lo > b.hi, lambda: a.hi <= b.lo),
            "ge": (lambda: a.lo >= b.hi, lambda: a.hi < b.lo),
            "eq": (
                lambda: a.point is not None and a.point == b.point,
                lambda: a.hi < b.lo or a.lo > b.hi,
            ),
            "ne": (
                lambda: a.hi < b.lo or a.lo > b.hi,
                lambda: a.point is not None and a.point == b.point,
            ),
        }
        always, never = ops[name]
        if a.bounded and b.bounded:
            if always():
                return AbsVal(1.0, 1.0, True)
            if never():
                return AbsVal(0.0, 0.0, True)
        return AbsVal(0.0, 1.0, True)

    def _dtype_range(self, var: Any) -> AbsVal:
        dtype = getattr(getattr(var, "aval", None), "dtype", None)
        if dtype is None:
            return TOP
        window = exact_int_window(dtype)
        if window is None:
            return TOP
        np_dtype = np.dtype(str(dtype)) if not isinstance(dtype, np.dtype) else dtype
        try:
            if np_dtype.kind == "u" or np_dtype.kind == "b":
                return AbsVal(0.0, float(window), True)
            if np_dtype.kind == "i":
                return AbsVal(float(np.iinfo(np_dtype).min), float(window), True)
        except Exception:
            pass
        return TOP

    def _clamp_int(self, val: AbsVal, var: Any) -> AbsVal:
        """Integer results that could exceed their dtype's range wrap; the
        sound abstraction is the full dtype range (the packed-wire byte sum
        relies on exactly this — 8 disjoint-bit terms wrap-free in uint8 is
        a VALUE property the interval cannot see, so the range widens to
        the dtype and the downstream unpack's `& 1` re-tightens it)."""
        dtype = getattr(getattr(var, "aval", None), "dtype", None)
        if dtype is None or not val.bounded:
            return val
        np_dtype = np.dtype(str(dtype))
        if np_dtype.kind not in ("i", "u"):
            return val
        info = np.iinfo(np_dtype)
        if val.lo < info.min or val.hi > info.max:
            return AbsVal(float(info.min), float(info.max), True, val.delta)
        return val


def _lit_value(atom: Any) -> Optional[int]:
    if _is_var(atom):
        return None
    try:
        val = np.asarray(atom.val)
        if val.size == 1:
            return int(val)
    except Exception:
        return None
    return None


def _is_int_dtype(name: str) -> bool:
    try:
        return np.dtype(name).kind in ("i", "u", "b")
    except TypeError:
        return False


# --------------------------------------------------------------------------
# Kernel specs, the audit, and the report.
# --------------------------------------------------------------------------


@dataclass
class RangeKernelSpec:
    """One kernel × geometry × contract assignment to prove.

    ``build`` returns ``(callable, abstract_args)`` (the same builders the
    IR auditor uses — the runtime's own constructors). ``input_contracts``
    assigns one declared contract per top-level invar (None = uncontracted:
    any dot it reaches is GR004). ``rows_per_flush``/``max_count`` mirror
    what the runtime's ``_flush`` feeds the projection formula;
    ``declared_rows`` is the max geometry (total variant rows) the GR001
    overflow proof covers."""

    name: str
    build: Callable[[], Tuple[Callable[..., Any], Tuple[Any, ...]]]
    input_contracts: Tuple[Optional[RangeContract], ...]
    axis_sizes: Dict[str, int] = field(default_factory=dict)
    #: Which invar is the accumulator (None = the kernel has none: delta
    #: tracking and the GR005 trigger check are skipped).
    acc_invar: Optional[int] = 0
    rows_per_flush: int = 0
    max_count: int = 1
    operand_window_dtype: str = "bfloat16"
    accum_dtype: str = "float32"
    declared_rows: int = DECLARED_MAX_SITES
    projection: Callable[[int, int], int] = flush_entry_increment


@dataclass
class RangeAudit:
    """One kernel's range/exactness audit: findings + machine facts."""

    name: str
    findings: List[Finding] = field(default_factory=list)
    facts: Dict[str, object] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_json(self) -> Dict[str, object]:
        return {
            "kernel": self.name,
            "ok": self.ok,
            "facts": self.facts,
            "findings": [f.to_json() for f in self.findings],
        }


def _emit(audit: RangeAudit, rule_id: str, detail: str) -> None:
    audit.findings.append(Finding(rule_id, audit.name, 0, 0, detail))


def _refined_increment(interp: Interpreter) -> Optional[float]:
    """Per-call per-entry accumulator increment from the recorded events:
    plain adds sum (× trips); dynamic_update_slice groups whose disjoint
    column-slice pattern is proven (same modulus/width/base, k values
    pairwise distinct mod D) count ONE dot partial per group; unproven dus
    events fall back to trips × growth. None = unprovable."""
    consumed = {e.update_id for e in interp.dus}
    total = 0.0
    for add in interp.adds:
        if add.out_id in consumed:
            continue
        if not math.isfinite(add.t_hi):
            return None
        total += add.t_hi * add.trips
    groups: Dict[Tuple[int, int, Tuple[int, ...]], List[DusEvent]] = {}
    loose: List[DusEvent] = []
    for ev in interp.dus:
        if ev.pattern is None:
            loose.append(ev)
        else:
            modulus, width, base_key, _ = ev.pattern
            groups.setdefault((modulus, width, base_key), []).append(ev)
    for (modulus, _w, _b), events in groups.items():
        ks: List[int] = []
        for ev in events:
            assert ev.pattern is not None
            ks.extend(ev.pattern[3])
        residues = [k % modulus for k in ks]
        if len(set(residues)) == len(residues):
            hi = max(ev.t_hi for ev in events)
            if not math.isfinite(hi):
                return None
            # One update per entry per RING PASS. A site's k values
            # enumerate exactly the scan iterations the pattern consumed
            # (one per proven-disjoint slice), so executions / |k values|
            # is the pass count of the scans OUTSIDE the pattern — the
            # enclosing block loop for the flat ring, the top level for
            # the two-radix hierarchical ring (whose k values already
            # span BOTH loop levels; multiplying by the outer scan's
            # trips would double-count its iterations).
            passes = max(
                -(-ev.trips // max(1, len(ev.pattern[3]))) for ev in events
            )
            total += hi * passes
        else:
            loose.extend(events)
    for ev in loose:
        if not math.isfinite(ev.t_hi):
            return None
        total += ev.t_hi * ev.trips
    return total


def audit_range_kernel(
    spec: RangeKernelSpec, traced: Optional[Any] = None
) -> RangeAudit:
    """Trace one kernel (or reuse a caller-supplied ``traced`` ClosedJaxpr
    of the SAME build — how the plan validator shares one trace between
    the IR and range audits) and prove its range/exactness contracts."""
    import jax

    audit = RangeAudit(spec.name)
    if traced is not None:
        closed = traced
    else:
        try:
            with jax.enable_x64(True):
                fn, args = spec.build()
                closed = jax.make_jaxpr(fn)(*args)
        except Exception as e:  # noqa: BLE001 — the trace failure is the finding
            _emit(
                audit,
                "GR000",
                f"kernel failed to trace: {type(e).__name__}: {e}",
            )
            return audit

    in_vals: List[AbsVal] = []
    for i, _ in enumerate(closed.jaxpr.invars):
        contract = (
            spec.input_contracts[i] if i < len(spec.input_contracts) else None
        )
        val = contract_val(contract)
        if spec.acc_invar is not None and i == spec.acc_invar:
            # The accumulator is abstracted as zero with delta (0,0): every
            # claim about it is RELATIVE (the per-call per-entry increment);
            # its absolute magnitude across a run is the geometry arithmetic
            # (GR001/GR005), not the jaxpr's business.
            val = replace(
                val,
                lo=0.0,
                hi=0.0,
                integer=True,
                delta=(0.0, 0.0),
                contracted=True,
            )
        in_vals.append(val)

    interp = Interpreter(spec.axis_sizes)
    outs = interp.run(closed, in_vals)
    if traced is None:
        del closed  # zero live arrays after the audit (test-asserted)

    audit.facts["input_contracts"] = [
        c.name if c is not None else None for c in spec.input_contracts
    ]
    audit.facts["accum_dtype"] = spec.accum_dtype

    # ---- GR004: uncontracted inputs reaching a dot --------------------
    for dot in interp.dots:
        if dot.uncontracted:
            _emit(
                audit,
                "GR004",
                "a dot_general consumes an operand with no declared range "
                "contract (ops/contracts.py) — interval "
                f"[{dot.operands[0].lo}, {dot.operands[0].hi}] × "
                f"[{dot.operands[1].lo}, {dot.operands[1].hi}]; no "
                "exactness claim about this kernel can be made",
            )

    # ---- GR002 / per-dispatch partial windows -------------------------
    accum_window = exact_int_window(spec.accum_dtype) or 0
    operand_window = exact_int_window(spec.operand_window_dtype) or 0
    partial_hi = 0.0
    accum_is_float = not _is_int_dtype(spec.accum_dtype)
    for dot in interp.dots:
        if dot.uncontracted:
            continue
        partial_hi = max(partial_hi, dot.out.magnitude)
        for op in dot.operands:
            if op.integer and op.magnitude > operand_window:
                _emit(
                    audit,
                    "GR002" if accum_is_float else "GR001",
                    f"dot operand interval [{op.lo:g}, {op.hi:g}] exceeds "
                    f"the {spec.operand_window_dtype} exact-integer window "
                    f"({operand_window}) — operands would round before the "
                    "multiply",
                )
        if not dot.out.integer:
            continue
        if dot.out.magnitude > accum_window:
            _emit(
                audit,
                "GR002" if accum_is_float else "GR001",
                f"per-dispatch partial can reach {dot.out.magnitude:g} "
                f"(contraction {dot.contraction} × operand bounds), past "
                f"the {spec.accum_dtype} exact window ({accum_window}) — "
                "exactness is lost BEFORE the conversion point can fire",
            )
    audit.facts["dot_partial_bound"] = partial_hi

    # ---- GR003: lossy narrowing casts ---------------------------------
    for conv in interp.converts:
        if not conv.src.integer:
            continue
        if np.dtype(conv.src_dtype).kind in ("i", "u"):
            info = np.iinfo(np.dtype(conv.src_dtype))
            if conv.src.lo <= info.min and conv.src.hi >= info.max:
                # Full-dtype-range source: pure bit entropy (hash/RNG
                # mixing), carrying no magnitude claim a narrowing could
                # lose — the int→int truncation IS the modular semantics
                # there. A magnitude that matters downstream still reaches
                # the accumulator dot and is bounded (or flagged) by
                # GR001/GR002/GR004.
                continue
        src_window = exact_int_window(conv.src_dtype)
        effective = conv.src.magnitude
        if src_window is not None:
            effective = min(effective, float(src_window))
        dst_window = exact_int_window(conv.dst_dtype)
        if dst_window is not None and effective > dst_window:
            _emit(
                audit,
                "GR003",
                f"convert_element_type {conv.src_dtype}→{conv.dst_dtype} "
                f"with inferred operand magnitude {effective:g} past the "
                f"destination's exact window ({dst_window}) — integer "
                "values would round or wrap",
            )

    # ---- per-dispatch entry increment + GR005 -------------------------
    if spec.acc_invar is not None:
        acc_out_delta = None
        for out in outs:
            if out.delta is not None:
                acc_out_delta = out.delta
                break
        conservative = (
            acc_out_delta[1]
            if acc_out_delta is not None and math.isfinite(acc_out_delta[1])
            else None
        )
        refined = _refined_increment(interp)
        increment = (
            min(x for x in (conservative, refined) if x is not None)
            if (conservative is not None or refined is not None)
            else None
        )
        audit.facts["entry_increment"] = increment
        audit.facts["entry_increment_conservative"] = conservative
        projection = spec.projection(spec.rows_per_flush, spec.max_count)
        audit.facts["flush_projection"] = projection
        if increment is None:
            _emit(
                audit,
                "GR005",
                "the per-dispatch accumulator entry increment is "
                "unprovable from the traced jaxpr (accumulator dataflow "
                "left the tracked forms) — the conversion trigger's "
                "projection cannot be verified conservative",
            )
        elif projection < increment:
            _emit(
                audit,
                "GR005",
                f"the runtime conversion trigger projects {projection} per "
                f"flush (ops/contracts.py:flush_entry_increment with rows="
                f"{spec.rows_per_flush}, max_count={spec.max_count}) but "
                f"the traced kernel can add {increment:g} to one entry "
                "per dispatch — the f32→int32 conversion could fire late",
            )

    # ---- GR001: declared-geometry accumulation ------------------------
    int32_window = exact_int_window(np.int32) or 0
    entry_bound = flush_entry_increment(spec.declared_rows, spec.max_count)
    audit.facts["gramian_entry_bound"] = entry_bound
    audit.facts["declared_rows"] = spec.declared_rows
    audit.facts["exactness_headroom_sites"] = {
        "float32": exactness_headroom_sites(np.float32, spec.max_count),
        "int32": exactness_headroom_sites(np.int32, spec.max_count),
    }
    if entry_bound > int32_window:
        _emit(
            audit,
            "GR001",
            f"declared geometry ({spec.declared_rows} rows × max_count "
            f"{spec.max_count}²) bounds an entry at {entry_bound}, past "
            f"int32's exact window ({int32_window}) — the terminal ladder "
            "rung can overflow; shrink the geometry contract",
        )
    if interp.unknown_prims:
        audit.facts["unhandled_primitives"] = sorted(interp.unknown_prims)
    return audit


# --------------------------------------------------------------------------
# The shipped audit matrix (the REAL kernels, via check/ir.py's builders).
# --------------------------------------------------------------------------

#: Mirrors check/ir.py's mesh matrix.
DEFAULT_MESHES: Tuple[Tuple[int, int], ...] = ((1, 2), (1, 4), (2, 2))


def dense_range_spec(
    data: int, num_samples: int, block_size: int
) -> RangeKernelSpec:
    from spark_examples_tpu.check.ir import dense_kernel_spec

    ir_spec = dense_kernel_spec(data, num_samples, block_size)
    return RangeKernelSpec(
        name=f"ranges:{ir_spec.name}",
        build=ir_spec.build,
        input_contracts=(None, PACKED_BYTE),
        rows_per_flush=data * block_size,
        max_count=HAS_VARIATION.hi,
        operand_window_dtype="bfloat16",
        accum_dtype="float32",
    )


def stacked_range_spec(
    jobs: int, num_samples: int, block_size: int
) -> RangeKernelSpec:
    """The fused batch groups' stacked-jobs update under the packed-byte
    contract. Unlike the dense spec's data axis, the jobs axis lanes are
    INDEPENDENT accumulators that never sum together at finalize (each
    job takes its own slice), so one drain step grows any single entry by
    at most ``block_size`` rows — not ``jobs * block_size``."""
    from spark_examples_tpu.check.ir import stacked_kernel_spec

    ir_spec = stacked_kernel_spec(jobs, num_samples, block_size)
    return RangeKernelSpec(
        name=f"ranges:{ir_spec.name}",
        build=ir_spec.build,
        input_contracts=(None, PACKED_BYTE),
        rows_per_flush=block_size,
        max_count=HAS_VARIATION.hi,
        operand_window_dtype="bfloat16",
        accum_dtype="float32",
    )


def counts_range_spec(
    data: int, num_samples: int, block_size: int
) -> RangeKernelSpec:
    from spark_examples_tpu.check.ir import counts_kernel_spec

    ir_spec = counts_kernel_spec(data, num_samples, block_size)
    return RangeKernelSpec(
        name=f"ranges:{ir_spec.name}",
        build=ir_spec.build,
        input_contracts=(None, COUNT_ROW),
        rows_per_flush=data * block_size,
        max_count=COUNT_ROW.hi,
        operand_window_dtype="bfloat16",
        accum_dtype="float32",
    )


def ring_range_spec(
    data: int,
    samples: int,
    num_samples: int,
    block_size: int,
    pack: bool,
    exact_int: bool,
    counts: bool = False,
) -> RangeKernelSpec:
    """``counts=True`` audits the UNPACKED ring under the count-valued
    contract: same-set-join flushes (entries up to ``COUNT_ROW.hi``) ride
    the unpacked kernel per flush regardless of ``--ring-pack-bits``
    (``ShardedGramianAccumulator._flush``), so the sharded join path's
    exactness needs its own proof — packed-[0,1] operands do not cover it."""
    from spark_examples_tpu.check.ir import ring_kernel_spec
    from spark_examples_tpu.parallel.mesh import DATA_AXIS, SAMPLES_AXIS

    if counts:
        pack = False  # count-valued blocks cannot bit-pack
    ir_spec = ring_kernel_spec(
        data, samples, num_samples, block_size, pack, exact_int=exact_int
    )
    contract = (
        COUNT_ROW if counts else (PACKED_BYTE if pack else HAS_VARIATION)
    )
    flavor = "int8" if exact_int else "bf16"
    return RangeKernelSpec(
        name=(
            f"ranges:{ir_spec.name}"
            f"[{flavor}{',counts' if counts else ''}]"
        ),
        build=ir_spec.build,
        input_contracts=(None, contract),
        axis_sizes={DATA_AXIS: data, SAMPLES_AXIS: samples},
        rows_per_flush=data * block_size,
        max_count=contract.hi if counts else HAS_VARIATION.hi,
        operand_window_dtype="int8" if exact_int else "bfloat16",
        accum_dtype="int32" if exact_int else "float32",
    )


def hier_range_spec(
    hosts: int,
    devices_per_host: int,
    num_samples: int,
    block_size: int,
    pack: bool,
    exact_int: bool,
    data: int = 1,
) -> RangeKernelSpec:
    """The hierarchical two-level ring under the same contracts as the
    flat ring (``graftcheck ranges --topology H,D``). The per-dispatch
    entry increment is refined by the TWO-RADIX disjoint-slice proof
    (``Interpreter._peel_two_radix``): every update slice's owner index is
    ``((h + k) mod H) * D + ((d + j) mod D)`` with the (k, j) pairs
    pairwise distinct across the double loop, so one entry still takes
    exactly ONE dot partial per pass and GR005 holds with the same
    runtime projection the flat ring uses."""
    from spark_examples_tpu.check.ir import hier_kernel_spec
    from spark_examples_tpu.parallel.mesh import (
        DATA_AXIS,
        HOST_AXIS,
        SAMPLES_AXIS,
    )

    ir_spec = hier_kernel_spec(
        data, hosts, devices_per_host, num_samples, block_size, pack,
        exact_int=exact_int,
    )
    contract = PACKED_BYTE if pack else HAS_VARIATION
    flavor = "int8" if exact_int else "bf16"
    return RangeKernelSpec(
        name=f"ranges:{ir_spec.name}[{flavor}]",
        build=ir_spec.build,
        input_contracts=(None, contract),
        axis_sizes={
            DATA_AXIS: data,
            HOST_AXIS: hosts,
            SAMPLES_AXIS: devices_per_host,
        },
        rows_per_flush=data * block_size,
        max_count=HAS_VARIATION.hi,
        operand_window_dtype="int8" if exact_int else "bfloat16",
        accum_dtype="int32" if exact_int else "float32",
    )


def devicegen_range_spec(
    data: int,
    samples: int,
    num_samples: int,
    block_size: int,
    blocks_per_dispatch: int = 2,
    pack: bool = True,
) -> RangeKernelSpec:
    """The fused generate-and-ring-accumulate dispatch
    (``ops/devicegen.py:_ring_update``) under the flat schedule. The
    genotype operands are GENERATED on device — their {0,1} range is not a
    declared input contract but the comparison lattice's own inference
    (``Interpreter``: a compare yields [0, 1] integer), so the dot
    operands arrive contracted without any input declaration and GR005's
    one-partial-per-entry-per-pass proof runs on the same dus pattern as
    the host-fed ring. The scalar invars (row counters, kept-site counts,
    dispatch offsets, valid-site counts) carry the SITE_INDEX contract —
    all are bounded by the declared production geometry."""
    from spark_examples_tpu.check.ir import devicegen_ring_spec
    from spark_examples_tpu.parallel.mesh import DATA_AXIS, SAMPLES_AXIS

    ir_spec = devicegen_ring_spec(
        data, samples, num_samples, block_size, blocks_per_dispatch, pack
    )
    return RangeKernelSpec(
        name=f"ranges:{ir_spec.name}",
        build=ir_spec.build,
        input_contracts=(None, SITE_INDEX, SITE_INDEX, SITE_INDEX, SITE_INDEX),
        axis_sizes={DATA_AXIS: data, SAMPLES_AXIS: samples},
        rows_per_flush=data * blocks_per_dispatch * block_size,
        max_count=HAS_VARIATION.hi,
        operand_window_dtype="int8",
        accum_dtype="int32",
    )


def devicegen_hier_range_spec(
    hosts: int,
    devices_per_host: int,
    num_samples: int,
    block_size: int,
    blocks_per_dispatch: int = 2,
    pack: bool = True,
    data: int = 1,
) -> RangeKernelSpec:
    """The fused generation ring under the hierarchical two-level
    schedule (``graftcheck ranges --topology H,D``): the same two-radix
    owner index ``((h + k) mod H) * D + ((d + j) mod D)`` as the host-fed
    hier kernel (``Interpreter._peel_two_radix``), so one Gramian entry
    still takes exactly ONE dot partial per ring pass (GR005) — the
    devicegen/hier seam is proven, not assumed."""
    from spark_examples_tpu.check.ir import devicegen_hier_spec
    from spark_examples_tpu.parallel.mesh import (
        DATA_AXIS,
        HOST_AXIS,
        SAMPLES_AXIS,
    )

    ir_spec = devicegen_hier_spec(
        data, hosts, devices_per_host, num_samples, block_size,
        blocks_per_dispatch, pack,
    )
    return RangeKernelSpec(
        name=f"ranges:{ir_spec.name}",
        build=ir_spec.build,
        input_contracts=(None, SITE_INDEX, SITE_INDEX, SITE_INDEX, SITE_INDEX),
        axis_sizes={
            DATA_AXIS: data,
            HOST_AXIS: hosts,
            SAMPLES_AXIS: devices_per_host,
        },
        rows_per_flush=data * blocks_per_dispatch * block_size,
        max_count=HAS_VARIATION.hi,
        operand_window_dtype="int8",
        accum_dtype="int32",
    )


def default_specs(
    num_samples: int = 64,
    block_size: int = 8,
    meshes: Sequence[Tuple[int, int]] = DEFAULT_MESHES,
    topologies: Sequence[Tuple[int, int]] = (),
) -> List[RangeKernelSpec]:
    """The shipped matrix: dense + counts per data-axis size, the ring
    kernel over every mesh shape × {packed, unpacked} × {int8, bf16}, the
    count-valued (same-set-join) unpacked ring per mesh shape, and the
    fused device-generation ring per mesh shape. ``topologies`` append the
    hierarchical two-level kernel per declared ``hosts,devices_per_host``
    pair (packed × {int8, bf16}) plus the hier devicegen ring."""
    specs: List[RangeKernelSpec] = []
    for data in sorted({d for d, _ in meshes}):
        specs.append(dense_range_spec(data, num_samples, block_size))
        specs.append(counts_range_spec(data, num_samples, block_size))
    # The fused batch groups' stacked program, same group sizes as the
    # ir matrix.
    for jobs in (2, 4):
        specs.append(stacked_range_spec(jobs, num_samples, block_size))
    for data, samples in meshes:
        if samples < 2:
            continue
        for pack in (True, False):
            for exact_int in (True, False):
                specs.append(
                    ring_range_spec(
                        data, samples, num_samples, block_size, pack, exact_int
                    )
                )
        specs.append(
            ring_range_spec(
                data, samples, num_samples, block_size, False, False,
                counts=True,
            )
        )
        specs.append(
            devicegen_range_spec(data, samples, num_samples, block_size)
        )
    for hosts, per_host in topologies:
        if hosts * per_host < 2:
            continue
        for exact_int in (True, False):
            specs.append(
                hier_range_spec(
                    hosts, per_host, num_samples, block_size, True, exact_int
                )
            )
        specs.append(
            devicegen_hier_range_spec(
                hosts, per_host, num_samples, block_size
            )
        )
    return specs


@dataclass
class RangesReport:
    audits: List[RangeAudit] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return all(a.ok for a in self.audits)

    @property
    def findings(self) -> List[Finding]:
        return [f for a in self.audits for f in a.findings]

    def to_json(self) -> str:
        return json.dumps(
            {
                "tool": "graftcheck-ranges",
                "ok": self.ok,
                "kernel_count": len(self.audits),
                "finding_count": len(self.findings),
                "kernels": [a.to_json() for a in self.audits],
            },
            indent=2,
        )

    def format(self) -> str:
        lines = []
        for a in self.audits:
            if a.ok:
                head = a.facts.get("exactness_headroom_sites", {})
                lines.append(
                    f"  proved: {a.name}: partial ≤ "
                    f"{a.facts.get('dot_partial_bound', 0):g}, entry "
                    f"increment ≤ {a.facts.get('entry_increment', 0):g}"
                    f"/flush (projection "
                    f"{a.facts.get('flush_projection', 0)}), headroom "
                    f"f32 {head.get('float32', 0)} / int32 "
                    f"{head.get('int32', 0)} sites"
                )
            else:
                for f in a.findings:
                    lines.append(f"  {f.format()}")
        verdict = "clean" if self.ok else f"{len(self.findings)} finding(s)"
        lines.append(
            f"graftcheck ranges: {len(self.audits)} kernel(s), {verdict}"
        )
        return "\n".join(lines)


def run_audit(specs: Optional[Sequence[RangeKernelSpec]] = None) -> RangesReport:
    """Audit ``specs`` (default: the shipped matrix). Pure tracing — zero
    device buffers survive the call (test-asserted)."""
    report = RangesReport()
    for spec in specs if specs is not None else default_specs():
        report.audits.append(audit_range_kernel(spec))
    return report


__all__ = [
    "AbsVal",
    "DEFAULT_MESHES",
    "Interpreter",
    "RangeAudit",
    "RangeKernelSpec",
    "RangesReport",
    "TOP",
    "audit_range_kernel",
    "contract_val",
    "counts_range_spec",
    "default_specs",
    "dense_range_spec",
    "devicegen_hier_range_spec",
    "devicegen_range_spec",
    "hier_range_spec",
    "ring_range_spec",
    "stacked_range_spec",
    "run_audit",
]

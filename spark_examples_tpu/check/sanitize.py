"""Sanitizer replay of the native parser (``graftcheck sanitize``).

PR 1 made ``native/vcfparse.cpp`` concurrent: span entry points run
GIL-released on a thread pool over one shared buffer. Its safety claims —
no out-of-bounds writes sizing arrays from the pre-scan, no UB in the
integer/float parsing, no data races between span workers — are exactly
the claims compilers can *instrument*. This module builds the standalone
harness (``utils/native.py:build_sanitizer_harness``) under each requested
sanitizer and replays the deterministic fuzz corpus (``check/corpus.py``)
through it.

Graceful degradation is part of the contract (``ci.sh --sanitize`` must
not fail images without a toolchain): no compiler → the run reports a SKIP
and exits 0, unless ``--strict`` (CI images that are SUPPOSED to have a
compiler pass it so a silently-missing toolchain cannot masquerade as a
green sanitizer gate).
"""

from __future__ import annotations

import os
import subprocess
import tempfile
from typing import Dict, List, Optional, Sequence

from spark_examples_tpu.check.corpus import corpus_documents

#: Per-mode runtime options: deterministic, fail-fast, and quiet enough to
#: read. Leak checking stays off — the harness frees everything it owns,
#: but the one-time C runtime/locale allocations below it are not ours to
#: assert on, and the replay's subject is overflows and races, not leaks.
_SANITIZER_ENV: Dict[str, Dict[str, str]] = {
    "asan": {"ASAN_OPTIONS": "detect_leaks=0:abort_on_error=0:exitcode=99"},
    "ubsan": {"UBSAN_OPTIONS": "print_stacktrace=1"},
    "tsan": {"TSAN_OPTIONS": "halt_on_error=1:exitcode=99"},
}

DEFAULT_MODES = ("asan", "ubsan", "tsan")


def replay_corpus(
    mode: str, corpus: Optional[Sequence[bytes]] = None, timeout: float = 300.0
) -> subprocess.CompletedProcess:
    """Build the ``mode`` harness and replay the corpus through it in one
    subprocess. Raises ``RuntimeError`` when the harness cannot build."""
    from spark_examples_tpu.utils.native import build_sanitizer_harness

    harness = build_sanitizer_harness(mode)
    docs = corpus_documents() if corpus is None else list(corpus)
    with tempfile.TemporaryDirectory(prefix=f"graftcheck-{mode}-") as d:
        paths: List[str] = []
        for i, doc in enumerate(docs):
            path = os.path.join(d, f"corpus-{i:03d}.vcf")
            with open(path, "wb") as f:
                f.write(doc)
            paths.append(path)
        env = dict(os.environ)
        env.update(_SANITIZER_ENV.get(mode, {}))
        return subprocess.run(
            [harness, *paths],
            capture_output=True,
            text=True,
            timeout=timeout,
            env=env,
        )


def run_sanitize(
    modes: Sequence[str] = DEFAULT_MODES, strict: bool = False
) -> int:
    """Replay the corpus under each sanitizer mode; returns the exit code
    for the CLI (0 = clean or skipped, 1 = violations, 2 = infra failure
    under --strict)."""
    from spark_examples_tpu.utils.native import _compiler

    if _compiler() is None:
        message = (
            "graftcheck sanitize: SKIP (no C++ compiler on PATH; the "
            "native layer itself falls back to pure Python on this image)"
        )
        print(message)
        return 2 if strict else 0
    n_docs = len(corpus_documents())
    failures = 0
    for mode in modes:
        try:
            proc = replay_corpus(mode)
        except subprocess.TimeoutExpired as e:
            # A hung harness IS the bug class this stage hunts (e.g. a
            # lock-order deadlock in the span entry points): a per-mode
            # FAIL, never a traceback that aborts the remaining modes.
            failures += 1
            print(
                f"graftcheck sanitize[{mode}]: FAIL (harness hung past "
                f"{e.timeout:.0f}s — deadlock suspected)"
            )
            continue
        except RuntimeError as e:
            # A present compiler that cannot produce this mode (e.g. no
            # tsan runtime) is a per-mode skip, not a failure — unless the
            # operator demanded the full matrix.
            print(f"graftcheck sanitize[{mode}]: SKIP ({e})")
            if strict:
                failures += 1
            continue
        if proc.returncode == 0:
            print(
                f"graftcheck sanitize[{mode}]: OK — {n_docs} corpus "
                "documents replayed clean"
            )
        else:
            failures += 1
            print(
                f"graftcheck sanitize[{mode}]: FAIL "
                f"(exit {proc.returncode})"
            )
            tail = (proc.stderr or proc.stdout or "").strip()
            if tail:
                print(tail[-4000:])
    return 1 if failures else 0


__all__ = ["DEFAULT_MODES", "replay_corpus", "run_sanitize"]

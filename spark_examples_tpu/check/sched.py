"""Device-free collective-schedule proving (``graftcheck sched``).

``graftcheck ir`` proves per-kernel IR contracts (overlap, donation, wire
dtype, total traffic) and ``graftcheck ranges`` proves exactness — both
blind to WHERE the bytes ride. At pod scale that is the whole question:
a v5e-256 has two link classes (ICI within a host, DCN between hosts,
~4x slower and shared per host), and a flat packed ring's ``S - 1``
lockstep steps are each gated on the slowest edge of that step's
permutation. This module is the schedule-level layer on top:

- **topology** — :class:`~spark_examples_tpu.parallel.mesh.Topology`
  declares a pod (``hosts x devices_per_host`` + per-link bandwidths)
  that need not exist: like ``--plan-devices``, it is validated against,
  never queried.
- **schedule extraction** — the communication schedule (every ``ppermute``
  site with its operand bytes, scan trip counts, mesh axis, and
  overlap-with-compute flag) is read off the REAL kernel jaxprs via
  ``check/ir.py``'s trace builders — ``ops/gramian.py:
  build_sharded_update`` (flat) and ``build_hierarchical_update`` (the
  two-level ring), never re-implementations.
- **per-level simulation** — each extracted step is attributed to a link
  class. The hierarchical schedule's split is PROVEN by construction (its
  inner axis is intra-host under the host-major mesh factorization); a
  flat ``ppermute`` over one mesh axis carries no host-boundary structure,
  so on a multi-host topology no byte of it is provably intra-host and the
  sound bound attributes the whole circulation to DCN
  (``parallel/mesh.py:flat_traffic_split``). The simulator then closes
  per-level traffic, step counts, per-device peak liveness, and the
  critical path (overlapped levels run concurrently; an overlap hole
  serializes them).

Rules (``check/rules.py:SCHED_RULES``): GS001 a flat ring SELECTED on a
multi-host topology (its DCN bytes exceed the hierarchical bound); GS002
simulated traffic diverging from the closed-form formulas
(``ring_traffic_bytes`` / ``hierarchical_traffic_bytes``); GS003 a
link-bound step with no concurrent compute; GS004 per-device peak
liveness past the HBM fraction; GS005 a predicted critical path past a
declared ``--sched-budget-seconds``. The full ``graftcheck ir`` audit
(GI001-GI006) runs over the same trace, so the flat-ring contracts hold
under both schedules.

Everything is device-free: the whole topology matrix — including the
32x8 pod — is proven on a laptop with zero live device arrays
(test-asserted), which is the point: the hierarchical reduction was
developed and machine-proven before the pod it targets exists.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from spark_examples_tpu.check.ir import (
    KernelSpec,
    _aval_nbytes,
    _is_dot_eqn,
    _producer_map,
    _ring_bodies,
    _upstream_eqns,
    _walk_eqns,
    audit_kernel,
    devicegen_hier_spec,
    devicegen_ring_spec,
    hier_kernel_spec,
    ring_kernel_spec,
    trace_kernel,
)
from spark_examples_tpu.check.rules import Finding
from spark_examples_tpu.parallel.mesh import (
    HOST_AXIS,
    Topology,
    flat_traffic_split,
    hierarchical_traffic_bytes,
    resolve_reduce_schedule,
    ring_traffic_bytes,
)

#: The shipped topology matrix: single-host shapes (where flat is the
#: right schedule), small multi-host shapes (CI-traceable in seconds), and
#: the v5e-256-class pod (32 hosts x 8 chips) the hierarchical reduction
#: targets — proven on every build, no pod required.
DEFAULT_TOPOLOGIES: Tuple[Tuple[int, int], ...] = (
    (1, 2),
    (1, 4),
    (2, 4),
    (4, 8),
    (32, 8),
)


@dataclass(frozen=True)
class ScheduleStep:
    """One ``ppermute`` site of the extracted schedule: which link class
    its bytes ride, how many times it executes per kernel call (scan trip
    counts multiplied through), the per-device payload, and whether the
    jaxpr proves a concurrent compute dependency-free of it."""

    level: str  # "ici" | "dcn"
    axis: str
    bytes_per_execution: int
    executions: int
    overlapped: bool


@dataclass
class CollectiveSchedule:
    """The communication schedule of one kernel x topology: extracted
    steps plus the geometry needed to scale and price them."""

    schedule: str  # "flat" | "hier"
    topology: Topology
    steps: List[ScheduleStep]
    rows_per_call: int
    n_local: int
    packed: bool
    total_devices: int

    def per_device_bytes(self) -> Dict[str, int]:
        out = {"ici": 0, "dcn": 0}
        for step in self.steps:
            out[step.level] += step.bytes_per_execution * step.executions
        return out

    def mesh_bytes(self) -> Dict[str, int]:
        return {
            level: per_device * self.total_devices
            for level, per_device in self.per_device_bytes().items()
        }

    def step_counts(self) -> Dict[str, int]:
        out = {"ici": 0, "dcn": 0}
        for step in self.steps:
            out[step.level] += step.executions
        return out

    def overlap_holes(self) -> List[ScheduleStep]:
        return [s for s in self.steps if not s.overlapped]

    def link_seconds(self, rows: Optional[int] = None) -> Dict[str, float]:
        """Per-link-class serialized transfer time for ``rows`` variant
        rows (default: one kernel call). ICI is per chip; the DCN NIC is
        shared by the host's chips, so its level serializes the host's
        ``devices_per_host`` tile streams through one link."""
        scale = (
            float(rows) / self.rows_per_call
            if rows is not None and self.rows_per_call
            else 1.0
        )
        per_device = self.per_device_bytes()
        topo = self.topology
        return {
            "ici": per_device["ici"] * scale / topo.ici_bytes_per_s,
            "dcn": (
                per_device["dcn"] * topo.devices_per_host * scale
                / topo.dcn_bytes_per_s
            ),
        }

    def critical_path_seconds(self, rows: Optional[int] = None) -> float:
        """Predicted schedule-limited time: with every link step proven
        overlap-independent of compute (GS003 clean), the two link classes
        also overlap each other (the outer DCN hop hides behind a whole
        inner ring), so the critical path is the slower level; an overlap
        hole serializes the levels instead."""
        seconds = self.link_seconds(rows)
        if self.overlap_holes():
            return seconds["ici"] + seconds["dcn"]
        return max(seconds.values())


def _overlapped_permutes(jaxpr: Any) -> Dict[int, bool]:
    """``id(ppermute eqn) -> proven overlap-independent of every dot in
    its ring body`` — the per-site form of the GI001 analysis."""
    flags: Dict[int, bool] = {}
    for body in _ring_bodies(jaxpr):
        prod = _producer_map(body)
        perm_idx = [
            i for i, e in enumerate(body.eqns)
            if e.primitive.name == "ppermute"
        ]
        dot_idx = [i for i, e in enumerate(body.eqns) if _is_dot_eqn(e)]
        for p in perm_idx:
            p_up = _upstream_eqns(body, p, prod)
            ok = True
            for d in dot_idx:
                d_up = _upstream_eqns(body, d, prod)
                if p in d_up or d in p_up:
                    ok = False
            flags[id(body.eqns[p])] = ok and bool(dot_idx)
    return flags


def _axis_of(eqn: Any) -> str:
    axis = eqn.params.get("axis_name")
    if isinstance(axis, (tuple, list)):
        return str(axis[0]) if len(axis) == 1 else str(tuple(axis))
    return str(axis)


def extract_schedule(
    traced: Any,
    spec: KernelSpec,
    topology: Topology,
    schedule: str,
) -> CollectiveSchedule:
    """Read the communication schedule off one traced kernel.

    Link attribution is the schedule's PROVABLE placement: the
    hierarchical kernel's ``hosts``-axis permutes are DCN and its
    ``samples``-axis permutes are ICI by the host-major mesh
    factorization; a flat kernel's single samples axis spans the whole
    topology, so its permutes are ICI only when the topology has one host
    — on a pod, nothing pins any hop intra-host and every byte is
    attributed to the slow link (the GS001 premise)."""
    jaxpr = traced.jaxpr
    overlap = _overlapped_permutes(jaxpr)
    steps: List[ScheduleStep] = []
    for eqn, mult, _ in _walk_eqns(jaxpr):
        if eqn.primitive.name != "ppermute":
            continue
        axis = _axis_of(eqn)
        if schedule == "hier":
            level = "dcn" if axis == HOST_AXIS else "ici"
        else:
            level = "ici" if topology.hosts == 1 else "dcn"
        steps.append(
            ScheduleStep(
                level=level,
                axis=axis,
                bytes_per_execution=_aval_nbytes(eqn.invars[0].aval),
                executions=mult,
                overlapped=overlap.get(id(eqn), False),
            )
        )
    return CollectiveSchedule(
        schedule=schedule,
        topology=topology,
        steps=steps,
        rows_per_call=spec.rows_per_call,
        n_local=spec.n_local,
        packed=spec.packed,
        total_devices=spec.total_devices,
    )


@dataclass
class ScheduleAudit:
    """One schedule x topology audit: findings + machine-readable facts."""

    name: str
    findings: List[Finding] = field(default_factory=list)
    facts: Dict[str, object] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_json(self) -> Dict[str, object]:
        return {
            "subject": self.name,
            "ok": self.ok,
            "facts": self.facts,
            "findings": [f.to_json() for f in self.findings],
        }


def _emit(audit: ScheduleAudit, rule_id: str, detail: str) -> None:
    audit.findings.append(Finding(rule_id, audit.name, 0, 0, detail))


def schedule_kernel_spec(
    topology: Topology,
    schedule: str,
    num_samples: int,
    block_size: int,
    data: int = 1,
    pack: bool = True,
    exact_int: bool = False,
    kernel: str = "gramian",
    blocks_per_dispatch: int = 2,
) -> KernelSpec:
    """The IR kernel spec for one schedule on one topology — the flat ring
    over a ``data x S`` abstract mesh, or the two-level ring over the
    host-major ``data x hosts x samples`` factorization. ``kernel``
    selects the subject: the host-fed gramian update
    (``ops/gramian.py``) or the fused device-generation ring
    (``ops/devicegen.py:_ring_update``, ``blocks_per_dispatch`` ring
    passes per call). All four are the runtime's own constructors."""
    if kernel == "devicegen":
        if schedule == "hier":
            return devicegen_hier_spec(
                data,
                topology.hosts,
                topology.devices_per_host,
                num_samples,
                block_size,
                blocks_per_dispatch,
                pack,
            )
        return devicegen_ring_spec(
            data,
            topology.devices,
            num_samples,
            block_size,
            blocks_per_dispatch,
            pack,
        )
    if kernel != "gramian":
        raise ValueError(
            f"kernel must be 'gramian' or 'devicegen', got {kernel!r}"
        )
    if schedule == "hier":
        return hier_kernel_spec(
            data,
            topology.hosts,
            topology.devices_per_host,
            num_samples,
            block_size,
            pack,
            exact_int=exact_int,
        )
    return ring_kernel_spec(
        data, topology.devices, num_samples, block_size, pack,
        exact_int=exact_int,
    )


def audit_schedule(
    topology: Topology,
    schedule: str,
    num_samples: int = 64,
    block_size: int = 8,
    data: int = 1,
    pack: bool = True,
    exact_int: bool = False,
    rows: Optional[int] = None,
    budget_seconds: Optional[float] = None,
    selected: bool = True,
    traced: Optional[Any] = None,
    hbm_budget_bytes: Optional[int] = None,
    kernel: str = "gramian",
) -> ScheduleAudit:
    """Trace (or reuse ``traced``), IR-audit, extract, and simulate one
    schedule on one topology; enforce the GS rules.

    ``selected`` marks the schedule the run would actually build (the
    ``--reduce-schedule``/auto resolution): GS001 is a SELECTION rule —
    a flat ring is a fine reference subject on any topology, but choosing
    it for a multi-host run puts the whole circulation on the slow link.
    ``rows`` scales the critical-path prediction (default: one flush);
    ``budget_seconds`` arms GS005."""
    from spark_examples_tpu.ops.gramian import (
        _DEFAULT_DEVICE_BYTES,
        DENSE_HBM_FRACTION,
    )

    spec = schedule_kernel_spec(
        topology, schedule, num_samples, block_size, data, pack, exact_int,
        kernel=kernel,
    )
    audit = ScheduleAudit(
        f"sched[{topology.describe()},{schedule},{spec.name}]"
    )
    audit.facts["topology"] = topology.describe()
    audit.facts["schedule"] = schedule
    audit.facts["kernel"] = kernel
    audit.facts["selected"] = bool(selected)
    if traced is None:
        try:
            traced = trace_kernel(spec)
        except Exception as e:  # noqa: BLE001 — the trace failure is the finding
            _emit(
                audit,
                "GS002",
                f"kernel failed to trace on topology "
                f"{topology.describe()}: {type(e).__name__}: {e} — no "
                "schedule can be extracted, so no traffic/overlap claim "
                "holds",
            )
            return audit

    # The full IR audit over the same trace: the flat-ring contracts
    # (overlap, donation, wire dtype, GI005/GI006 traffic/step counts)
    # hold under BOTH schedules — any GI finding is a sched finding too.
    ir_audit = audit_kernel(spec, traced=traced)
    audit.findings.extend(ir_audit.findings)
    peak_live = int(ir_audit.facts.get("peak_live_bytes", 0))
    audit.facts["peak_live_bytes_per_device"] = peak_live

    sched = extract_schedule(traced, spec, topology, schedule)
    mesh_bytes = sched.mesh_bytes()
    counts = sched.step_counts()
    audit.facts["ici_bytes"] = mesh_bytes["ici"]
    audit.facts["dcn_bytes"] = mesh_bytes["dcn"]
    audit.facts["ici_steps"] = counts["ici"]
    audit.facts["dcn_steps"] = counts["dcn"]
    audit.facts["rows_per_call"] = sched.rows_per_call

    # ---- GS002: simulated schedule vs the closed-form formulas --------
    if schedule == "hier":
        formula = hierarchical_traffic_bytes(
            sched.rows_per_call,
            topology.hosts,
            topology.devices_per_host,
            spec.n_local,
            spec.packed,
        )
        expect = {"ici": formula.ici_bytes, "dcn": formula.dcn_bytes}
    else:
        split = flat_traffic_split(
            sched.rows_per_call, topology, spec.n_local, spec.packed
        )
        expect = {"ici": split.ici_bytes, "dcn": split.dcn_bytes}
    audit.facts["formula_ici_bytes"] = expect["ici"]
    audit.facts["formula_dcn_bytes"] = expect["dcn"]
    for level in ("ici", "dcn"):
        if mesh_bytes[level] != expect[level]:
            _emit(
                audit,
                "GS002",
                f"simulated {level.upper()} traffic is "
                f"{mesh_bytes[level]} bytes/call but the audited formula "
                f"({'hierarchical_traffic_bytes' if schedule == 'hier' else 'ring_traffic_bytes'}) "
                f"says {expect[level]} — the schedule the kernel executes "
                "no longer matches the one telemetry and the plan "
                "validator describe",
            )

    # ---- GS003: overlap holes -----------------------------------------
    for hole in sched.overlap_holes():
        _emit(
            audit,
            "GS003",
            f"a {hole.level.upper()} step over axis {hole.axis!r} "
            f"({hole.bytes_per_execution} B x {hole.executions} "
            "execution(s)) has no concurrent compute proven "
            "dependency-free of it — the link time adds to the critical "
            "path instead of hiding behind the MXU",
        )

    # ---- GS004: per-device liveness -----------------------------------
    hbm_budget = (
        hbm_budget_bytes
        if hbm_budget_bytes is not None
        else int(DENSE_HBM_FRACTION * _DEFAULT_DEVICE_BYTES)
    )
    audit.facts["hbm_budget_bytes"] = hbm_budget
    if peak_live > hbm_budget:
        _emit(
            audit,
            "GS004",
            f"static per-device peak liveness {peak_live} B exceeds the "
            f"HBM budget {hbm_budget} B "
            f"({DENSE_HBM_FRACTION:.0%} of the default device memory) — "
            "the schedule cannot run at this geometry; widen the samples "
            "axis or shrink the block",
        )

    # ---- GS001: flat ring selected on a multi-host topology -----------
    if selected and schedule == "flat" and topology.hosts > 1:
        hier_bound = hierarchical_traffic_bytes(
            sched.rows_per_call,
            topology.hosts,
            topology.devices_per_host,
            spec.n_local,
            spec.packed,
        ).dcn_bytes
        audit.facts["hier_dcn_bound_bytes"] = hier_bound
        if mesh_bytes["dcn"] > hier_bound:
            _emit(
                audit,
                "GS001",
                f"the flat ring on {topology.describe()} puts "
                f"{mesh_bytes['dcn']} bytes/call on the inter-host link "
                f"(no hop is provably intra-host), "
                f"{mesh_bytes['dcn'] / max(1, hier_bound):.1f}x the "
                f"hierarchical schedule's proven {hier_bound} B DCN bound "
                "— use --reduce-schedule hier (or auto) for multi-host "
                "topologies",
            )

    # ---- GS005: declared critical-path budget -------------------------
    sim_rows = rows if rows is not None else sched.rows_per_call
    seconds = sched.link_seconds(sim_rows)
    critical = sched.critical_path_seconds(sim_rows)
    audit.facts["sim_rows"] = int(sim_rows)
    audit.facts["ici_seconds"] = seconds["ici"]
    audit.facts["dcn_seconds"] = seconds["dcn"]
    audit.facts["critical_path_seconds"] = critical
    if budget_seconds is not None and critical > budget_seconds:
        _emit(
            audit,
            "GS005",
            f"predicted schedule-limited critical path "
            f"{critical:.3f} s for {sim_rows} rows on "
            f"{topology.describe()} (ICI {seconds['ici']:.3f} s, DCN "
            f"{seconds['dcn']:.3f} s) exceeds the declared "
            f"--sched-budget-seconds {budget_seconds:g} — the schedule "
            "cannot be proven to fit the budget on this topology",
        )
    return audit


@dataclass
class SchedReport:
    """Every schedule audit of one ``graftcheck sched`` run, grouped per
    topology, with the flat-vs-hier DCN comparison the hierarchical
    schedule exists for."""

    audits: List[ScheduleAudit] = field(default_factory=list)
    comparisons: List[Dict[str, object]] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return all(a.ok for a in self.audits)

    @property
    def findings(self) -> List[Finding]:
        return [f for a in self.audits for f in a.findings]

    def to_json(self) -> str:
        return json.dumps(
            {
                "tool": "graftcheck-sched",
                "ok": self.ok,
                "subject_count": len(self.audits),
                "finding_count": len(self.findings),
                "subjects": [a.to_json() for a in self.audits],
                "comparisons": self.comparisons,
            },
            indent=2,
        )

    def format(self) -> str:
        lines = []
        for a in self.audits:
            if a.ok:
                bits = [
                    f"ici {a.facts.get('ici_bytes', 0)} B/"
                    f"{a.facts.get('ici_steps', 0)} steps",
                    f"dcn {a.facts.get('dcn_bytes', 0)} B/"
                    f"{a.facts.get('dcn_steps', 0)} steps",
                    "== formula",
                    f"critical path {a.facts.get('critical_path_seconds', 0):.2e} s",
                    f"peak live {a.facts.get('peak_live_bytes_per_device', 0)} B",
                ]
                if a.facts.get("selected"):
                    bits.append("selected")
                lines.append(f"  proved: {a.name}: " + ", ".join(bits))
            else:
                for f in a.findings:
                    lines.append(f"  {f.format()}")
        for comp in self.comparisons:
            lines.append(
                f"  compared: {comp['topology']} "
                f"{comp.get('kernel', 'gramian')}: hier DCN "
                f"{comp['hier_dcn_bytes']} B < flat DCN "
                f"{comp['flat_dcn_bytes']} B "
                f"({comp['dcn_reduction']:.1f}x less on the slow link)"
            )
        verdict = "clean" if self.ok else f"{len(self.findings)} finding(s)"
        lines.append(
            f"graftcheck sched: {len(self.audits)} schedule(s), {verdict}"
        )
        return "\n".join(lines)


def run_audit(
    topologies: Optional[Sequence[Tuple[int, int]]] = None,
    num_samples: int = 64,
    block_size: int = 8,
    reduce_schedule: str = "auto",
    budget_seconds: Optional[float] = None,
) -> SchedReport:
    """Prove the schedule matrix: for every topology and BOTH ring
    kernels (the host-fed gramian update and the fused device-generation
    ring — ``ops/devicegen.py`` runs the same two-level schedule since the
    devicegen/hier seam closed), audit the schedule the
    ``--reduce-schedule`` resolution would build (GS001 armed) AND, on
    multi-host topologies, the flat ring as the reference subject
    (facts + GS002/GS003 — its contracts must hold even where it is the
    wrong choice), then record the flat-vs-hier DCN comparison. Pure
    tracing — zero device buffers survive the call (test-asserted)."""
    report = SchedReport()
    pairs = tuple(topologies) if topologies is not None else DEFAULT_TOPOLOGIES
    for hosts, per_host in pairs:
        topo = Topology(hosts, per_host)
        if topo.devices < 2:
            continue
        chosen = resolve_reduce_schedule(reduce_schedule, topo.hosts)
        for kernel in ("gramian", "devicegen"):
            chosen_audit = audit_schedule(
                topo,
                chosen,
                num_samples=num_samples,
                block_size=block_size,
                budget_seconds=budget_seconds,
                selected=True,
                kernel=kernel,
            )
            report.audits.append(chosen_audit)
            if topo.hosts > 1 and chosen == "hier":
                flat_audit = audit_schedule(
                    topo,
                    "flat",
                    num_samples=num_samples,
                    block_size=block_size,
                    selected=False,
                    kernel=kernel,
                )
                report.audits.append(flat_audit)
                flat_dcn = int(flat_audit.facts.get("dcn_bytes", 0))
                hier_dcn = int(chosen_audit.facts.get("dcn_bytes", 0))
                report.comparisons.append(
                    {
                        "topology": topo.describe(),
                        "kernel": kernel,
                        "flat_dcn_bytes": flat_dcn,
                        "hier_dcn_bytes": hier_dcn,
                        "dcn_reduction": (
                            flat_dcn / hier_dcn if hier_dcn else float("inf")
                        ),
                        "hier_strictly_below": hier_dcn < flat_dcn,
                    }
                )
    return report


__all__ = [
    "DEFAULT_TOPOLOGIES",
    "CollectiveSchedule",
    "ScheduleAudit",
    "ScheduleStep",
    "SchedReport",
    "audit_schedule",
    "extract_schedule",
    "run_audit",
    "schedule_kernel_spec",
]

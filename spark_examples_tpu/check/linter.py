"""AST-walking JAX-pitfall linter (the ``graftcheck lint`` engine).

Design: one :class:`_LintVisitor` pass per file, no type inference — every
rule is a syntactic pattern plus *scope* (which package subtree it applies
to, ``rules.py``) plus a small amount of dataflow that stays inside one
function body (names assigned from ``jnp.*`` expressions). The rules are
deliberately tuned to THIS repo's idioms; anything legitimately outside
them carries a ``# graftcheck: disable=ID -- why`` escape hatch, so the
merged tree lints clean and the linter can gate CI (``ci.sh``).

Import-alias resolution makes the patterns robust to import style:
``import jax.numpy as jnp``, ``from jax import numpy as jnp``,
``from jax import jit``, and ``from threading import Lock`` all resolve to
their canonical dotted names before matching.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from spark_examples_tpu.check.rules import (
    RULES,
    Finding,
    apply_disables,
    parse_disables,
)

#: Call roots that convert a device value to host (GC001 sinks).
_HOST_SINKS = ("float", "int", "numpy.asarray", "numpy.array", "numpy.float64")

#: Lock constructors that demand the lock-ordering idiom (GC006). Event is
#: excluded: it is a flag, not a mutual-exclusion primitive, and cannot
#: participate in a lock-ordering deadlock by itself.
_LOCK_CTORS = (
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "threading.Semaphore",
    "threading.BoundedSemaphore",
)

#: How far above a lock construction the ``# lock order:`` comment may sit.
_LOCK_COMMENT_WINDOW = 3

#: Spellings for GC009's finding text (the common augmented operators).
_AUG_OPS = {"Add": "+", "Sub": "-", "Mult": "*", "BitOr": "|"}

#: Canonical dotted names that resolve to shard_map (GC010's second
#: decoration context — a shard_map body executes per device under trace,
#: where a host numpy call is just as wrong as under jit).
_SHARD_MAP_NAMES = (
    "shard_map",
    "jax.experimental.shard_map.shard_map",
    "spark_examples_tpu.utils.compat.shard_map",
)

#: GC011: cast targets narrow enough that the Gramian dtype ladder's
#: integer-exactness can silently break (anything with an exact-integer
#: window below f64's). A cast to one of these in ops/ must carry a
#: `# range:` comment (on the line, or within _RANGE_COMMENT_WINDOW lines
#: above — the `# lock order:` layout) stating why the operand range fits,
#: ideally naming its ops/contracts.py contract.
_NARROW_CAST_TARGETS = frozenset(
    {"int8", "uint8", "int16", "uint16", "int32", "uint32",
     "float16", "bfloat16", "float32"}
)

#: How far above a narrowing cast the `# range:` justification may sit —
#: wider than the lock-order window because the cast often sits mid-way
#: down a multi-line chained expression whose node anchors a few lines in.
_RANGE_COMMENT_WINDOW = 6

#: Canonical dotted names of the explicit cast function (GC011's second
#: spelling besides the .astype method).
_CONVERT_FNS = ("jax.lax.convert_element_type", "lax.convert_element_type")

#: GC012: callables whose result is a file handle. A READ-mode handle in
#: ``sources/``/``pipeline/`` may only live inside the one windowed stream
#: abstraction (``sources/stream.py``) — anywhere else, iterating it or
#: calling ``.read*()`` on it is the raw-ingest shape the hostmem totality
#: proof exists to keep out of the tree.
_FILE_OPEN_FNS = ("open", "io.open", "gzip.open", "bz2.open", "lzma.open")

#: The one module allowed to touch raw read handles (it IS the stream
#: abstraction), exempt from GC012 by construction.
_STREAM_MODULE = "sources/stream.py"

#: The one module allowed to construct journal protocol records (it IS
#: the protocol: its record constructors are the shapes `graftcheck
#: proto` proves the coordination protocol against), exempt from GC013
#: by construction.
_JOURNAL_MODULE = "serve/journal.py"

#: GC013: the protocol event names whose dict-literal construction is
#: reserved to serve/journal.py.
_JOURNAL_EVENTS = ("accepted", "began", "terminal", "lease")

#: numpy calls that are trace-time constants, not host compute: dtype
#: constructors used as astype/array arguments. These run on Python
#: scalars/metadata, never on traced values, and are pervasive legitimate
#: idiom in kernel signatures (``operand_dtype=np.int8``).
_NP_DTYPE_CTORS = frozenset(
    {"numpy.dtype", "numpy.int8", "numpy.int32", "numpy.int64",
     "numpy.uint8", "numpy.uint32", "numpy.uint64", "numpy.float32",
     "numpy.bool_"}
)


def _dotted(node: ast.AST, alias: Dict[str, str]) -> Optional[str]:
    """Canonical dotted name of a Name/Attribute chain, with the leading
    segment resolved through the file's import aliases; ``None`` for
    anything else (subscripts, calls, literals)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    parts.reverse()
    head = alias.get(parts[0], parts[0])
    return ".".join([head] + parts[1:])


def _collect_aliases(tree: ast.Module) -> Dict[str, str]:
    """Map local names to canonical dotted module paths, normalizing the
    numpy/jax spellings the rules match against."""
    alias: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for item in node.names:
                alias[item.asname or item.name.split(".")[0]] = (
                    item.name if item.asname else item.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for item in node.names:
                alias[item.asname or item.name] = f"{node.module}.{item.name}"
    # Canonical spellings for the matchers (jnp/np import styles collapse).
    resolved = {}
    for name, target in alias.items():
        if target == "jax.numpy":
            resolved[name] = "jax.numpy"
        elif target in ("numpy", "np"):
            resolved[name] = "numpy"
        else:
            resolved[name] = target
    return resolved


def _is_jnp_rooted(node: ast.AST, alias: Dict[str, str]) -> bool:
    """Whether an expression's outermost call/attr chain starts at
    ``jax.numpy`` (covers ``jnp.sum(x)``, ``jnp.linalg.eigh(x)``)."""
    if isinstance(node, ast.Call):
        node = node.func
    name = _dotted(node, alias)
    return bool(name and name.startswith("jax.numpy."))


class _JitContext:
    """One jit-decorated function on the stack: its traced (non-static)
    parameter names, for GC002's branch test."""

    def __init__(self, traced_params: Set[str], fn_name: str):
        self.traced_params = traced_params
        self.fn_name = fn_name


def _jit_decoration(
    dec: ast.expr, alias: Dict[str, str]
) -> Optional[Dict[str, ast.expr]]:
    """If ``dec`` applies ``jax.jit``, return its keyword arguments
    (empty dict for the bare form); else ``None``. Recognized forms::

        @jax.jit                      @jit
        @functools.partial(jax.jit, static_argnames=...)
        @partial(jit, donate_argnums=...)
        @jax.jit(static_argnums=...)   (decorator-factory form)
    """
    name = _dotted(dec, alias)
    if name in ("jax.jit", "jax.jit.jit", "jit"):
        return {}
    if isinstance(dec, ast.Call):
        fn_name = _dotted(dec.func, alias)
        kwargs = {k.arg: k.value for k in dec.keywords if k.arg}
        if fn_name in ("jax.jit", "jit"):
            return kwargs
        if fn_name in ("functools.partial", "partial") and dec.args:
            inner = _dotted(dec.args[0], alias)
            if inner in ("jax.jit", "jit"):
                return kwargs
    return None


def _shard_map_decoration(dec: ast.expr, alias: Dict[str, str]) -> bool:
    """Whether ``dec`` applies shard_map (bare, factory, or partial form) —
    the traced-body context GC010 shares with jit."""
    if _dotted(dec, alias) in _SHARD_MAP_NAMES:
        return True
    if isinstance(dec, ast.Call):
        fn_name = _dotted(dec.func, alias)
        if fn_name in _SHARD_MAP_NAMES:
            return True
        if fn_name in ("functools.partial", "partial") and dec.args:
            if _dotted(dec.args[0], alias) in _SHARD_MAP_NAMES:
                return True
    return False


def _static_param_names(
    args: ast.arguments, jit_kwargs: Dict[str, ast.expr]
) -> Set[str]:
    """Resolve static_argnames/static_argnums to parameter names (constant
    specs only — dynamic specs conservatively leave params traced)."""
    posonly = [a.arg for a in getattr(args, "posonlyargs", [])]
    names = posonly + [a.arg for a in args.args]
    static: Set[str] = set()
    spec = jit_kwargs.get("static_argnames")
    if spec is not None:
        for node in ast.walk(spec):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                static.add(node.value)
    spec = jit_kwargs.get("static_argnums")
    if spec is not None:
        for node in ast.walk(spec):
            if isinstance(node, ast.Constant) and isinstance(node.value, int):
                if 0 <= node.value < len(names):
                    static.add(names[node.value])
    return static


class _LintVisitor(ast.NodeVisitor):
    def __init__(
        self,
        relpath: str,
        source_lines: Sequence[str],
        alias: Dict[str, str],
    ):
        self.relpath = relpath
        self.lines = source_lines
        self.alias = alias
        self.findings: List[Finding] = []
        self._loop_depth = 0
        self._func_depth = 0
        self._jit_stack: List[_JitContext] = []
        self._shard_map_depth = 0
        #: Per-function-scope set of names assigned from jnp expressions.
        self._jnp_names: List[Set[str]] = []
        #: Per-scope read-mode file-handle names (GC012); index 0 is the
        #: module scope.
        self._read_handles: List[Set[str]] = [set()]

    # ------------------------------------------------------------- plumbing

    def emit(self, rule_id: str, node: ast.AST, detail: str) -> None:
        rule = RULES[rule_id]
        if not rule.applies_to(self.relpath):
            return
        self.findings.append(
            Finding(
                rule_id,
                self.relpath,
                getattr(node, "lineno", 0),
                getattr(node, "col_offset", 0) + 1,
                detail,
            )
        )

    def _has_lock_order_comment(self, lineno: int) -> bool:
        lo = max(0, lineno - 1 - _LOCK_COMMENT_WINDOW)
        window = self.lines[lo:lineno]
        return any("lock order:" in line for line in window)

    def _has_range_comment(self, lineno: int) -> bool:
        lo = max(0, lineno - 1 - _RANGE_COMMENT_WINDOW)
        window = self.lines[lo:lineno]
        return any(
            "range:" in line or "ops/contracts" in line for line in window
        )

    # ------------------------------------------------------ GC012 (raw file)

    def _read_mode_open(self, node: ast.expr) -> bool:
        """Whether a call opens a file for READING (default mode counts;
        an unresolvable dynamic mode is conservatively read — the stream
        abstraction is where dynamic file plumbing belongs anyway)."""
        if not isinstance(node, ast.Call):
            return False
        if _dotted(node.func, self.alias) not in _FILE_OPEN_FNS:
            return False
        mode = None
        if len(node.args) >= 2:
            mode = node.args[1]
        else:
            for kw in node.keywords:
                if kw.arg == "mode":
                    mode = kw.value
        if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
            return not any(c in mode.value for c in "wax")
        return True

    def _bind_read_handles(self, value: ast.expr, target: ast.expr) -> None:
        if (
            self.relpath != _STREAM_MODULE
            and self._read_mode_open(value)
            and isinstance(target, ast.Name)
        ):
            self._read_handles[-1].add(target.id)

    def _is_raw_handle_iter(self, node: ast.expr) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self._read_handles[-1]
        if isinstance(node, ast.Call) and _dotted(node.func, self.alias) in (
            "enumerate",
            "zip",
            "iter",
            "reversed",
        ):
            return any(self._is_raw_handle_iter(arg) for arg in node.args)
        return False

    # ------------------------------------------------------------ functions

    def _visit_function(self, node) -> None:
        jit_kwargs = None
        for dec in getattr(node, "decorator_list", []):
            jit_kwargs = _jit_decoration(dec, self.alias)
            if jit_kwargs is not None:
                break
        sm_decorated = any(
            _shard_map_decoration(dec, self.alias)
            for dec in getattr(node, "decorator_list", [])
        )
        if sm_decorated:
            self._shard_map_depth += 1
        ctx = None
        if jit_kwargs is not None:
            static = _static_param_names(node.args, jit_kwargs)
            params = {a.arg for a in node.args.args} | {
                a.arg for a in getattr(node.args, "posonlyargs", [])
            }
            ctx = _JitContext(params - static - {"self"}, node.name)
            self._jit_stack.append(ctx)
            self._check_donation(node, jit_kwargs)
        self._func_depth += 1
        self._jnp_names.append(set())
        self._read_handles.append(set())
        # Loops outside don't lexically contain this body's dispatches.
        outer_loop_depth, self._loop_depth = self._loop_depth, 0
        self.generic_visit(node)
        self._loop_depth = outer_loop_depth
        self._read_handles.pop()
        self._jnp_names.pop()
        self._func_depth -= 1
        if ctx is not None:
            self._jit_stack.pop()
        if sm_decorated:
            self._shard_map_depth -= 1

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # A lambda body runs at CALL time: module-level `f = lambda x:
        # jnp.sum(x)` must not trip the import-time rule (GC004).
        self._func_depth += 1
        self._read_handles.append(set())
        self.generic_visit(node)
        self._read_handles.pop()
        self._func_depth -= 1

    def visit_With(self, node: ast.With) -> None:
        for item in node.items:
            if isinstance(item.optional_vars, ast.Name):
                self._bind_read_handles(
                    item.context_expr, item.optional_vars
                )
        self.generic_visit(node)

    visit_AsyncWith = visit_With  # type: ignore[assignment]

    def _check_donation(self, node, jit_kwargs: Dict[str, ast.expr]) -> None:
        """GC005: jitted accumulator-shaped updates must donate (or carry a
        justification disable). Heuristic: the function name says it updates
        state in place (update/accum/flush) and takes at least two params."""
        name = node.name.lower()
        if not any(tag in name for tag in ("update", "accum", "flush")):
            return
        n_params = len(node.args.args) + len(
            getattr(node.args, "posonlyargs", [])
        )
        if n_params < 2:
            return
        if {"donate_argnums", "donate_argnames"} & set(jit_kwargs):
            return
        self.emit(
            "GC005",
            node,
            f"jitted accumulator update {node.name!r} has no "
            "donate_argnums/donate_argnames; donating the accumulator "
            "halves its peak memory (disable with a justification if "
            "non-donation is a measured win)",
        )

    # ---------------------------------------------------------------- loops

    def _visit_loop(self, node) -> None:
        if isinstance(
            node, (ast.For, ast.AsyncFor)
        ) and self._is_raw_handle_iter(node.iter):
            self.emit(
                "GC012",
                node,
                "iterating a raw read-mode file handle outside the stream "
                "abstraction; route the read through sources/stream.py "
                "(iter_text_lines/iter_byte_windows) so the hostmem "
                "totality proof covers it",
            )
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop

    def visit_While(self, node: ast.While) -> None:
        self._check_branch_on_traced(node, "while")
        self._visit_loop(node)

    def visit_If(self, node: ast.If) -> None:
        self._check_branch_on_traced(node, "if")
        self.generic_visit(node)

    # ------------------------------------------------------- GC002 (branch)

    def _check_branch_on_traced(self, node, kind: str) -> None:
        if not self._jit_stack:
            return
        ctx = self._jit_stack[-1]
        test = node.test
        # `x is None` / `x is not None` and isinstance() never call a
        # tracer's __bool__; only value comparisons and bare names do.
        traced = self._traced_names_in_bool_test(test, ctx.traced_params)
        if traced:
            names = ", ".join(sorted(traced))
            self.emit(
                "GC002",
                node,
                f"Python `{kind}` on traced value(s) {names} inside jitted "
                f"{ctx.fn_name!r}; use lax.cond/lax.select/lax.while_loop "
                "or mark the argument static",
            )

    def _traced_names_in_bool_test(
        self, test: ast.expr, traced_params: Set[str]
    ) -> Set[str]:
        """Traced parameter names whose runtime VALUE the test branches on.

        Conservative by construction: identity tests (``is``/``is not``),
        ``isinstance``/callable probes, and attribute accesses (``x.ndim``,
        ``x.shape``) are trace-time Python values, not tracers — only bare
        names, value comparisons, boolean combinations, and negations of
        those convert a tracer to bool.
        """
        if isinstance(test, ast.Name):
            return {test.id} & traced_params
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return self._traced_names_in_bool_test(test.operand, traced_params)
        if isinstance(test, ast.BoolOp):
            out: Set[str] = set()
            for value in test.values:
                out |= self._traced_names_in_bool_test(value, traced_params)
            return out
        if isinstance(test, ast.Compare):
            if any(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
                return set()
            out = set()
            for operand in [test.left, *test.comparators]:
                if isinstance(operand, ast.Name):
                    out |= {operand.id} & traced_params
                elif isinstance(operand, ast.BinOp):
                    for sub in ast.walk(operand):
                        if isinstance(sub, ast.Name):
                            out |= {sub.id} & traced_params
            return out
        return set()

    # ----------------------------------------------------------- assignment

    def visit_Assign(self, node: ast.Assign) -> None:
        if self._jnp_names and _is_jnp_rooted(node.value, self.alias):
            for target in node.targets:
                if isinstance(target, ast.Name):
                    self._jnp_names[-1].add(target.id)
        for target in node.targets:
            self._bind_read_handles(node.value, target)
        self.generic_visit(node)

    # ------------------------------------------------- GC009 (stats bypass)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        """GC009: ``x.y += n`` where ``x`` is a stats/counters object —
        the mutation bypasses the owner's lock/registry-backed methods.
        Matched on the holder's name (any dotted segment named ``stats``/
        ``counters`` or suffixed ``_stats``/``_counters``), so the rule
        follows the objects wherever they are threaded."""
        target = node.target
        if isinstance(target, ast.Attribute):
            base = _dotted(target.value, self.alias)
            if base is not None and any(
                seg in ("stats", "counters")
                or seg.endswith("_stats")
                or seg.endswith("_counters")
                for seg in base.split(".")
            ):
                self.emit(
                    "GC009",
                    node,
                    f"direct `{base}.{target.attr} {_AUG_OPS.get(type(node.op).__name__, 'op')}= ...` "
                    "bypasses the stats object's accounting methods (lock "
                    "+ metrics registry); use its add_*() method so the "
                    "count is thread-safe and lands in the run manifest",
                )
        self.generic_visit(node)

    # ------------------------------------------- GC013 (journal records)

    def visit_Dict(self, node: ast.Dict) -> None:
        """GC013: a journal protocol record built as a dict literal
        outside serve/journal.py — matched on the shape itself (an
        ``"event"`` key naming a protocol event), so the rule catches a
        hand-rolled record whatever it is assigned to or passed into."""
        if self.relpath != _JOURNAL_MODULE:
            for key, value in zip(node.keys, node.values):
                if (
                    isinstance(key, ast.Constant)
                    and key.value == "event"
                    and isinstance(value, ast.Constant)
                    and value.value in _JOURNAL_EVENTS
                ):
                    self.emit(
                        "GC013",
                        node,
                        f"journal {value.value!r} record constructed as a "
                        "dict literal outside serve/journal.py; use "
                        f"journal.{value.value}_record(...) (or the "
                        "JobJournal method) so the record shape stays one "
                        "`graftcheck proto` has proven",
                    )
                    break
        self.generic_visit(node)

    # ----------------------------------------------------------------- call

    def visit_Call(self, node: ast.Call) -> None:
        name = _dotted(node.func, self.alias)

        # GC003: jit construction inside a loop body.
        if self._loop_depth > 0:
            jit_built = name in ("jax.jit", "jit") or (
                name in ("functools.partial", "partial")
                and node.args
                and _dotted(node.args[0], self.alias) in ("jax.jit", "jit")
            )
            if jit_built:
                self.emit(
                    "GC003",
                    node,
                    "jax.jit constructed inside a loop — every iteration "
                    "pays a cache lookup on a fresh callable (recompile "
                    "storm); hoist the jit out of the loop",
                )

        # GC004: jnp at import time (module/class body, not inside a def).
        if self._func_depth == 0 and name and name.startswith("jax.numpy."):
            self.emit(
                "GC004",
                node,
                f"{name.replace('jax.numpy', 'jnp')}(...) executed at import "
                "time initializes the JAX backend as an import side effect; "
                "move into a function or use numpy",
            )

        # GC006: bare lock construction in ingest code.
        if name in _LOCK_CTORS and not self._has_lock_order_comment(
            node.lineno
        ):
            self.emit(
                "GC006",
                node,
                f"{name}() in ingest code without the lock-ordering idiom; "
                "add a `# lock order: ...` comment on or just above this "
                "line stating what may be held when taking it",
            )

        # GC007: per-iteration device sync.
        if self._loop_depth > 0:
            syncs = name == "jax.block_until_ready" or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "block_until_ready"
            )
            if syncs:
                self.emit(
                    "GC007",
                    node,
                    "block_until_ready inside a loop serializes dispatch "
                    "against compute; sync once after the loop or bound "
                    "the in-flight window",
                )

        # GC008: trace-time print under jit.
        if self._jit_stack and name == "print":
            self.emit(
                "GC008",
                node,
                f"print() inside jitted {self._jit_stack[-1].fn_name!r} "
                "runs at trace time with tracers; use jax.debug.print",
            )

        # GC010: host numpy call inside a traced kernel body.
        if (
            (self._jit_stack or self._shard_map_depth)
            and name
            and name.startswith("numpy.")
            and name not in _NP_DTYPE_CTORS
        ):
            where = (
                f"jitted {self._jit_stack[-1].fn_name!r}"
                if self._jit_stack
                else "a shard_map-decorated kernel"
            )
            self.emit(
                "GC010",
                node,
                f"{name.replace('numpy', 'np')}(...) inside {where} runs "
                "on the HOST at trace time: it crashes on tracers or "
                "silently bakes a trace-time constant into the compiled "
                "program; use the jnp equivalent",
            )

        # GC012: .read*() on a raw read-mode handle outside stream.py.
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in ("read", "read1", "readline", "readlines")
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in self._read_handles[-1]
        ):
            self.emit(
                "GC012",
                node,
                f"`{node.func.value.id}.{node.func.attr}()` on a raw "
                "read-mode file handle outside the stream abstraction; "
                "route the read through sources/stream.py "
                "(open_binary/iter_byte_windows) so the hostmem totality "
                "proof covers it",
            )

        # GC013: a journal appender's private _append outside journal.py
        # (the public record methods are the protocol surface; _append
        # would smuggle an arbitrary record past the proven shapes).
        if (
            self.relpath != _JOURNAL_MODULE
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "_append"
            and "journal" in (_dotted(node.func.value, self.alias) or "").lower()
        ):
            self.emit(
                "GC013",
                node,
                "journal._append() called outside serve/journal.py — the "
                "appender's private seam bypasses the record constructors "
                "`graftcheck proto` proves the protocol against; use the "
                "JobJournal record methods",
            )

        # GC011: narrowing cast without a range justification.
        self._check_narrowing_cast(node, name)

        # GC001: implicit device→host sync in hot paths.
        self._check_host_sink(node, name)

        # .item() on anything in a hot path is a per-call sync.
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "item"
            and not node.args
            and not node.keywords
        ):
            self.emit(
                "GC001",
                node,
                ".item() forces a device→host sync per call in hot-path "
                "code; batch values and fetch once (see "
                "parallel/mesh.py:packed_host_fetch)",
            )

        self.generic_visit(node)

    def _check_narrowing_cast(
        self, node: ast.Call, name: Optional[str]
    ) -> None:
        """GC011: ``.astype(<narrow dtype>)`` / ``lax.convert_element_type``
        in ops/ must carry a ``# range:`` justification (or an
        ``ops/contracts`` reference) within the comment window — the
        operand-range claim behind a narrowing cast belongs next to the
        cast, where ``graftcheck ranges`` (check/ranges.py) can hold the
        prose against the proven interval. Dynamic targets (a dtype held in
        a variable, e.g. ``operand_dtype``) are skipped: their range story
        lives at the variable's producer."""
        target = None
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "astype"
            and len(node.args) == 1
            and not node.keywords
        ):
            target = node.args[0]
        elif name in _CONVERT_FNS:
            if len(node.args) >= 2:
                target = node.args[1]
            else:
                for kw in node.keywords:
                    if kw.arg == "new_dtype":
                        target = kw.value
        if target is None:
            return
        dotted = _dotted(target, self.alias)
        if dotted is None:
            return  # dtype variable / np.dtype(...) call — producer's story
        leaf = dotted.rsplit(".", 1)[-1]
        if leaf not in _NARROW_CAST_TARGETS:
            return
        if self._has_range_comment(node.lineno):
            return
        self.emit(
            "GC011",
            node,
            f"narrowing cast to {leaf} without a range justification; add "
            "a `# range: ...` comment (or reference the operand's "
            "ops/contracts.py contract) stating why every value fits the "
            "destination's exact window",
        )

    def _check_host_sink(self, node: ast.Call, name: Optional[str]) -> None:
        if name not in _HOST_SINKS or len(node.args) != 1:
            return
        arg = node.args[0]
        jnp_value = _is_jnp_rooted(arg, self.alias) or (
            isinstance(arg, ast.Name)
            and any(arg.id in scope for scope in self._jnp_names)
        )
        if jnp_value:
            self.emit(
                "GC001",
                node,
                f"{name}() on a jnp value forces an implicit device→host "
                "sync in hot-path code; keep the value on device or batch "
                "the fetch (parallel/mesh.py:packed_host_fetch)",
            )


def lint_source(
    source: str, relpath: str, honor_disables: bool = True
) -> List[Finding]:
    """Lint one file's text; ``relpath`` (package-relative, '/'-separated)
    drives rule scoping. Returns findings sorted by (line, rule)."""
    tree = ast.parse(source, filename=relpath)
    alias = _collect_aliases(tree)
    visitor = _LintVisitor(relpath, source.splitlines(), alias)
    visitor.visit(tree)
    findings = visitor.findings
    if honor_disables:
        per_line, whole_file = parse_disables(source)
        findings = apply_disables(findings, per_line, whole_file)
    return sorted(findings, key=lambda f: (f.line, f.rule_id, f.col))


def _package_relpath(path: str) -> str:
    """Scope-resolvable relpath of one file: relative to the topmost
    enclosing package root (the highest ancestor chain of directories that
    all carry ``__init__.py``), so ``graftcheck lint <pkg>/ops/gramian.py``
    sees the same ``ops/gramian.py`` relpath — and therefore the same
    scoped rules — as a whole-tree lint."""
    path = os.path.abspath(path)
    top = cur = os.path.dirname(path)
    while os.path.exists(os.path.join(cur, "__init__.py")):
        top = cur  # the highest dir that is itself a package
        parent = os.path.dirname(cur)
        if parent == cur:
            break
        cur = parent
    return os.path.relpath(path, top).replace(os.sep, "/")


def _iter_py_files(root: str) -> Iterable[Tuple[str, str]]:
    """Yield ``(abs_path, relpath)`` for package .py files under ``root``
    (or the single file itself), skipping caches."""
    if os.path.isfile(root):
        yield root, _package_relpath(root)
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [
            d for d in dirnames if d not in ("__pycache__", ".git")
        ]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                full = os.path.join(dirpath, fn)
                yield full, os.path.relpath(full, root).replace(os.sep, "/")


def lint_paths(paths: Sequence[str]) -> Tuple[List[Finding], int]:
    """Lint files/trees; returns ``(findings, files_checked)``."""
    findings: List[Finding] = []
    checked = 0
    for root in paths:
        for full, relpath in _iter_py_files(root):
            with open(full, "r", encoding="utf-8") as f:
                source = f.read()
            try:
                findings.extend(lint_source(source, relpath))
            except SyntaxError as e:
                findings.append(
                    Finding(
                        "GC000",
                        relpath,
                        e.lineno or 0,
                        (e.offset or 0),
                        f"syntax error: {e.msg}",
                    )
                )
            checked += 1
    return findings, checked


def json_report(findings: Sequence[Finding], checked: int) -> str:
    """Machine-readable report (one stable schema for CI tooling)."""
    return json.dumps(
        {
            "tool": "graftcheck",
            "checked_files": checked,
            "finding_count": len(findings),
            "findings": [f.to_json() for f in findings],
        },
        indent=2,
    )


__all__ = ["lint_source", "lint_paths", "json_report"]

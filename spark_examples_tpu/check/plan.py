"""Device-free pipeline plan validation (``graftcheck plan``).

A whole-genome run is hours of wall-clock; a partition/mesh/dtype config
error that only surfaces at the finalize reduce (or at the first sharded
flush) wastes all of it. This module dry-runs a full flag configuration
*statically*:

- flag grammar and cross-flag contracts are parsed through the REAL parser
  (``config.build_pca_parser`` / ``PcaConf._from_namespace`` — never a
  drifted copy);
- mesh/partition geometry is checked arithmetically against a *declared*
  device count (``--plan-devices``), so the validator runs on a dev box
  with zero accelerators;
- the actual jitted Gramian update kernels are traced with
  ``jax.eval_shape`` over ``ShapeDtypeStruct`` operands — and, for the
  sharded strategy, through ``shard_map`` over an ``AbstractMesh`` — so
  ingest-block → accumulator shape/dtype agreement is proven by the same
  code that will run, without touching a device or allocating a byte.

The population-genetics analyses (``analyses/``: GRM/kinship, windowed LD
pruning, association scan) validate through the same machinery —
``graftcheck plan --analysis grm|ld|assoc <flags>`` parses the REAL
per-verb parser (``config.build_grm_parser`` etc.), mirrors the runtime
admission gate (``analyses/base.py:analysis_conf_violations`` — one
catalogue, zero drift), and eval_shapes the real per-site kernels
(``ops/ld.py``), so a doomed GRM/LD/assoc configuration is an exit-2
reject before any ingest, exactly like a doomed PCA one.

Exit contract (``check/cli.py``): 0 = plan OK (warnings allowed),
2 = plan rejected with at least one error.
"""

from __future__ import annotations

import argparse
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from spark_examples_tpu.config import (
    AssocConf,
    GrmConf,
    LdConf,
    PcaConf,
    build_assoc_parser,
    build_grm_parser,
    build_ld_parser,
    build_pca_parser,
)

#: The validated flag surfaces: one entry per CLI verb, each the REAL
#: parser/conf pair the verb itself parses — never a drifted copy.
ANALYSIS_SURFACES = {
    "pca": (build_pca_parser, PcaConf),
    "grm": (build_grm_parser, GrmConf),
    "ld": (build_ld_parser, LdConf),
    "assoc": (build_assoc_parser, AssocConf),
}


@dataclass
class PlanIssue:
    """One validation result: ``severity`` is 'error' (plan rejected) or
    'warning' (plan runs, but something is off-contract or wasteful)."""

    code: str
    severity: str
    message: str

    def format(self) -> str:
        return f"{self.severity.upper()} [{self.code}] {self.message}"


@dataclass
class PlanReport:
    issues: List[PlanIssue] = field(default_factory=list)
    #: Resolved geometry facts (mesh shape, shard count, padded cohort, ...).
    geometry: Dict[str, object] = field(default_factory=dict)
    #: eval_shape-verified kernel signatures, for the human report.
    shape_checks: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not any(i.severity == "error" for i in self.issues)

    def error(self, code: str, message: str) -> None:
        self.issues.append(PlanIssue(code, "error", message))

    def warn(self, code: str, message: str) -> None:
        self.issues.append(PlanIssue(code, "warning", message))

    def to_json(self) -> str:
        return json.dumps(
            {
                "tool": "graftcheck-plan",
                "ok": self.ok,
                "issues": [
                    {"code": i.code, "severity": i.severity, "message": i.message}
                    for i in self.issues
                ],
                "geometry": self.geometry,
                "shape_checks": self.shape_checks,
            },
            indent=2,
        )

    def format(self) -> str:
        lines = []
        for key, value in self.geometry.items():
            lines.append(f"  {key}: {value}")
        for check in self.shape_checks:
            lines.append(f"  verified: {check}")
        for issue in self.issues:
            lines.append(f"  {issue.format()}")
        verdict = "plan OK" if self.ok else "plan REJECTED"
        lines.append(verdict)
        return "\n".join(lines)


class _RaisingParser(argparse.ArgumentParser):
    """argparse whose flag errors raise ``ValueError`` instead of
    ``SystemExit``-with-usage-text: the plan CLI reports them as
    machine-readable plan rejections, and in-process callers of
    ``check.cli.main(['plan', ...])`` get the documented int return.
    ``-h`` keeps argparse's normal exit."""

    def error(self, message):
        raise ValueError(message)


def parse_plan_args(argv: Sequence[str]):
    """Parse ``graftcheck plan`` argv: the analysis's full flag surface
    (``--analysis pca|grm|ld|assoc``, default pca — pre-scanned so the
    remaining flags parse through that verb's REAL parser) plus the
    plan-only ``--plan-devices``, ``--host-mem-budget``, ``--topology``
    and ``--sched-budget-seconds``. Returns ``(conf, plan_devices,
    json_out, host_mem_budget, analysis, topology,
    sched_budget_seconds)``. Flag errors raise ``ValueError`` (argparse's
    SystemExit is converted so the caller reports them as plan
    rejections, not a CLI crash)."""
    argv = list(argv)
    analysis = "pca"
    for index, arg in enumerate(argv):
        if arg == "--analysis":
            if index + 1 >= len(argv):
                raise ValueError(
                    "--analysis needs a value: one of "
                    + "|".join(sorted(ANALYSIS_SURFACES))
                )
            analysis = argv[index + 1]
            del argv[index : index + 2]
            break
        if arg.startswith("--analysis="):
            analysis = arg.split("=", 1)[1]
            del argv[index]
            break
    if analysis not in ANALYSIS_SURFACES:
        raise ValueError(
            f"--analysis {analysis!r} is not one of "
            + "|".join(sorted(ANALYSIS_SURFACES))
        )
    build_parser, conf_cls = ANALYSIS_SURFACES[analysis]
    parser = build_parser(
        _RaisingParser(prog=f"graftcheck plan [{analysis}]", add_help=True)
    )
    parser.add_argument(
        "--analysis",
        choices=sorted(ANALYSIS_SURFACES),
        default=analysis,
        help=(
            "Which analysis surface to validate (default pca). Consumed "
            "by a pre-scan so the remaining flags parse through that "
            "verb's real parser; registered here so --help documents it."
        ),
    )
    parser.add_argument(
        "--plan-devices",
        type=int,
        default=None,
        help=(
            "Declared device count to validate the mesh against (the "
            "validator never queries real devices). Unset: device-count "
            "checks are skipped, geometry/shape checks still run."
        ),
    )
    parser.add_argument(
        "--host-mem-budget",
        type=int,
        default=None,
        metavar="BYTES",
        help=(
            "Host-RAM budget in bytes to enforce against the static bound "
            "parallel/mesh.py:host_peak_bytes (bounded ingest paths only — "
            "a configuration whose ingest is O(file) cannot be proven and "
            "is rejected under a budget). Over-budget configs exit 2."
        ),
    )
    parser.add_argument(
        "--topology",
        default=None,
        metavar="H,D",
        help=(
            "Declared pod topology (hosts,devices_per_host — e.g. 32,8) "
            "to prove the reduction schedule against: the collective "
            "schedule is extracted from the real kernel jaxprs and "
            "simulated per link class (check/sched.py) — per-level "
            "traffic, overlap, liveness, and the GS rules, for a pod "
            "that need not exist. The samples axis it implies is "
            "hosts x devices_per_host; an explicit --mesh-shape must "
            "agree."
        ),
    )
    parser.add_argument(
        "--sched-budget-seconds",
        type=float,
        default=None,
        metavar="S",
        help=(
            "Declared schedule-limited wall-clock budget for the whole "
            "run's statically-known site count: a topology whose "
            "predicted critical path exceeds it is a GS005 rejection "
            "(exit 2). Needs --topology."
        ),
    )
    parser.add_argument(
        "--json", action="store_true", help="Emit the machine-readable report."
    )
    ns = parser.parse_args(argv)
    conf = conf_cls._from_namespace(ns)
    topology = None
    if ns.topology is not None:
        from spark_examples_tpu.parallel.mesh import parse_topology

        topology = parse_topology(ns.topology)  # ValueError -> rejection
    return (
        conf,
        ns.plan_devices,
        ns.json,
        ns.host_mem_budget,
        analysis,
        topology,
        ns.sched_budget_seconds,
    )


def _resolve_mesh_axes(
    conf: PcaConf, plan_devices: Optional[int], report: PlanReport
):
    """(data, samples) the run would build, mirroring
    ``pca_driver._make_mesh`` / ``parallel.mesh.default_mesh`` — or None
    when the mesh is unresolvable (errors recorded)."""
    from spark_examples_tpu.parallel.mesh import parse_mesh_shape

    if conf.mesh_shape:
        try:
            shape = parse_mesh_shape(conf.mesh_shape)
        except ValueError as e:
            report.error("mesh-grammar", str(e))
            return None
        data, samples = shape["data"], shape["samples"]
        if data < 1 or samples < 1:
            report.error(
                "mesh-axis-size",
                f"--mesh-shape {conf.mesh_shape}: every axis must be >= 1",
            )
            return None
        if plan_devices is not None and data * samples > plan_devices:
            report.error(
                "mesh-exceeds-devices",
                f"--mesh-shape {conf.mesh_shape} needs {data * samples} "
                f"devices; --plan-devices declares {plan_devices} "
                "(make_mesh would raise at run start, after flags parsed "
                "but potentially after ingest warm-up)",
            )
        if data > conf.num_reduce_partitions:
            # The reference contract (GenomicsConf.scala:35-38 via
            # BASELINE.json): --num-reduce-partitions BOUNDS the data-axis
            # parallelism. default_mesh enforces the cap; an explicit mesh
            # that exceeds it contradicts the flag surface.
            report.error(
                "data-axis-exceeds-reduce-partitions",
                f"--mesh-shape data axis {data} exceeds "
                f"--num-reduce-partitions {conf.num_reduce_partitions}; "
                "the reduce-partition flag bounds data parallelism "
                "(raise it, or shrink the mesh)",
            )
        return data, samples
    # Default mesh: all declared devices data-major, samples axis 1,
    # data capped by --num-reduce-partitions (parallel/mesh.py:default_mesh).
    devices = plan_devices if plan_devices is not None else 1
    data = max(1, min(devices, conf.num_reduce_partitions))
    return data, 1


def _eval_dense_update(report: PlanReport, data: int, conf: PcaConf) -> None:
    """Trace the real dense-update kernels abstractly: ingest block
    (B, N) uint8 → bit-packed (D, B, ceil(N/8)) → G (D, N, N)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from spark_examples_tpu.ops.gramian import (
        _dense_update,
        _dense_update_counts,
        data_axis_sum,
    )

    N = int(conf.num_samples)
    B = int(conf.block_size)
    operand = np.int8 if conf.exact_similarity else np.float32
    accum = jnp.int32 if conf.exact_similarity else jnp.float32
    G = jax.ShapeDtypeStruct((data, N, N), accum)
    X_packed = jax.ShapeDtypeStruct((data, B, -(-N // 8)), jnp.uint8)
    out = jax.eval_shape(
        lambda g, x: _dense_update(g, x, operand, N), G, X_packed
    )
    if out.shape != G.shape or out.dtype != G.dtype:
        report.error(
            "dense-update-shape",
            f"dense Gramian update maps {G.shape}/{G.dtype} to "
            f"{out.shape}/{out.dtype} — accumulator would diverge",
        )
    else:
        report.shape_checks.append(
            f"dense update: ({data}, {B}, {N}) uint8 blocks -> "
            f"G {out.shape} {out.dtype}"
        )
    X_counts = jax.ShapeDtypeStruct((data, B, N), jnp.uint8)
    out_c = jax.eval_shape(
        lambda g, x: _dense_update_counts(g, x, operand), G, X_counts
    )
    if out_c.shape != G.shape or out_c.dtype != G.dtype:
        report.error(
            "counts-update-shape",
            f"count-valued update maps {G.shape} to {out_c.shape}",
        )
    final = jax.eval_shape(data_axis_sum, G)
    if final.shape != (N, N):
        report.error(
            "finalize-shape",
            f"finalize reduce yields {final.shape}, expected {(N, N)}",
        )
    else:
        report.shape_checks.append(
            f"finalize psum over data axis: {G.shape} -> "
            f"{final.shape} {final.dtype}"
        )


def _eval_stacked_update(
    report: PlanReport, fused_jobs: int, conf: PcaConf
) -> None:
    """Trace the STACKED-JOBS kernel abstractly (``--fused-jobs K``): the
    fused batch executor's one-device-program path runs the identical
    ``_dense_update`` body with a leading jobs axis in the batch slot —
    G (K, N, N), X (K, B, ceil(N/8)) — so the same eval_shape proof that
    covers the serial kernel covers the stacked one, at the group's
    geometry. Device-free, like every proof here."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from spark_examples_tpu.ops.gramian import _dense_update

    K = int(fused_jobs)
    N = int(conf.num_samples)
    B = int(conf.block_size)
    operand = np.int8 if conf.exact_similarity else np.float32
    accum = jnp.int32 if conf.exact_similarity else jnp.float32
    G = jax.ShapeDtypeStruct((K, N, N), accum)
    X_packed = jax.ShapeDtypeStruct((K, B, -(-N // 8)), jnp.uint8)
    out = jax.eval_shape(
        lambda g, x: _dense_update(g, x, operand, N), G, X_packed
    )
    if out.shape != G.shape or out.dtype != G.dtype:
        report.error(
            "stacked-update-shape",
            f"stacked {K}-job Gramian update maps {G.shape}/{G.dtype} to "
            f"{out.shape}/{out.dtype} — per-job accumulator lanes would "
            "diverge",
        )
        return
    # The per-job result is a host-side slice of the stacked accumulator:
    # prove the slice geometry too (what the fused runner hands each
    # job's epilogue).
    lane = jax.eval_shape(lambda g: g[0], G)
    if lane.shape != (N, N):
        report.error(
            "stacked-slice-shape",
            f"per-job slice of the stacked accumulator yields "
            f"{lane.shape}, expected {(N, N)}",
        )
        return
    report.shape_checks.append(
        f"stacked update: jobs={K}, ({K}, {B}, {N}) uint8 blocks -> "
        f"G {out.shape} {out.dtype}; per-job slice -> {lane.shape}"
    )


#: Simultaneous per-device buffers of the sharded strategy at peak: the
#: local G row-tile, its non-donated update output, and the (smaller)
#: column-block operands rounded up to one more tile.
_SHARDED_BUFFERS = 3


def _eval_sharded_update(
    report: PlanReport, data: int, samples: int, conf: PcaConf
):
    """Trace the sharded ring update through shard_map over an
    ``AbstractMesh`` — the same `_ring_tiles` body the run executes, with
    the same PartitionSpecs ``ShardedGramianAccumulator`` installs and the
    same wire format ``--ring-pack-bits`` selects, proven shape-correct
    with zero devices. Also the home of the sharded geometry facts: the
    pack-width-padded cohort (auto-rounded exactly as the accumulators
    round it), per-device ring buffer bytes, per-flush ICI ring traffic,
    and the sharded HBM feasibility check."""
    import jax.numpy as jnp
    import numpy as np

    from spark_examples_tpu.ops.gramian import (
        _DEFAULT_DEVICE_BYTES,
        DENSE_HBM_FRACTION,
        resolve_ring_pack,
    )
    from spark_examples_tpu.parallel.mesh import (
        DATA_AXIS,
        RING_PACK_MULTIPLE,
        SAMPLES_AXIS,
        padded_cohort,
        ring_traffic_bytes,
    )

    N = int(conf.num_samples)
    B = int(conf.block_size)
    pack = resolve_ring_pack(getattr(conf, "ring_pack_bits", "auto"))
    padded = padded_cohort(N, samples, pack=pack)
    n_local = padded // samples
    if pack and n_local % RING_PACK_MULTIPLE:
        # Unreachable through padded_cohort — a defensive contract check so
        # a future geometry change cannot silently ship a ragged packed
        # tile (the ring would shard mid-byte and corrupt columns).
        report.error(
            "ring-pack-width",
            f"packed ring needs a per-device column width divisible by "
            f"{RING_PACK_MULTIPLE}, got {n_local} "
            f"(padded cohort {padded} over samples={samples})",
        )
        return
    if padded != N:
        rule = (
            f"{RING_PACK_MULTIPLE}x the samples axis (packed-ring "
            "pack-width invariant)"
            if pack
            else f"the samples axis ({samples})"
        )
        report.warn(
            "cohort-padding",
            f"--num-samples {N} is not a multiple of {rule}; the sharded "
            f"accumulator auto-rounds the cohort to {padded} "
            f"(+{(padded - N) * 100.0 / N:.1f}% all-zero pad columns, "
            "trimmed at finalize)",
        )
    width = n_local // RING_PACK_MULTIPLE if pack else n_local
    report.geometry["ring_pack_bits"] = "packed" if pack else "unpacked"
    report.geometry["ring_local_columns"] = n_local
    report.geometry["ring_tile_bytes_per_device"] = B * width
    report.geometry["ring_bytes_per_flush"] = ring_traffic_bytes(
        data * B, samples, n_local, pack
    )
    # Sharded HBM feasibility against the default budget (the validator
    # never queries devices): per device, the local (padded/samples, padded)
    # accumulator tile dominates, times the non-donation working copies.
    accum_bytes = 4
    tile_bytes = n_local * padded * accum_bytes
    report.geometry["sharded_tile_bytes_per_device"] = tile_bytes
    if (
        conf.similarity_strategy == "sharded"
        and _SHARDED_BUFFERS * tile_bytes
        > DENSE_HBM_FRACTION * _DEFAULT_DEVICE_BYTES
    ):
        report.error(
            "sharded-exceeds-hbm",
            f"--similarity-strategy sharded with N={N} over samples="
            f"{samples} needs ~"
            f"{_SHARDED_BUFFERS * tile_bytes / (1 << 30):.1f} GiB of "
            f"ring working buffers per device, past "
            f"{DENSE_HBM_FRACTION:.0%} of the "
            f"{_DEFAULT_DEVICE_BYTES >> 30} GiB default budget; widen the "
            "samples axis",
        )

    try:
        # Capability probe only — the IR audit below constructs the mesh.
        from jax.sharding import AbstractMesh  # noqa: F401
    except ImportError:
        report.warn(
            "no-abstract-mesh",
            "this jax has no AbstractMesh; sharded-update shape check "
            "skipped (geometry checks above still hold)",
        )
        return

    accum = jnp.int32 if conf.exact_similarity else jnp.float32
    x_width = padded // RING_PACK_MULTIPLE if pack else padded

    # ONE trace serves every layer: the runtime's own build_sharded_update
    # is traced through make_jaxpr over an AbstractMesh exactly once, and
    # the same ClosedJaxpr feeds the IR auditor (overlap/donation/dtype/
    # traffic contracts + the output signature the shape check needs — no
    # second eval_shape) AND, returned from here, the range prover
    # (_check_exactness) — no second make_jaxpr either. The jaxpr-derived
    # ring traffic and static peak-live-bytes land in the plan report so a
    # whole-genome run can be sized before a single device is touched; any
    # IR finding is a plan rejection — the configured kernel would ship
    # without its contracts.
    from spark_examples_tpu.check.ir import (
        audit_kernel,
        ring_kernel_spec,
        trace_kernel,
    )

    ir_spec = ring_kernel_spec(
        data, samples, N, B, pack, exact_int=conf.exact_similarity
    )
    try:
        ring_trace = trace_kernel(ir_spec)
    except Exception as e:  # noqa: BLE001 — the trace failure is the finding
        report.error(
            "sharded-update-trace",
            f"sharded ring update fails to trace on a {data}x{samples} "
            f"abstract mesh: {type(e).__name__}: {e}",
        )
        return None
    audit = audit_kernel(ir_spec, traced=ring_trace)
    g_shape = (data, padded, padded)
    out_shape = tuple(audit.facts["out_shapes"][0])
    out_dtype = audit.facts["out_dtypes"][0]
    if out_shape != g_shape or out_dtype != str(np.dtype(accum)):
        report.error(
            "sharded-update-shape",
            f"sharded update maps {g_shape} to {out_shape} {out_dtype}",
        )
    else:
        wire = "bit-packed" if pack else "unpacked"
        report.shape_checks.append(
            f"sharded ring update over abstract {data}x{samples} mesh: "
            f"({data}, {B}, {x_width}) {wire} uint8 blocks -> "
            f"G {out_shape} {out_dtype}"
        )
    for finding in audit.findings:
        report.error(f"ir-{finding.rule_id}", finding.detail)
    if "ring_bytes_jaxpr" in audit.facts:
        report.geometry["ring_bytes_per_flush_jaxpr"] = audit.facts[
            "ring_bytes_jaxpr"
        ]
    if "peak_live_bytes" in audit.facts:
        report.geometry["ring_peak_live_bytes_per_device"] = audit.facts[
            "peak_live_bytes"
        ]
    if "permute_executions" in audit.facts:
        report.geometry["ring_permute_steps"] = audit.facts[
            "permute_executions"
        ]
    if audit.ok:
        report.shape_checks.append(
            f"ring IR audit over abstract {data}x{samples} mesh: "
            f"{audit.facts.get('permute_executions', 0)} independent "
            "ppermute(s), donation contract justified, jaxpr ring bytes "
            "== ring_traffic_bytes"
        )
    return ring_trace


def _check_exactness(
    report: PlanReport,
    data: int,
    samples: int,
    conf: PcaConf,
    ring_trace=None,
) -> None:
    """Range/exactness proof of the CONFIGURED kernels (the ``graftcheck
    ranges`` abstract interpreter over exactly the geometry the run would
    build) plus geometry-level exactness facts: ``gramian_entry_bound``
    (the declared static site count × max_count² when the synthetic grid
    makes the site count statically known) and ``exactness_headroom_sites``
    (the largest cohort/site count provable exact on each dtype-ladder
    rung). A geometry whose accumulation could leave the terminal int32
    exact window — or whose per-dispatch partial leaves the f32 window
    before the conversion point (GR002) — is rejected (exit 2): this
    replaces the hand-reasoned per-dispatch exactness prose of DESIGN.md
    §5 with a machine proof per configuration."""
    import numpy as np

    from spark_examples_tpu.check.ranges import (
        audit_range_kernel,
        counts_range_spec,
        dense_range_spec,
        ring_range_spec,
    )
    from spark_examples_tpu.ops.contracts import (
        exact_int_window,
        exactness_headroom_sites,
        flush_entry_increment,
    )
    from spark_examples_tpu.ops.gramian import resolve_ring_pack

    N, B = int(conf.num_samples), int(conf.block_size)
    exact = bool(getattr(conf, "exact_similarity", False))
    pack = resolve_ring_pack(getattr(conf, "ring_pack_bits", "auto"))
    ids = list(conf.variant_set_id)
    max_count = max((ids.count(i) for i in set(ids)), default=1)

    audits = []
    sharded = conf.similarity_strategy == "sharded"
    if not sharded:
        audits.append(audit_range_kernel(dense_range_spec(data, N, B)))
        if max_count > 1:
            # Duplicate set ids take the count-valued (same-set-join) kernel.
            audits.append(audit_range_kernel(counts_range_spec(data, N, B)))
    if samples >= 2:
        # `ring_trace` is _eval_sharded_update's ClosedJaxpr of this exact
        # geometry (same ir builder, same conf-derived args) — one trace
        # serves the shape check, the IR audit, AND this range proof.
        audits.append(
            audit_range_kernel(
                ring_range_spec(data, samples, N, B, pack, exact_int=exact),
                traced=ring_trace,
            )
        )
        if max_count > 1:
            # Count-valued flushes (duplicate set ids) ride the UNPACKED
            # ring kernel per flush regardless of --ring-pack-bits; prove
            # that path under the count contract too — packed-[0,1]
            # operands do not cover it.
            audits.append(
                audit_range_kernel(
                    ring_range_spec(
                        data, samples, N, B, False, exact_int=exact,
                        counts=True,
                    )
                )
            )
    partial = 0.0
    for audit in audits:
        for finding in audit.findings:
            report.error(f"ranges-{finding.rule_id}", finding.detail)
        partial = max(partial, float(audit.facts.get("dot_partial_bound", 0)))
    if all(a.ok for a in audits) and audits:
        increments = [
            a.facts.get("entry_increment") for a in audits
        ]
        report.shape_checks.append(
            f"range audit ({len(audits)} kernel(s)): per-dispatch partial "
            f"<= {partial:g} exact, entry increment <= "
            f"{max(float(i) for i in increments if i is not None):g}/flush, "
            "conversion trigger proven conservative (GR005)"
        )
    report.geometry["exactness_headroom_sites"] = {
        "float32": exactness_headroom_sites(np.float32, max_count),
        "int32": exactness_headroom_sites(np.int32, max_count),
    }

    static_rows = _static_site_rows(conf)
    if static_rows is None:
        report.geometry["gramian_entry_bound"] = None
        return
    entry_bound = flush_entry_increment(static_rows, max_count)
    report.geometry["gramian_entry_bound"] = entry_bound
    int32_window = exact_int_window(np.int32) or 0
    if entry_bound > int32_window:
        report.error(
            "exactness-window",
            f"the declared geometry bounds a Gramian entry at "
            f"{entry_bound} ({static_rows} candidate sites x max_count "
            f"{max_count}²), past int32's exact-integer window "
            f"({int32_window}) — no dtype-ladder rung can hold the count "
            "exactly; shrink --references or split the cohort "
            "(graftcheck ranges GR001)",
        )


def _static_site_rows(conf: PcaConf) -> Optional[int]:
    """Statically-known total variant rows, or None: the synthetic grid
    has one candidate site per DEFAULT_VARIANT_SPACING bases, so explicit
    ``--references`` windows bound the total statically (variant sets
    share the site grid — DESIGN.md §6; file/REST cohorts carry their
    counts in the data, so no static bound exists for them). Shared by the
    exactness proof (``gramian_entry_bound``) and the schedule prover's
    critical-path projection (GS005)."""
    if (
        getattr(conf, "source", "synthetic") != "synthetic"
        or conf.all_references
        or conf.input_path
    ):
        return None
    try:
        from spark_examples_tpu.sources.synthetic import (
            DEFAULT_VARIANT_SPACING,
        )

        return sum(
            (contig.end - contig.start) // DEFAULT_VARIANT_SPACING + 1
            for contigs in conf.get_references()
            for contig in contigs
        )
    except (ValueError, TypeError):
        return None


def _check_schedule(
    report: PlanReport,
    conf: PcaConf,
    topology,
    data: int,
    samples: int,
    sched_budget_seconds: Optional[float],
    plan_devices: Optional[int] = None,
) -> None:
    """The collective-schedule proof for a DECLARED topology
    (``check/sched.py`` over the configured kernel geometry): resolve the
    schedule ``--reduce-schedule`` would build on that topology, extract
    and simulate it from the traced kernel, and turn GS/GI findings into
    plan rejections — a pod-scale run is schedule-proven before the pod
    exists. ``--sched-budget-seconds`` projects the critical path over
    the statically-known site count (GS005); a budget over an unknowable
    site count is itself a rejection (the flag asks for a proof the
    configuration cannot give — the ``--host-mem-budget`` rule)."""
    from spark_examples_tpu.check.sched import audit_schedule
    from spark_examples_tpu.ops.gramian import resolve_ring_pack
    from spark_examples_tpu.parallel.mesh import resolve_reduce_schedule

    if conf.mesh_shape and samples != topology.devices:
        # An explicit mesh must span the declared pod's samples axis —
        # including the data-only (samples=1) spelling, which pins a run
        # that dispatches no ring at all; only the default-mesh case
        # (no --mesh-shape) lets the topology imply the schedule mesh.
        report.error(
            "topology-mesh-mismatch",
            f"--topology {topology.describe()} implies a samples axis of "
            f"{topology.devices} but --mesh-shape {conf.mesh_shape} "
            f"declares {samples}; the schedule would not span the "
            "declared pod",
        )
        return
    if plan_devices is not None and plan_devices != topology.devices:
        # One report must describe ONE pod: the mesh/HBM/host-mem facts
        # are computed against --plan-devices while the schedule proof
        # spans the topology — a disagreement proves a plan no single
        # run can execute.
        report.error(
            "topology-devices-mismatch",
            f"--topology {topology.describe()} declares "
            f"{topology.devices} devices but --plan-devices declares "
            f"{plan_devices}; the geometry facts and the schedule proof "
            "would describe different pods",
        )
        return
    schedule = resolve_reduce_schedule(
        getattr(conf, "reduce_schedule", "auto"), topology.hosts
    )
    static_rows = _static_site_rows(conf)
    if sched_budget_seconds is not None and sched_budget_seconds <= 0:
        report.error(
            "sched-budget-seconds",
            f"--sched-budget-seconds must be positive, got "
            f"{sched_budget_seconds}",
        )
        return
    if sched_budget_seconds is not None and static_rows is None:
        report.error(
            "sched-budget-unprovable",
            "--sched-budget-seconds needs a statically-known site count "
            "to project the schedule over (synthetic source with explicit "
            "--references); this configuration's total rows are only "
            "known at run time, so no critical-path proof exists",
        )
        return
    audit = audit_schedule(
        topology,
        schedule,
        num_samples=int(conf.num_samples),
        block_size=int(conf.block_size),
        data=data if conf.mesh_shape and samples == topology.devices else 1,
        pack=resolve_ring_pack(getattr(conf, "ring_pack_bits", "auto")),
        exact_int=bool(getattr(conf, "exact_similarity", False)),
        rows=static_rows,
        budget_seconds=sched_budget_seconds,
        selected=True,
        # Prove the kernel the run would actually dispatch: device ingest
        # rides the fused generation ring, not the host-fed Gramian ring.
        kernel="devicegen" if conf.ingest == "device" else "gramian",
    )
    for finding in audit.findings:
        report.error(f"sched-{finding.rule_id}", finding.detail)
    report.geometry["sched_topology"] = topology.describe()
    report.geometry["sched_schedule"] = schedule
    report.geometry["sched_kernel"] = audit.facts.get("kernel")
    report.geometry["sched_ici_bytes"] = audit.facts.get("ici_bytes")
    report.geometry["sched_dcn_bytes"] = audit.facts.get("dcn_bytes")
    report.geometry["sched_rows"] = audit.facts.get("sim_rows")
    report.geometry["sched_critical_path_seconds"] = audit.facts.get(
        "critical_path_seconds"
    )
    if audit.ok:
        report.shape_checks.append(
            f"schedule audit on {topology.describe()}: {schedule} "
            f"schedule, ici {audit.facts.get('ici_bytes')} B / dcn "
            f"{audit.facts.get('dcn_bytes')} B per flush == formula, "
            "overlap clean, predicted critical path "
            f"{audit.facts.get('critical_path_seconds'):.3g} s over "
            f"{audit.facts.get('sim_rows')} rows"
        )


def _check_artifact_parent(
    report: PlanReport, code: str, flag: str, path: Optional[str]
) -> None:
    """An output artifact whose parent directory is missing/unwritable only
    fails AFTER the analysis streamed every site — the exact class of
    late-surfacing error the validator exists to catch (the
    ``--metrics-json`` rule, shared by the analyses' out flags)."""
    if not path:
        return
    import os

    parent = os.path.dirname(os.path.abspath(path)) or "."
    if not os.path.isdir(parent):
        report.error(
            code,
            f"{flag} {path}: parent directory {parent} does not exist; "
            "the output publish would fail AFTER the analysis completed",
        )
    elif not os.access(parent, os.W_OK):
        report.error(
            code,
            f"{flag} {path}: parent directory {parent} is not writable; "
            "the output publish would fail AFTER the analysis completed",
        )
    elif os.path.isdir(path):
        report.error(
            code,
            f"{flag} {path} is a directory; the output needs a file path",
        )


def _check_analysis(
    report: PlanReport, conf: PcaConf, analysis: str, samples: int
) -> None:
    """The device-free mirror of the analyses' runtime admission gate
    (``analyses/base.py:analysis_conf_violations`` — the ONE catalogue)
    plus per-analysis flag contracts: LD window/threshold grammar and the
    samples-axis divisibility the ``shard_map`` kernel needs, the assoc
    phenotype TSV (parsed HERE, device-free, including synthetic-cohort
    coverage), and every per-site output path's parent."""
    from spark_examples_tpu.analyses.base import analysis_conf_violations

    for code, message in analysis_conf_violations(conf, analysis):
        report.error(code, message)

    if analysis == "grm":
        _check_artifact_parent(
            report, "grm-out", "--grm-out", getattr(conf, "grm_out", None)
        )
        return

    if analysis == "ld":
        threshold = getattr(conf, "ld_r2_threshold", 0.2)
        if not 0.0 <= threshold <= 1.0:
            report.error(
                "ld-r2-threshold",
                f"--ld-r2-threshold must be in [0, 1], got {threshold} "
                "(outside the range every site, or no site, is pruned)",
            )
        window = int(getattr(conf, "ld_window_sites", 256))
        if window < 2:
            report.error(
                "ld-window-sites",
                f"--ld-window-sites must be >= 2, got {window} (a "
                "one-site window has nothing to correlate)",
            )
        else:
            N = int(conf.num_samples)
            report.geometry["ld_window_sites"] = window
            # The per-window device statistics: C (W, W) int32 + k (W,)
            # int32 — the whole M-sized analysis only ever materializes
            # this much at once (plus the (W, N) uint8 window buffer).
            stats_bytes = window * window * 4 + window * 4
            report.geometry["ld_window_stats_bytes"] = stats_bytes
            report.geometry["ld_window_buffer_bytes"] = window * N
            from spark_examples_tpu.ops.gramian import (
                _DEFAULT_DEVICE_BYTES,
                DENSE_HBM_FRACTION,
            )

            if stats_bytes > DENSE_HBM_FRACTION * _DEFAULT_DEVICE_BYTES:
                report.error(
                    "ld-window-exceeds-hbm",
                    f"--ld-window-sites {window} needs a ~"
                    f"{stats_bytes / (1 << 30):.1f} GiB W×W statistics "
                    f"matrix per flush, past {DENSE_HBM_FRACTION:.0%} of "
                    f"the {_DEFAULT_DEVICE_BYTES >> 30} GiB default HBM "
                    "budget; shrink the window (host memory scales with "
                    "W² too — see host_peak_bytes)",
                )
        if (
            samples >= 2
            and conf.pca_backend != "host"
            and int(conf.num_samples) % samples
        ):
            # --pca-backend host runs the NumPy window oracle: no mesh,
            # no sharding constraint (mirrors analyses/ld.py).
            report.error(
                "ld-cohort-not-divisible",
                f"--num-samples {conf.num_samples} does not divide over "
                f"the mesh samples axis ({samples}); the LD window kernel "
                "shards sample columns without padding (choose a mesh "
                "whose samples axis divides the cohort)",
            )
        _check_artifact_parent(
            report, "ld-out", "--ld-out", getattr(conf, "ld_out", None)
        )
        return

    # assoc
    top = int(getattr(conf, "assoc_top", 10))
    if top < 1:
        report.error(
            "assoc-top", f"--assoc-top must be >= 1, got {top}"
        )
    phenotypes = getattr(conf, "phenotypes", None)
    if not phenotypes:
        report.error(
            "assoc-phenotypes",
            "the assoc analysis requires --phenotypes TSV "
            "(name<TAB>status per line, status 0=control/1=case)",
        )
    else:
        from spark_examples_tpu.analyses.assoc import load_phenotypes

        try:
            statuses = load_phenotypes(phenotypes)
        except (OSError, ValueError) as e:
            report.error("assoc-phenotypes", f"--phenotypes: {e}")
        else:
            cases = sum(statuses.values())
            report.geometry["assoc_cases"] = cases
            report.geometry["assoc_controls"] = len(statuses) - cases
            if getattr(conf, "source", "synthetic") == "synthetic":
                # The synthetic cohort's callset names are derivable
                # device-free, so the strict both-ways coverage check the
                # runtime applies (``analyses/assoc.py:case_vector``) runs
                # at plan time too; file cohorts carry their names in the
                # data, so only the runtime can check them.
                from spark_examples_tpu.analyses.assoc import case_vector
                from spark_examples_tpu.pipeline.pca_driver import (
                    make_source,
                )

                try:
                    callsets = make_source(conf).search_callsets(
                        conf.variant_set_id
                    )
                    case_vector(
                        statuses, [cs["name"] for cs in callsets]
                    )
                except ValueError as e:
                    report.error("assoc-cohort-mismatch", str(e))
    _check_artifact_parent(
        report, "assoc-out", "--assoc-out", getattr(conf, "assoc_out", None)
    )


def _eval_analysis_kernels(
    report: PlanReport, conf: PcaConf, analysis: str, data: int, samples: int
) -> None:
    """Abstract shape proof of the per-site kernels the analysis will
    dispatch (``ops/ld.py`` — the SAME construction sites the runtime
    calls), traced with ``jax.eval_shape`` over ``ShapeDtypeStruct``
    operands and, when the mesh has a samples axis, through ``shard_map``
    over an ``AbstractMesh`` — zero devices, zero bytes."""
    import jax
    import jax.numpy as jnp

    N = int(conf.num_samples)
    if analysis == "ld":
        from spark_examples_tpu.ops.ld import build_ld_window_stats

        window = int(conf.ld_window_sites)
        mesh = None
        mesh_note = "single-device"
        if samples >= 2:
            try:
                from jax.sharding import AbstractMesh
            except ImportError:
                report.warn(
                    "no-abstract-mesh",
                    "this jax has no AbstractMesh; the LD window kernel "
                    "is shape-checked single-device only",
                )
            else:
                from spark_examples_tpu.parallel.mesh import (
                    DATA_AXIS,
                    SAMPLES_AXIS,
                )

                mesh = AbstractMesh(
                    ((DATA_AXIS, data), (SAMPLES_AXIS, samples))
                )
                mesh_note = f"abstract {data}x{samples} mesh"
        try:
            stats_fn = build_ld_window_stats(mesh)
            C, k = jax.eval_shape(
                stats_fn, jax.ShapeDtypeStruct((window, N), jnp.uint8)
            )
        except Exception as e:  # noqa: BLE001 — the trace failure is the finding
            report.error(
                "ld-window-stats-trace",
                f"LD window-statistics kernel fails to trace over "
                f"{mesh_note}: {type(e).__name__}: {e}",
            )
            return
        if (
            C.shape != (window, window)
            or str(C.dtype) != "int32"
            or k.shape != (window,)
            or str(k.dtype) != "int32"
        ):
            report.error(
                "ld-window-stats-shape",
                f"LD window statistics map ({window}, {N}) uint8 to "
                f"C {C.shape} {C.dtype}, k {k.shape} {k.dtype} — expected "
                f"(({window}, {window}) int32, ({window},) int32)",
            )
        else:
            report.shape_checks.append(
                f"LD window stats over {mesh_note}: ({window}, {N}) uint8 "
                f"window -> C ({window}, {window}) int32, k ({window},) "
                "int32"
            )
        return

    if analysis == "assoc":
        from spark_examples_tpu.ops.ld import build_case_counts

        B = int(conf.block_size)
        try:
            a, t = jax.eval_shape(
                build_case_counts(),
                jax.ShapeDtypeStruct((B, N), jnp.uint8),
                jax.ShapeDtypeStruct((N,), jnp.uint8),
            )
        except Exception as e:  # noqa: BLE001 — the trace failure is the finding
            report.error(
                "assoc-counts-trace",
                f"association counts kernel fails to trace: "
                f"{type(e).__name__}: {e}",
            )
            return
        if a.shape != (B,) or t.shape != (B,) or str(a.dtype) != "int32":
            report.error(
                "assoc-counts-shape",
                f"association counts map ({B}, {N}) uint8 blocks to "
                f"a {a.shape} {a.dtype}, t {t.shape} {t.dtype} — expected "
                f"(({B},) int32, ({B},) int32)",
            )
        else:
            report.shape_checks.append(
                f"association counts: ({B}, {N}) uint8 blocks x ({N},) "
                f"case mask -> a ({B},) int32, t ({B},) int32"
            )


def _check_host_memory(
    conf: PcaConf,
    plan_devices: Optional[int],
    host_mem_budget: Optional[int],
    report: PlanReport,
) -> None:
    """Host-memory facts + budget enforcement: the static bound from the
    ONE formula (``parallel/mesh.py:host_peak_bytes``, resolved through
    ``check/hostmem.py:conf_host_peak_bytes`` — the same resolver the
    driver's ``host_static_bound_bytes`` gauge uses). The resolver is
    TOTAL: every configuration — wire ingest, JSONL/SAM, REST, multi-set
    joins, checkpoint resume — gets a finite bound as a geometry fact,
    so ``--host-mem-budget`` is enforceable against ANY workload; the
    only failure mode left is a bound genuinely over budget."""
    from spark_examples_tpu.check.hostmem import conf_host_peak_bytes

    bound = conf_host_peak_bytes(conf, device_count=plan_devices)
    report.geometry["host_peak_bytes"] = bound
    if host_mem_budget is not None and bound > host_mem_budget:
        report.error(
            "host-mem-over-budget",
            f"static host-memory bound ~{bound / (1 << 30):.2f} GiB "
            f"(parallel/mesh.py:host_peak_bytes) exceeds "
            f"--host-mem-budget {host_mem_budget} "
            f"({host_mem_budget / (1 << 30):.2f} GiB); shrink the "
            "ingest window (--stream-chunk-bytes, --ingest-workers, "
            "--block-size) or raise the budget",
        )


def validate_plan(
    conf: PcaConf,
    plan_devices: Optional[int] = None,
    host_mem_budget: Optional[int] = None,
    analysis: str = "pca",
    topology=None,
    sched_budget_seconds: Optional[float] = None,
) -> PlanReport:
    """Statically validate one pipeline configuration. Pure flag/geometry
    arithmetic plus abstract kernel traces — no device is queried.
    ``analysis`` selects the validated workload: ``pca`` (the default —
    also the ``similarity`` served kind) keeps every Gramian proof;
    ``grm`` adds the analyses' shared admission gate on top of them (its
    device work IS the Gramian); ``ld``/``assoc`` swap the Gramian
    shape/exactness/HBM proofs for their own per-site kernel proofs —
    they never allocate an N×N accumulator, so rejecting an LD plan for a
    Gramian-only bound would be a false contract."""
    if analysis not in ANALYSIS_SURFACES:
        raise ValueError(
            f"analysis {analysis!r} is not one of "
            + "|".join(sorted(ANALYSIS_SURFACES))
        )
    report = PlanReport()
    if analysis != "pca":
        report.geometry["analysis"] = analysis
    if plan_devices is not None:
        # The device count every device-bound check below ran against —
        # with executor slices (serve/daemon.py) this is the TARGET
        # SLICE's count, not the whole pod's, so a rejection body says
        # which budget the job actually failed.
        report.geometry["plan_devices"] = int(plan_devices)
    if host_mem_budget is not None and host_mem_budget <= 0:
        report.error(
            "host-mem-budget",
            f"--host-mem-budget must be a positive byte count, got "
            f"{host_mem_budget}",
        )
        host_mem_budget = None

    # ---------------------------------------------------------- flag sanity
    if conf.num_reduce_partitions < 1:
        report.error(
            "reduce-partitions",
            f"--num-reduce-partitions must be >= 1, got "
            f"{conf.num_reduce_partitions}",
        )
    if conf.bases_per_partition <= 0:
        report.error(
            "bases-per-partition",
            f"--bases-per-partition must be positive, got "
            f"{conf.bases_per_partition} (shard enumeration would reject it)",
        )
    if conf.block_size < 1:
        report.error(
            "block-size", f"--block-size must be >= 1, got {conf.block_size}"
        )
    if conf.num_pc < 1:
        report.error("num-pc", f"--num-pc must be >= 1, got {conf.num_pc}")
    elif conf.num_pc > conf.num_samples and analysis == "pca":
        # Only the PCA pipeline eigensolves; the analyses ride the PCA
        # flag surface but never call compute_pca, so a defaulted --num-pc
        # must not reject a 1-sample GRM/LD/assoc run.
        report.error(
            "num-pc-exceeds-cohort",
            f"--num-pc {conf.num_pc} exceeds the cohort size "
            f"{conf.num_samples}: the eigensolve cannot produce more "
            "components than samples",
        )
    if conf.ingest == "device" and conf.source != "synthetic":
        report.error(
            "device-ingest-source",
            f"--ingest device requires --source synthetic "
            f"(got --source {conf.source}); the fused on-device generator "
            "has no data plane for file/REST inputs",
        )
    if conf.ingest == "device" and conf.pca_backend != "tpu":
        report.error(
            "device-ingest-backend",
            "--ingest device requires --pca-backend tpu",
        )
    try:
        # Programmatic PcaConf construction bypasses argparse's choices;
        # validate through the ONE runtime resolver, never a copied set.
        from spark_examples_tpu.ops.gramian import resolve_ring_pack

        resolve_ring_pack(getattr(conf, "ring_pack_bits", "auto"))
    except ValueError as e:
        report.error("ring-pack-bits", str(e))
    try:
        from spark_examples_tpu.parallel.mesh import resolve_reduce_schedule

        resolve_reduce_schedule(getattr(conf, "reduce_schedule", "auto"), 1)
    except ValueError as e:
        report.error("reduce-schedule", str(e))
    if sched_budget_seconds is not None and topology is None:
        report.error(
            "sched-budget-seconds",
            "--sched-budget-seconds needs --topology: a critical-path "
            "budget is a claim about a specific pod's link bandwidths",
        )

    # Robustness flags (pipeline/checkpoint.py + utils/faults.py): a
    # checkpointed whole-genome run that only discovers its resume flags
    # are incoherent AFTER the preemption is the worst possible time.
    checkpointing = bool(
        getattr(conf, "gramian_checkpoint_dir", None)
        or getattr(conf, "resume_from", None)
    )
    if checkpointing and conf.pca_backend != "tpu":
        report.error(
            "checkpoint-backend",
            "--gramian-checkpoint-dir/--resume-from snapshot the DEVICE "
            "accumulator; they need --pca-backend tpu",
        )
    if checkpointing and conf.ingest == "device":
        report.error(
            "checkpoint-device-ingest",
            "--ingest device has no host-fed row cursor to checkpoint or "
            "resume; use --ingest packed or wire (auto falls back for "
            "checkpointed runs)",
        )
    every = getattr(conf, "checkpoint_every_sites", None)
    if every is not None and every < 1:
        report.error(
            "checkpoint-every-sites",
            f"--checkpoint-every-sites must be >= 1, got {every}",
        )
    elif every is not None and not getattr(
        conf, "gramian_checkpoint_dir", None
    ):
        report.warn(
            "checkpoint-every-sites",
            "--checkpoint-every-sites without --gramian-checkpoint-dir "
            "has nothing to snapshot; the cadence is ignored",
        )
    fault_plan = getattr(conf, "fault_plan", None)
    if fault_plan is not None:
        try:
            from spark_examples_tpu.utils.faults import parse_plan

            parse_plan(fault_plan)
        except ValueError as e:
            report.error("fault-plan", str(e))

    # Observability flags: nonsense here only surfaces at the END of an
    # hours-long run (the heartbeat thread refusing to start, or the
    # manifest write failing after the epilogue) — exactly the class of
    # error the plan validator exists to catch up front. The parse path
    # rejects a negative heartbeat too; this validates programmatic
    # PcaConf construction, which bypasses _from_namespace.
    if conf.heartbeat_seconds < 0:
        report.error(
            "heartbeat-seconds",
            f"--heartbeat-seconds must be >= 0 (0 = off), got "
            f"{conf.heartbeat_seconds}",
        )
    if conf.metrics_json:
        import os

        parent = os.path.dirname(os.path.abspath(conf.metrics_json)) or "."
        if not os.path.isdir(parent):
            report.error(
                "metrics-json-parent",
                f"--metrics-json {conf.metrics_json}: parent directory "
                f"{parent} does not exist; the run manifest write would "
                "fail AFTER the run completed",
            )
        elif not os.access(parent, os.W_OK):
            report.error(
                "metrics-json-parent",
                f"--metrics-json {conf.metrics_json}: parent directory "
                f"{parent} is not writable; the run manifest write would "
                "fail AFTER the run completed",
            )
        elif os.path.isdir(conf.metrics_json):
            report.error(
                "metrics-json-parent",
                f"--metrics-json {conf.metrics_json} is a directory; the "
                "manifest needs a file path",
            )

    # -------------------------------------------------------- shard windows
    n_shards: Optional[int] = None
    if not conf.all_references and conf.bases_per_partition > 0:
        try:
            contig_lists = conf.get_references()
        except (ValueError, TypeError) as e:
            report.error("references-grammar", f"--references: {e}")
        else:
            n_shards = sum(
                len(contig.get_shards(conf.bases_per_partition))
                for contigs in contig_lists
                for contig in contigs
            )
            report.geometry["shard_windows"] = n_shards
            if n_shards == 0:
                report.error(
                    "no-shards",
                    "--references yields zero shard windows: nothing to "
                    "ingest",
                )

    # ------------------------------------------------------------- the mesh
    axes = _resolve_mesh_axes(conf, plan_devices, report)
    if axes is None:
        return report
    data, samples = axes
    report.geometry["mesh"] = f"data={data}, samples={samples}"
    report.geometry["devices_needed"] = data * samples

    sharded = conf.similarity_strategy == "sharded"
    if sharded and samples < 2:
        report.error(
            "sharded-needs-samples-axis",
            "--similarity-strategy sharded needs a mesh samples axis of at "
            f"least 2, resolved mesh has samples={samples} "
            "(use --mesh-shape data,samples)",
        )
    if getattr(conf, "reduce_schedule", "auto") == "hier" and conf.mesh_shape:
        # hier serves BOTH ingest families — the host-fed accumulators and
        # the fused generation ring (``ops/devicegen.py:_ring_update`` runs
        # the two-level tile exchange when its mesh carries a host axis) —
        # so device ingest no longer rejects it. What IS statically
        # checkable is the factorization invariant: the host factor must
        # divide the DECLARED samples axis (without --mesh-shape the
        # topology implies the mesh and divides by construction). Offline,
        # the factor is the declared topology's host count, else the
        # rehearsal env override; absent both it is a runtime fact (the
        # process count) that ``resolve_hier_hosts`` enforces loudly at
        # accumulator construction.
        import os

        from spark_examples_tpu.parallel.mesh import HIER_HOSTS_ENV

        hier_hosts = None
        if topology is not None:
            hier_hosts = int(topology.hosts)
        else:
            env = os.environ.get(HIER_HOSTS_ENV, "")
            if env.isdigit():
                hier_hosts = int(env)
        if hier_hosts is not None and hier_hosts > 1 and samples % hier_hosts:
            report.error(
                "hier-hosts-samples-axis",
                f"--reduce-schedule hier needs the host factor "
                f"({hier_hosts}) to divide the mesh samples axis "
                f"({samples}); choose a mesh whose samples axis is a "
                "multiple of the host count",
            )
    if n_shards is not None and n_shards < data:
        report.warn(
            "data-axis-starvation",
            f"only {n_shards} shard window(s) feed a data axis of {data}; "
            "blocks stripe across the staging buffer so devices still "
            "receive work, but the data-parallel speedup is bounded by "
            "the window count",
        )

    # -------------------------------------- analyses admission gate (if any)
    if analysis != "pca":
        _check_analysis(report, conf, analysis, samples)

    # ----------------------------------------- abstract kernel shape proofs
    # GRM's device work IS the Gramian accumulation (analyses/grm.py rides
    # the full driver), so pca and grm prove the Gramian kernels; ld and
    # assoc never allocate an N×N accumulator — they prove their own
    # per-site kernels instead.
    gramian_like = analysis in ("pca", "grm")
    if conf.pca_backend == "tpu" and gramian_like:
        if report.ok:
            _eval_dense_update(report, data, conf)
        if report.ok and conf.fused_jobs is not None:
            if conf.fused_jobs < 1:
                report.error(
                    "fused-jobs-invalid",
                    f"--fused-jobs must be >= 1, got {conf.fused_jobs}",
                )
            else:
                _eval_stacked_update(report, conf.fused_jobs, conf)
        ring_trace = None
        if report.ok and (sharded or samples >= 2):
            ring_trace = _eval_sharded_update(report, data, samples, conf)
        # ------------------------------------ range/exactness proofs (GRnnn)
        if report.ok:
            _check_exactness(
                report, data, samples, conf, ring_trace=ring_trace
            )
    if conf.pca_backend == "tpu" and not gramian_like and report.ok:
        _eval_analysis_kernels(report, conf, analysis, data, samples)

    # ----------------------------------------- schedule proof (if declared)
    if topology is not None and report.ok:
        if (
            conf.pca_backend == "tpu"
            and gramian_like
            and conf.similarity_strategy != "dense"
        ):
            _check_schedule(
                report,
                conf,
                topology,
                data,
                samples,
                sched_budget_seconds,
                plan_devices,
            )
        else:
            # No collective reduction exists to prove: host backend and
            # the per-site analyses dispatch no ring, and an EXPLICIT
            # dense strategy pins the replicated accumulator even on the
            # pod (auto would resolve sharded there, so auto still
            # proves).
            why = (
                "--pca-backend host"
                if conf.pca_backend != "tpu"
                else (
                    f"--analysis {analysis}"
                    if not gramian_like
                    else "--similarity-strategy dense"
                )
            )
            if sched_budget_seconds is not None:
                # A declared budget the configuration cannot prove is a
                # rejection, never a silent pass (the --host-mem-budget
                # rule).
                report.error(
                    "sched-budget-unprovable",
                    "--sched-budget-seconds declares a schedule-limited "
                    "budget, but this configuration dispatches no "
                    f"collective reduction to prove ({why} has no ring "
                    "schedule); drop the budget or validate a ring-"
                    "bearing tpu configuration",
                )
            else:
                report.warn(
                    "sched-not-applicable",
                    f"--topology {topology.describe()} declared, but "
                    f"this configuration dispatches no collective "
                    f"reduction ({why}) — no schedule facts to prove",
                )

    # --------------------------------------------------- memory feasibility
    from spark_examples_tpu.ops.gramian import (
        _DEFAULT_DEVICE_BYTES,
        _DENSE_BUFFERS,
        DENSE_HBM_FRACTION,
    )

    N = int(conf.num_samples)
    accum_bytes = 4
    dense_need = _DENSE_BUFFERS * N * N * accum_bytes
    if gramian_like:
        report.geometry["dense_accumulator_bytes_per_device"] = (
            N * N * accum_bytes
        )
    staging = data * conf.block_size * N
    report.geometry["host_staging_bytes"] = staging
    _check_host_memory(conf, plan_devices, host_mem_budget, report)
    if not gramian_like:
        # LD/assoc never build the Gramian: no dense-HBM rule to apply.
        return report
    if not sharded and conf.similarity_strategy == "dense":
        # Explicit dense: validate against the default HBM budget (the
        # validator must not query real devices; the run's auto rule reads
        # memory_stats when available). Auto configs fall back to sharded
        # at run time, so only the EXPLICIT flag can be infeasible.
        if dense_need > DENSE_HBM_FRACTION * _DEFAULT_DEVICE_BYTES:
            report.error(
                "dense-exceeds-hbm",
                f"--similarity-strategy dense with N={N} needs ~"
                f"{dense_need / (1 << 30):.1f} GiB of working buffers per "
                f"device, past {DENSE_HBM_FRACTION:.0%} of the "
                f"{_DEFAULT_DEVICE_BYTES >> 30} GiB default budget; use "
                "the sharded strategy (and a samples axis)",
            )
    if conf.fused_jobs is not None and conf.fused_jobs >= 1:
        # The stacked program's HBM liveness is K× the per-job dense
        # liveness (K accumulator lanes resident at once, same working
        # buffers per lane) — the rejection that caps a batch group's
        # size BEFORE devices are touched. The group ceiling rides the
        # geometry either way, so serve admission and graftcheck plan
        # agree on the largest K a cohort admits.
        from spark_examples_tpu.ops.batched import max_fused_jobs

        K = int(conf.fused_jobs)
        fused_need = K * dense_need
        ceiling = max_fused_jobs(N, accum_bytes=accum_bytes)
        report.geometry["fused_jobs"] = K
        report.geometry["max_fused_jobs"] = ceiling
        report.geometry["fused_group_hbm_bytes"] = fused_need
        if fused_need > DENSE_HBM_FRACTION * _DEFAULT_DEVICE_BYTES:
            report.error(
                "fused-group-exceeds-hbm",
                f"a fused group of {K} jobs with N={N} needs ~"
                f"{fused_need / (1 << 30):.1f} GiB of stacked working "
                f"buffers per device, past {DENSE_HBM_FRACTION:.0%} of "
                f"the {_DEFAULT_DEVICE_BYTES >> 30} GiB default budget "
                f"(this cohort admits at most {ceiling} fused job(s)); "
                "shrink the group or serve the jobs serially",
            )
    return report


def predict_job_cost(
    conf: PcaConf,
    topology=None,
    *,
    kind: str = "pca",
    plan_devices: Optional[int] = None,
    geometry: Optional[Dict] = None,
):
    """One job's admission-time :class:`~spark_examples_tpu.obs.costmodel.
    CostPrediction`, assembled from the SAME geometry facts the plan
    validator proves — plan, serve admission, and bench share this ONE
    estimator, so a prediction printed by ``graftcheck plan`` and one
    stamped on a served job can never disagree.

    ``geometry`` short-circuits re-validation: serve admission already
    ran :func:`validate_plan` and passes ``report.geometry`` straight in
    (one validation per job, not two). Without it, this validates the
    plan itself (``topology`` adds the schedule simulator's critical-path
    term). The prediction is always produced, even for a plan with
    findings — a cost estimate is telemetry, not a gate; admission
    rejects on the findings separately."""
    from spark_examples_tpu.obs.costmodel import (
        COMPILE_COLD,
        COMPILE_WARM,
        CostPrediction,
        estimate_seconds,
    )
    from spark_examples_tpu.utils.cache import (
        compile_fingerprint,
        geometry_seen,
    )

    if geometry is None:
        analysis = kind if kind in ANALYSIS_SURFACES else "pca"
        report = validate_plan(
            conf,
            plan_devices=plan_devices,
            analysis=analysis,
            topology=topology,
        )
        geometry = report.geometry

    fingerprint = compile_fingerprint(conf, kind=kind)
    warm = geometry_seen(fingerprint)
    sites = _static_site_rows(conf)
    host_peak = geometry.get("host_peak_bytes")
    if host_peak is None:
        from spark_examples_tpu.check.hostmem import conf_host_peak_bytes

        try:
            host_peak = conf_host_peak_bytes(conf, device_count=plan_devices)
        except Exception:
            host_peak = None
    sched_seconds = geometry.get("sched_critical_path_seconds")
    ring_bytes = geometry.get("ring_bytes_per_flush")
    model = estimate_seconds(
        sites=sites,
        host_peak_bytes=None if host_peak is None else int(host_peak),
        sched_seconds=(
            None if sched_seconds is None else float(sched_seconds)
        ),
        cold=not warm,
    )
    return CostPrediction(
        predicted_seconds=model["predicted_seconds"],
        kind=str(kind),
        fingerprint=fingerprint,
        compile=COMPILE_WARM if warm else COMPILE_COLD,
        compute_seconds=model["compute_seconds"],
        sched_seconds=(
            None if sched_seconds is None else float(sched_seconds)
        ),
        sites=sites,
        host_peak_bytes=None if host_peak is None else int(host_peak),
        ring_bytes_per_flush=(
            None if ring_bytes is None else int(ring_bytes)
        ),
    )


__all__ = [
    "ANALYSIS_SURFACES",
    "PlanIssue",
    "PlanReport",
    "parse_plan_args",
    "predict_job_cost",
    "validate_plan",
]

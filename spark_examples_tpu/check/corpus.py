"""The deterministic VCF fuzz corpus.

One corpus, two consumers, pinned together by construction:

- ``tests/test_files_fuzz.py`` replays it through the native↔Python parser
  parity check (alongside — not instead of — the hypothesis fuzzing, which
  needs an optional dependency this corpus does not);
- ``graftcheck sanitize`` (``check/sanitize.py``) replays the same
  documents through the ASAN/UBSAN/TSAN harness binary, so the memory- and
  race-safety claims are checked over exactly the grammar surface the
  parity tests exercise.

The generator mirrors ``test_files_fuzz.py:_vcf_documents`` (same grammar,
same adversarial AF spellings) with a seeded ``random.Random`` instead of
hypothesis draws, plus handwritten edge documents the random grammar cannot
reach (headerless, truncated, malformed, empty). Deterministic by
construction: the corpus is identical on every machine and every run, so a
sanitizer failure is reproducible by index.
"""

from __future__ import annotations

import random
from typing import List

#: Adversarial AF spellings — the exact list the hypothesis strategy
#: samples (`test_files_fuzz.py:_af_value`); every strtod↔float() edge.
AF_SPELLINGS = [
    "0.5", "1e-3", ".5", "5.", "+0.25", "-0", "0,0.5", "junk", "",
    "0.2_5", "0.5 ", " 0.5", "0x1A", "inf", "nan", "1e999",
    "0." + "1" * 70, "0.5" + " " * 61,
]

_INFO_CHOICES = [".", "DB", "NS=3;DP=14", "XAF=9"]
_FORMATS = ["GT", "GT:DP", "DP:GT", "DP"]
_CONTIGS = ["1", "17", "chr2", "X"]
_REFS = ["A", "AT", "GCC"]
_ALTS = [".", "G", "G,T"]


def _random_document(rng: random.Random) -> str:
    """One grammar-conforming VCF document (mirrors ``_vcf_documents``)."""
    n_samples = rng.randint(0, 5)
    n_records = rng.randint(0, 12)
    crlf = rng.random() < 0.5
    lines = ["##fileformat=VCFv4.2"]
    header = "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT" + "".join(
        f"\tS{i}" for i in range(n_samples)
    )
    if n_samples == 0:
        header = header[: header.rindex("\tFORMAT")]
    lines.append(header)
    for r in range(n_records):
        info = rng.choice(
            _INFO_CHOICES
            + [f"AF={rng.choice(AF_SPELLINGS)}"]
            + [f"NS=2;AF={rng.choice(AF_SPELLINGS)};DB"]
        )
        fields = [
            rng.choice(_CONTIGS),
            str(rng.randint(1, 10_000)),
            rng.choice([".", f"rs{r}"]),
            rng.choice(_REFS),
            rng.choice(_ALTS),
            ".",
            ".",
            info,
        ]
        if n_samples:
            fmt = rng.choice(_FORMATS)
            fields.append(fmt)
            n_cols = rng.choice([n_samples, max(0, n_samples - 1)])
            for _ in range(n_cols):
                alleles = [
                    rng.choice([".", str(rng.randint(0, 12))])
                    for _ in range(rng.randint(1, 3))
                ]
                gt = rng.choice(["/", "|"]).join(alleles)
                fields.append(
                    {"GT": gt, "GT:DP": f"{gt}:7", "DP:GT": f"7:{gt}", "DP": "7"}[
                        fmt
                    ]
                )
        lines.append("\t".join(fields))
    eol = "\r\n" if crlf else "\n"
    return eol.join(lines) + eol


def _edge_documents() -> List[str]:
    """Handwritten documents outside the random grammar: the boundary and
    malformed shapes where parser memory errors historically live."""
    big_gt = "|".join(["1"] * 64)
    return [
        "",  # empty buffer
        "\n\n\n",  # blank lines only
        "##meta only, no header\n",
        # Headerless (data before #CHROM): empty cohort, still parsed.
        "17\t100\t.\tA\tG\t.\t.\tAF=0.5\n",
        # Single-'#' comment before the header (the ADVICE.md regression).
        "# a bare comment\n#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO"
        "\tFORMAT\tS0\n17\t100\t.\tA\tG\t.\t.\t.\tGT\t0|1\n",
        # Malformed: < 8 fields — the parser must report, not overrun.
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n17\t100\tonly\n",
        # Malformed POS (non-numeric / zero / huge).
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\nX\tNaN\t.\tA\t.\t."
        "\t.\t.\n",
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\nX\t0\t.\tA\t.\t.\t."
        "\t.\n",
        # No trailing newline on the final data line.
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\tS0\n"
        "1\t5\t.\tA\tG\t.\t.\tAF=1e-3\tGT\t1/1",
        # Truncated mid-field (simulates a torn read).
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\tS0\n"
        "1\t5\t.\tA\tG\t.\t.\tAF=0.",
        # More sample columns than the header declared.
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\tS0\n"
        "1\t5\t.\tA\tG\t.\t.\t.\tGT\t0|1\t1|1\t1/0\n",
        # FORMAT without GT; GT index past the sample subfields.
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\tS0\n"
        "1\t5\t.\tA\tG\t.\t.\t.\tDP:GQ\t7:99\n",
        # Wide genotype (64 alleles) and a >63-char AF value.
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\tS0\n"
        f"1\t5\t.\tA\tG\t.\t.\tAF={'9' * 80}\tGT\t{big_gt}\n",
        # AF= at the very end of INFO, and empty AF value.
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n"
        "1\t5\t.\tA\tG\t.\t.\tNS=2;AF=\n1\t6\t.\tA\tG\t.\t.\tAF=\n",
        # Repeated #CHROM header mid-file (cohort re-declaration).
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\tS0\n"
        "1\t5\t.\tA\tG\t.\t.\t.\tGT\t0|1\n"
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\tS0\tS1\n"
        "1\t6\t.\tA\tG\t.\t.\t.\tGT\t1|1\t0/0\n",
        # CRLF everywhere including the header.
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\tS0\r\n"
        "1\t5\t.\tA\tG\t.\t.\tAF=0.25\tGT\t1|0\r\n",
    ]


def corpus_documents(n_random: int = 24, seed: int = 20240803) -> List[bytes]:
    """The full corpus: handwritten edges + ``n_random`` seeded grammar
    documents, as bytes ready for file replay. Deterministic for a given
    ``(n_random, seed)`` — the default is THE corpus CI replays."""
    rng = random.Random(seed)
    docs = _edge_documents() + [
        _random_document(rng) for _ in range(n_random)
    ]
    return [d.encode("utf-8") for d in docs]


__all__ = ["AF_SPELLINGS", "corpus_documents"]

"""jaxpr-level kernel auditing (``graftcheck ir``).

The AST linter (``linter.py``) and the plan validator (``plan.py``) stop at
source text and ``eval_shape`` signatures. The properties the packed ring
Gramian actually rests on live one layer down, in the *traced IR*:

- **overlap schedule** — the ring loop issues step k+1's ``ppermute``
  before step k's ``dot_general`` consumes its tile; the two must share NO
  data dependency or XLA serializes ICI against the MXU and the
  communication/compute overlap silently vanishes (GI001). A full ring
  pass must execute exactly ``samples_axis - 1`` permutes — the old
  serialized loop paid one extra, returning each tile to its owner
  (GI006).
- **donation/aliasing** — the accumulator's donation contract is read off
  the traced ``pjit`` eqn's ``donated_invars`` and cross-checked against
  the AST layer's justified ``# graftcheck: disable=GC005`` escape
  hatches, so the two layers cannot drift (GI002): a non-donated update
  needs the justification, a justified disable needs the non-donation.
- **dtype flow** — bit-packed wire tiles must stay ``uint8`` from staging
  (or on-device pack) through every ``ppermute`` until the designated
  unpack (the shift-and-mask), and no ``float64`` may appear anywhere in a
  kernel (GI003/GI004). Kernels are traced under ``enable_x64`` so silent
  weak-type promotions are visible instead of masked by canonicalization.
- **static traffic/liveness** — the ICI bytes the jaxpr moves (ppermute
  operand bytes x scan trip counts x devices) must equal the one audited
  formula ``parallel/mesh.py:ring_traffic_bytes`` that telemetry and the
  plan validator report (GI005), and a static buffer-lifetime walk yields
  peak live bytes per kernel, surfaced as facts here and in
  ``graftcheck plan``.

Everything runs device-free: kernels are traced with ``jax.make_jaxpr``
over ``ShapeDtypeStruct`` operands and ``AbstractMesh`` meshes — the same
staged-verification trick the plan validator uses, pushed from shapes down
to the full IR. The audited constructors are the runtime's own
(``ops/gramian.py:build_sharded_update``, ``ops/gramian.py:_dense_update``,
``ops/devicegen.py:_ring_update``), never re-implementations.
"""

from __future__ import annotations

import ast
import functools
import json
import os
from dataclasses import dataclass, field
from typing import (
    Any,
    Callable,
    Dict,
    Iterator,
    List,
    Optional,
    Sequence,
    Set,
    Tuple,
)

import numpy as np

from spark_examples_tpu.check.rules import Finding, parse_disables

# --------------------------------------------------------------------------
# jaxpr plumbing (version-tolerant: jax.core moved to jax.extend.core).
# --------------------------------------------------------------------------


def _core() -> Any:
    try:
        from jax.extend import core as jcore  # type: ignore[attr-defined]

        if hasattr(jcore, "Var"):
            return jcore
    except ImportError:
        pass
    from jax import core as jcore2  # type: ignore[no-redef]

    return jcore2


def _is_var(v: Any) -> bool:
    return not hasattr(v, "val")  # Literal carries .val; Var does not


def _sub_jaxprs(eqn: Any) -> List[Any]:
    """The inner Jaxpr objects of one eqn's params (pjit/scan/shard_map
    jaxpr=, cond branches=, while cond/body_jaxpr=...)."""
    out: List[Any] = []

    def add(v: Any) -> None:
        if hasattr(v, "jaxpr") and hasattr(v, "consts"):  # ClosedJaxpr
            out.append(v.jaxpr)
        elif hasattr(v, "eqns") and hasattr(v, "invars"):  # bare Jaxpr
            out.append(v)

    for value in eqn.params.values():
        if isinstance(value, (tuple, list)):
            for item in value:
                add(item)
        else:
            add(value)
    return out


def _aval_nbytes(aval: Any) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    return int(np.prod(shape, dtype=np.int64) or 1) * np.dtype(dtype).itemsize


def _walk_eqns(jaxpr: Any, mult: int = 1) -> Iterator[Tuple[Any, int, Any]]:
    """Yield ``(eqn, trip_multiplier, enclosing_jaxpr)`` over every eqn at
    every nesting depth. ``trip_multiplier`` is the product of the lengths
    of enclosing ``scan``s — how many times the eqn executes per call
    (``while`` bodies keep multiplier 1: their trip counts are dynamic, and
    no audited kernel loops with one)."""
    for eqn in jaxpr.eqns:
        yield eqn, mult, jaxpr
        sub_mult = mult
        if eqn.primitive.name == "scan":
            sub_mult = mult * int(eqn.params.get("length", 1))
        for sub in _sub_jaxprs(eqn):
            yield from _walk_eqns(sub, sub_mult)


def _contains_primitive(jaxpr: Any, name: str) -> bool:
    return any(eqn.primitive.name == name for eqn, _, _ in _walk_eqns(jaxpr))


# --------------------------------------------------------------------------
# Intra-body dependency analysis (the GI001 overlap proof).
# --------------------------------------------------------------------------


def _producer_map(jaxpr: Any) -> Dict[Any, int]:
    prod: Dict[Any, int] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.outvars:
            prod[v] = i
    return prod


def _upstream_eqns(jaxpr: Any, start: int, prod: Dict[Any, int]) -> Set[int]:
    """Indices of eqns transitively feeding eqn ``start`` (exclusive)."""
    seen: Set[int] = set()
    frontier = [start]
    while frontier:
        i = frontier.pop()
        for v in jaxpr.eqns[i].invars:
            if not _is_var(v):
                continue
            j = prod.get(v)
            if j is not None and j not in seen:
                seen.add(j)
                frontier.append(j)
    return seen


def _is_dot_eqn(eqn: Any) -> bool:
    if eqn.primitive.name == "dot_general":
        return True
    return any(_contains_primitive(sub, "dot_general") for sub in _sub_jaxprs(eqn))


def _ring_bodies(jaxpr: Any) -> List[Any]:
    """Bodies of scans that contain a ``ppermute`` at their own top level —
    the ring loops (a scan whose permutes are only in NESTED scans is an
    enclosing block loop, not a ring)."""
    bodies = []
    for eqn, _, _ in _walk_eqns(jaxpr):
        if eqn.primitive.name != "scan":
            continue
        for sub in _sub_jaxprs(eqn):
            if any(e.primitive.name == "ppermute" for e in sub.eqns):
                bodies.append(sub)
    return bodies


# --------------------------------------------------------------------------
# Packed-wire dtype flow (GI003).
# --------------------------------------------------------------------------

#: Ops a packed uint8 tile may pass through unchanged (layout/movement).
_PACKED_TRANSPARENT = {
    "broadcast_in_dim",
    "reshape",
    "slice",
    "squeeze",
    "transpose",
    "dynamic_slice",
    "copy",
    "concatenate",
    "expand_dims",
    "rev",
    "ppermute",
    "optimization_barrier",
    "pbroadcast",
}

#: The designated unpack: big-endian shift-and-mask (ops/gramian.py:
#: _unpack_bits). Its output is bit planes, no longer the wire format.
_PACKED_UNPACK = {"shift_right_logical"}

#: Consuming a packed tile with these is a contract violation: the byte
#: lanes would be treated as genotype values (wrong math) or widened
#: before the wire (8x traffic).
_PACKED_VIOLATION = {
    "convert_element_type",
    "dot_general",
    "add",
    "sub",
    "mul",
    "div",
    "reduce_sum",
    "reduce_max",
}


def _map_into_sub(eqn: Any, sub: Any, packed_in: Set[Any]) -> Set[Any]:
    """Positionally map packed eqn operands onto a sub-jaxpr's invars
    (pjit/shard_map/scan all bind operands to inner invars in order)."""
    seeds: Set[Any] = set()
    for outer, inner in zip(eqn.invars, sub.invars):
        if _is_var(outer) and outer in packed_in:
            seeds.add(inner)
    return seeds


def _packed_flow(
    jaxpr: Any,
    seeds: Set[Any],
    emit: Callable[[str], None],
) -> Set[Any]:
    """Forward-propagate wire-format packedness from ``seeds``; returns the
    packed members of ``jaxpr.outvars``. Emits one violation message per
    offending eqn."""
    packed: Set[Any] = set(seeds)
    for eqn in jaxpr.eqns:
        touched = [
            v for v in eqn.invars if _is_var(v) and v in packed
        ]
        subs = _sub_jaxprs(eqn)
        if subs:
            # Map into every sub-jaxpr; packed sub-outvars flow back to the
            # eqn outvars positionally (scan: final carry + ys align).
            for sub in subs:
                inner_seeds = _map_into_sub(eqn, sub, packed)
                if not inner_seeds:
                    continue
                inner_packed_out = _packed_flow(sub, inner_seeds, emit)
                for outer, inner in zip(eqn.outvars, sub.outvars):
                    if _is_var(inner) and inner in inner_packed_out:
                        packed.add(outer)
            continue
        if not touched:
            continue
        name = eqn.primitive.name
        if name in _PACKED_UNPACK:
            continue  # designated unpack — wire format ends here, by design
        if name in _PACKED_TRANSPARENT:
            for ov in eqn.outvars:
                out_dtype = getattr(ov.aval, "dtype", None)
                if out_dtype is not None and np.dtype(out_dtype) != np.uint8:
                    emit(
                        f"packed wire tile widened by {name} to "
                        f"{np.dtype(out_dtype).name} before the designated "
                        "unpack"
                    )
                else:
                    packed.add(ov)
            continue
        if name in _PACKED_VIOLATION:
            detail = name
            if name == "convert_element_type":
                target = np.dtype(eqn.outvars[0].aval.dtype).name
                if target == "uint8":
                    for ov in eqn.outvars:
                        packed.add(ov)
                    continue
                detail = f"convert_element_type to {target}"
            emit(
                f"packed wire tile consumed by {detail} before the "
                "designated unpack (shift-and-mask)"
            )
    return packed


def _ring_wire_seeds(body: Any) -> Set[Any]:
    """The ring body invars that (transitively) feed a ``ppermute`` —
    the carried wire tile, wherever the builder put it in the carry."""
    prod = _producer_map(body)
    used: Set[Any] = set()
    for i, eqn in enumerate(body.eqns):
        if eqn.primitive.name != "ppermute":
            continue
        upstream = _upstream_eqns(body, i, prod) | {i}
        for j in upstream:
            for v in body.eqns[j].invars:
                if _is_var(v):
                    used.add(v)
    return {v for v in body.invars if v in used}


# --------------------------------------------------------------------------
# Static liveness (peak live bytes from buffer lifetimes).
# --------------------------------------------------------------------------


def peak_live_bytes(jaxpr: Any, count_inputs: bool = True) -> int:
    """Static peak of simultaneously-live buffer bytes for one jaxpr.

    A buffer is live from its defining eqn (or entry, for inputs) to its
    last use (program exit for outputs); sub-jaxpr temporaries add their
    own peak at the enclosing eqn, with the sub-jaxpr's inputs excluded
    (they alias the operands already counted outside). Deterministic
    arithmetic over avals — an upper-bound estimate (XLA may fuse
    intermediates away), comparable across kernels and stable across runs,
    which is what a static fact needs.
    """
    n = len(jaxpr.eqns)
    last_use: Dict[Any, int] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if _is_var(v):
                last_use[v] = i
    for v in jaxpr.outvars:
        if _is_var(v):
            last_use[v] = n
    live = 0
    if count_inputs:
        for v in list(jaxpr.invars) + list(jaxpr.constvars):
            if v in last_use:
                live += _aval_nbytes(v.aval)
    peak = live
    for i, eqn in enumerate(jaxpr.eqns):
        sub_peak = max(
            (peak_live_bytes(s, count_inputs=False) for s in _sub_jaxprs(eqn)),
            default=0,
        )
        out_bytes = sum(
            _aval_nbytes(v.aval)
            for v in eqn.outvars
            if last_use.get(v, -1) >= i
        )
        peak = max(peak, live + out_bytes + sub_peak)
        live += out_bytes
        for v in {v for v in eqn.invars if _is_var(v)}:
            if last_use.get(v) == i:
                live -= _aval_nbytes(v.aval)
    return peak


# --------------------------------------------------------------------------
# AST cross-check: which functions carry a justified GC005 disable.
# --------------------------------------------------------------------------


@functools.lru_cache(maxsize=64)
def gc005_justified_functions(module_file: str) -> Set[str]:
    """Names of functions in ``module_file`` whose span contains a
    ``# graftcheck: disable=GC005`` escape hatch — the AST layer's
    justified non-donation sites, which GI002 holds the traced
    ``donated_invars`` against. A whole-file disable returns ``{"*"}``."""
    with open(module_file, "r", encoding="utf-8") as f:
        source = f.read()
    per_line, whole_file = parse_disables(source)
    if "GC005" in whole_file or "all" in whole_file:
        return {"*"}
    lines = {
        ln
        for ln, ids in per_line.items()
        if "GC005" in ids or "all" in ids
    }
    if not lines:
        return set()
    spans: List[Tuple[int, int, str]] = []
    tree = ast.parse(source)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            start = min(
                [node.lineno]
                + [d.lineno for d in node.decorator_list]
            )
            spans.append((start, node.end_lineno or node.lineno, node.name))
    out: Set[str] = set()
    for ln in lines:
        containing = [s for s in spans if s[0] <= ln <= s[1]]
        if containing:
            # Innermost = smallest span.
            containing.sort(key=lambda s: s[1] - s[0])
            out.add(containing[0][2])
    return out


# --------------------------------------------------------------------------
# Kernel specs and the audit itself.
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class DonationSite:
    """Where the GC005 justification for a non-donated kernel must live."""

    module_file: str
    function: str
    relpath: str


@dataclass
class KernelSpec:
    """One kernel x geometry to trace and audit.

    ``build`` returns ``(callable, abstract_args)``; it runs inside
    ``enable_x64`` so int64 operand signatures survive. Ring expectations
    (``samples_axis``, ``ring_passes``, ``rows_per_call``, ``n_local``) are
    the audit's ground truth, taken from the same geometry helpers the
    runtime uses (``parallel/mesh.py:padded_cohort``)."""

    name: str
    build: Callable[[], Tuple[Callable[..., Any], Tuple[Any, ...]]]
    samples_axis: int = 1
    total_devices: int = 1
    packed: bool = False
    ring: bool = False
    ring_passes: int = 1
    rows_per_call: int = 0
    n_local: int = 0
    packed_invars: Tuple[int, ...] = ()
    acc_invar: Optional[int] = 0
    donation: Optional[DonationSite] = None
    liveness_scope: str = "global"


@dataclass
class KernelAudit:
    """The audit result for one kernel: findings + machine-readable facts."""

    name: str
    findings: List[Finding] = field(default_factory=list)
    facts: Dict[str, object] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_json(self) -> Dict[str, object]:
        return {
            "kernel": self.name,
            "ok": self.ok,
            "facts": self.facts,
            "findings": [f.to_json() for f in self.findings],
        }


def _emit(audit: KernelAudit, rule_id: str, detail: str) -> None:
    audit.findings.append(Finding(rule_id, audit.name, 0, 0, detail))


def _find_top_pjit(jaxpr: Any) -> Optional[Any]:
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pjit":
            return eqn
    return None


def _audit_donation(spec: KernelSpec, jaxpr: Any, audit: KernelAudit) -> None:
    if spec.acc_invar is None:
        return
    eqn = _find_top_pjit(jaxpr)
    if eqn is None:
        _emit(
            audit,
            "GI002",
            "kernel has no jitted (pjit) entry point; the accumulator "
            "donation contract cannot be audited",
        )
        return
    acc_var = jaxpr.invars[spec.acc_invar]
    try:
        pos = next(
            i for i, v in enumerate(eqn.invars) if v is acc_var
        )
    except StopIteration:
        _emit(
            audit,
            "GI002",
            "accumulator argument is not an operand of the jitted entry "
            "point — the update cannot be writing it",
        )
        return
    donated_invars = eqn.params.get("donated_invars")
    donated = bool(donated_invars[pos]) if donated_invars else False
    audit.facts["accumulator_donated"] = donated
    justified = False
    if spec.donation is not None:
        names = gc005_justified_functions(spec.donation.module_file)
        justified = "*" in names or spec.donation.function in names
    audit.facts["gc005_disable_present"] = justified
    if not donated and not justified:
        where = (
            f"{spec.donation.relpath}:{spec.donation.function}"
            if spec.donation
            else "the kernel"
        )
        _emit(
            audit,
            "GI002",
            f"accumulator buffer is NOT donated and {where} carries no "
            "justified `# graftcheck: disable=GC005` — donate the buffer "
            "or document the measured reason at the AST layer",
        )
    elif donated and justified:
        _emit(
            audit,
            "GI002",
            f"stale justification: {spec.donation.relpath}:"  # type: ignore[union-attr]
            f"{spec.donation.function} carries a GC005 non-donation "
            "disable but the traced kernel DOES donate the accumulator — "
            "the AST and IR layers have drifted; drop the disable",
        )


def _audit_ring(spec: KernelSpec, jaxpr: Any, audit: KernelAudit) -> None:
    from spark_examples_tpu.parallel.mesh import ring_traffic_bytes

    permute_sites = [
        (eqn, mult)
        for eqn, mult, _ in _walk_eqns(jaxpr)
        if eqn.primitive.name == "ppermute"
    ]
    executions = sum(mult for _, mult in permute_sites)
    expected = spec.ring_passes * (spec.samples_axis - 1)
    audit.facts["permute_executions"] = executions
    audit.facts["permute_executions_expected"] = expected
    if executions != expected:
        _emit(
            audit,
            "GI006",
            f"{executions} ppermute execution(s) per call; the "
            f"double-buffered ring contract is ring_passes x (samples-1) "
            f"= {spec.ring_passes} x {spec.samples_axis - 1} = {expected}",
        )

    # Per-call ICI bytes straight from the IR vs the one audited formula.
    per_device = sum(
        _aval_nbytes(eqn.invars[0].aval) * mult for eqn, mult in permute_sites
    )
    jaxpr_bytes = per_device * spec.total_devices
    # rows_per_call already sums every ring pass's rows (D x K x B for the
    # device-generation dispatch), matching how the runtime feeds the
    # formula per flush/dispatch.
    formula_bytes = ring_traffic_bytes(
        spec.rows_per_call, spec.samples_axis, spec.n_local, spec.packed
    )
    audit.facts["ring_bytes_jaxpr"] = jaxpr_bytes
    audit.facts["ring_bytes_formula"] = formula_bytes
    if jaxpr_bytes != formula_bytes:
        _emit(
            audit,
            "GI005",
            f"traced ring traffic is {jaxpr_bytes} bytes/call but "
            f"parallel/mesh.py:ring_traffic_bytes says {formula_bytes} — "
            "telemetry and plan facts no longer describe this kernel",
        )

    # Wire dtype at every permute (the packed contract's visible edge).
    # Pack width comes from the ONE constant the runtime geometry uses
    # (parallel/mesh.py:RING_PACK_MULTIPLE), never a re-stated literal.
    if spec.packed:
        from spark_examples_tpu.parallel.mesh import RING_PACK_MULTIPLE

        for eqn, _ in permute_sites:
            aval = eqn.invars[0].aval
            if np.dtype(aval.dtype) != np.uint8:
                _emit(
                    audit,
                    "GI003",
                    f"ppermute circulates {np.dtype(aval.dtype).name} "
                    "tiles; the packed wire format is uint8 "
                    f"({RING_PACK_MULTIPLE} genotypes/byte)",
                )
            elif (
                aval.shape
                and aval.shape[-1] != spec.n_local // RING_PACK_MULTIPLE
            ):
                _emit(
                    audit,
                    "GI003",
                    f"ppermute tile trailing dim is {aval.shape[-1]} "
                    f"bytes; the pack-width invariant says "
                    f"n_local/{RING_PACK_MULTIPLE} = "
                    f"{spec.n_local // RING_PACK_MULTIPLE}",
                )

    # Overlap: within each ring body, this step's permute and dot must be
    # mutually unreachable.
    serialized = False
    for body in _ring_bodies(jaxpr):
        prod = _producer_map(body)
        perm_idx = [
            i for i, e in enumerate(body.eqns) if e.primitive.name == "ppermute"
        ]
        dot_idx = [i for i, e in enumerate(body.eqns) if _is_dot_eqn(e)]
        for p in perm_idx:
            p_up = _upstream_eqns(body, p, prod)
            for d in dot_idx:
                d_up = _upstream_eqns(body, d, prod)
                if p in d_up:
                    serialized = True
                    _emit(
                        audit,
                        "GI001",
                        "the ring step's dot_general depends on that "
                        "step's ppermute output — the matmul waits for the "
                        "ICI transfer every step (serialized ring; the "
                        "permute must move NEXT step's tile)",
                    )
                if d in p_up:
                    serialized = True
                    _emit(
                        audit,
                        "GI001",
                        "the ring step's ppermute depends on that step's "
                        "dot_general output — the ICI transfer waits for "
                        "the matmul every step (no overlap)",
                    )
    audit.facts["ring_overlap_independent"] = (
        bool(permute_sites) and not serialized
    )


def _audit_dtypes(spec: KernelSpec, jaxpr: Any, audit: KernelAudit) -> None:
    f64_prims: Set[str] = set()
    for eqn, _, _ in _walk_eqns(jaxpr):
        for ov in eqn.outvars:
            dtype = getattr(ov.aval, "dtype", None)
            if dtype is not None and np.dtype(dtype) == np.float64:
                f64_prims.add(eqn.primitive.name)
    audit.facts["f64_free"] = not f64_prims
    if f64_prims:
        _emit(
            audit,
            "GI004",
            "float64 values produced by: " + ", ".join(sorted(f64_prims)),
        )

    violations: List[str] = []
    seeds = {
        jaxpr.invars[i] for i in spec.packed_invars if i < len(jaxpr.invars)
    }
    if seeds:
        _packed_flow(jaxpr, seeds, violations.append)
    for body in _ring_bodies(jaxpr):
        wire = _ring_wire_seeds(body) if spec.packed else set()
        if wire:
            _packed_flow(body, wire, violations.append)
    for message in sorted(set(violations)):
        _emit(audit, "GI003", message)


def trace_kernel(spec: KernelSpec) -> Any:
    """Trace one kernel spec to its ClosedJaxpr — shared by the IR and
    range audit layers so one geometry pays ONE trace (the plan validator
    hands the same trace to both)."""
    import jax

    with jax.enable_x64(True):
        fn, args = spec.build()
        return jax.make_jaxpr(fn)(*args)


def audit_kernel(spec: KernelSpec, traced: Optional[Any] = None) -> KernelAudit:
    """Trace one kernel spec (or reuse a caller-supplied ``traced``
    ClosedJaxpr from :func:`trace_kernel`) and run every IR audit over its
    jaxpr."""
    audit = KernelAudit(spec.name)
    if traced is not None:
        closed = traced
    else:
        try:
            closed = trace_kernel(spec)
        except Exception as e:  # noqa: BLE001 — any trace failure is the finding
            _emit(
                audit,
                "GI000",
                f"kernel failed to trace: {type(e).__name__}: {e}",
            )
            return audit
    jaxpr = closed.jaxpr
    # Output signature facts: callers (the plan validator) derive their
    # shape checks from THIS trace instead of paying a second one.
    audit.facts["out_shapes"] = [
        list(getattr(a, "shape", ())) for a in closed.out_avals
    ]
    audit.facts["out_dtypes"] = [
        str(getattr(a, "dtype", "?")) for a in closed.out_avals
    ]
    _audit_donation(spec, jaxpr, audit)
    _audit_dtypes(spec, jaxpr, audit)
    if spec.ring:
        _audit_ring(spec, jaxpr, audit)
    scope_jaxpr = jaxpr
    if spec.liveness_scope == "per-device":
        for eqn, _, _ in _walk_eqns(jaxpr):
            if eqn.primitive.name == "shard_map":
                scope_jaxpr = _sub_jaxprs(eqn)[0]
                break
    audit.facts["peak_live_bytes"] = peak_live_bytes(scope_jaxpr)
    audit.facts["liveness_scope"] = spec.liveness_scope
    if traced is None:
        # free trace-time consts before the zero-arrays contract check
        # (a caller-supplied trace is the caller's to free)
        del closed
    return audit


# --------------------------------------------------------------------------
# The shipped audit matrix: the REAL kernels across mesh shapes/flags.
# --------------------------------------------------------------------------


def _gramian_file() -> str:
    from spark_examples_tpu.ops import gramian

    return os.path.abspath(gramian.__file__)


def _devicegen_file() -> str:
    from spark_examples_tpu.ops import devicegen

    return os.path.abspath(devicegen.__file__)


def dense_kernel_spec(data: int, num_samples: int, block_size: int) -> KernelSpec:
    """The dense (replicated N x N) packed update, ``ops/gramian.py:
    _dense_update`` — host blocks arrive bit-packed."""

    def build() -> Tuple[Callable[..., Any], Tuple[Any, ...]]:
        import jax
        import jax.numpy as jnp

        from spark_examples_tpu.ops.gramian import _dense_update
        from spark_examples_tpu.parallel.mesh import RING_PACK_MULTIPLE

        G = jax.ShapeDtypeStruct((data, num_samples, num_samples), jnp.float32)
        X = jax.ShapeDtypeStruct(
            (data, block_size, -(-num_samples // RING_PACK_MULTIPLE)),
            jnp.uint8,
        )
        return (
            lambda g, x: _dense_update(g, x, np.float32, num_samples),
            (G, X),
        )

    return KernelSpec(
        name=f"dense[data={data},N={num_samples},B={block_size}]",
        build=build,
        packed=True,
        packed_invars=(1,),
        acc_invar=0,
        donation=DonationSite(_gramian_file(), "_dense_update", "ops/gramian.py"),
        liveness_scope="global",
    )


def stacked_kernel_spec(
    jobs: int, num_samples: int, block_size: int
) -> KernelSpec:
    """The fused batch executor's stacked-jobs update (``ops/batched.py``):
    the IDENTICAL ``_dense_update`` body with the jobs axis in the batch
    slot — one program accumulating K independent Gramians. A first-class
    audit subject: the serving daemon's fused dispatch runs exactly this
    jaxpr, so its donation/dtype/liveness contracts must hold at group
    geometry, not just at the serial data-axis geometry."""

    def build() -> Tuple[Callable[..., Any], Tuple[Any, ...]]:
        import jax
        import jax.numpy as jnp

        from spark_examples_tpu.ops.gramian import _dense_update
        from spark_examples_tpu.parallel.mesh import RING_PACK_MULTIPLE

        G = jax.ShapeDtypeStruct((jobs, num_samples, num_samples), jnp.float32)
        X = jax.ShapeDtypeStruct(
            (jobs, block_size, -(-num_samples // RING_PACK_MULTIPLE)),
            jnp.uint8,
        )
        return (
            lambda g, x: _dense_update(g, x, np.float32, num_samples),
            (G, X),
        )

    return KernelSpec(
        name=f"stacked[jobs={jobs},N={num_samples},B={block_size}]",
        build=build,
        packed=True,
        packed_invars=(1,),
        acc_invar=0,
        donation=DonationSite(_gramian_file(), "_dense_update", "ops/gramian.py"),
        liveness_scope="global",
    )


def counts_kernel_spec(data: int, num_samples: int, block_size: int) -> KernelSpec:
    """The count-valued (same-set-join) dense update — unpacked by
    necessity, audited for donation and dtype hygiene."""

    def build() -> Tuple[Callable[..., Any], Tuple[Any, ...]]:
        import jax
        import jax.numpy as jnp

        from spark_examples_tpu.ops.gramian import _dense_update_counts

        G = jax.ShapeDtypeStruct((data, num_samples, num_samples), jnp.float32)
        X = jax.ShapeDtypeStruct((data, block_size, num_samples), jnp.uint8)
        return (
            lambda g, x: _dense_update_counts(g, x, np.float32),
            (G, X),
        )

    return KernelSpec(
        name=f"dense-counts[data={data},N={num_samples},B={block_size}]",
        build=build,
        acc_invar=0,
        donation=DonationSite(
            _gramian_file(), "_dense_update_counts", "ops/gramian.py"
        ),
        liveness_scope="global",
    )


def ring_kernel_spec(
    data: int,
    samples: int,
    num_samples: int,
    block_size: int,
    pack: bool,
    exact_int: bool = False,
) -> KernelSpec:
    """The sharded ring-exchange update over an abstract ``data x samples``
    mesh — ``ops/gramian.py:build_sharded_update``, the runtime's own
    constructor."""
    from spark_examples_tpu.parallel.mesh import padded_cohort

    padded = padded_cohort(num_samples, samples, pack=pack)
    n_local = padded // samples

    def build() -> Tuple[Callable[..., Any], Tuple[Any, ...]]:
        import jax
        import jax.numpy as jnp
        from jax.sharding import AbstractMesh

        from spark_examples_tpu.ops.gramian import build_sharded_update
        from spark_examples_tpu.parallel.mesh import (
            DATA_AXIS,
            RING_PACK_MULTIPLE,
            SAMPLES_AXIS,
        )

        mesh = AbstractMesh(((DATA_AXIS, data), (SAMPLES_AXIS, samples)))
        operand = np.int8 if exact_int else np.float32
        accum = jnp.int32 if exact_int else jnp.float32
        update = build_sharded_update(mesh, operand, pack)
        G = jax.ShapeDtypeStruct((data, padded, padded), accum)
        X = jax.ShapeDtypeStruct(
            (data, block_size,
             padded // RING_PACK_MULTIPLE if pack else padded),
            jnp.uint8,
        )
        return update, (G, X)

    wire = "on" if pack else "off"
    return KernelSpec(
        name=(
            f"ring[data={data},samples={samples},N={num_samples},"
            f"B={block_size},pack={wire}]"
        ),
        build=build,
        samples_axis=samples,
        total_devices=data * samples,
        packed=pack,
        ring=True,
        ring_passes=1,
        rows_per_call=data * block_size,
        n_local=n_local,
        packed_invars=(1,) if pack else (),
        acc_invar=0,
        donation=DonationSite(_gramian_file(), "update", "ops/gramian.py"),
        liveness_scope="per-device",
    )


def hier_kernel_spec(
    data: int,
    hosts: int,
    devices_per_host: int,
    num_samples: int,
    block_size: int,
    pack: bool,
    exact_int: bool = False,
) -> KernelSpec:
    """The hierarchical two-level ring update over an abstract
    ``data x hosts x samples`` mesh — ``ops/gramian.py:
    build_hierarchical_update``, the runtime's own constructor. The ring
    contracts hold UNCHANGED with ``samples_axis = hosts x
    devices_per_host``: total permutes are ``(H-1) + H x (D-1) = S - 1``
    (GI006) and total bytes equal ``ring_traffic_bytes`` (GI005) — the
    schedule moves the same data as the flat ring, split across link
    classes (which ``check/sched.py`` proves per level)."""
    from spark_examples_tpu.parallel.mesh import padded_cohort

    samples = hosts * devices_per_host
    padded = padded_cohort(num_samples, samples, pack=pack)
    n_local = padded // samples

    def build() -> Tuple[Callable[..., Any], Tuple[Any, ...]]:
        import jax
        import jax.numpy as jnp
        from jax.sharding import AbstractMesh

        from spark_examples_tpu.ops.gramian import build_hierarchical_update
        from spark_examples_tpu.parallel.mesh import (
            DATA_AXIS,
            HOST_AXIS,
            RING_PACK_MULTIPLE,
            SAMPLES_AXIS,
        )

        mesh = AbstractMesh(
            (
                (DATA_AXIS, data),
                (HOST_AXIS, hosts),
                (SAMPLES_AXIS, devices_per_host),
            )
        )
        operand = np.int8 if exact_int else np.float32
        accum = jnp.int32 if exact_int else jnp.float32
        update = build_hierarchical_update(mesh, operand, pack)
        G = jax.ShapeDtypeStruct((data, padded, padded), accum)
        X = jax.ShapeDtypeStruct(
            (data, block_size,
             padded // RING_PACK_MULTIPLE if pack else padded),
            jnp.uint8,
        )
        return update, (G, X)

    wire = "on" if pack else "off"
    return KernelSpec(
        name=(
            f"hier[data={data},hosts={hosts},devices={devices_per_host},"
            f"N={num_samples},B={block_size},pack={wire}]"
        ),
        build=build,
        samples_axis=samples,
        total_devices=data * samples,
        packed=pack,
        ring=True,
        ring_passes=1,
        rows_per_call=data * block_size,
        n_local=n_local,
        packed_invars=(1,) if pack else (),
        acc_invar=0,
        donation=DonationSite(_gramian_file(), "update", "ops/gramian.py"),
        liveness_scope="per-device",
    )


def devicegen_ring_spec(
    data: int,
    samples: int,
    num_samples: int,
    block_size: int,
    blocks_per_dispatch: int,
    pack: bool = True,
) -> KernelSpec:
    """The fused generate-and-ring-accumulate dispatch,
    ``ops/devicegen.py:_ring_update`` — traced through its unmemoized
    constructor (``__wrapped__``) so the audit neither pollutes nor pins
    the runtime's compile cache."""
    from spark_examples_tpu.parallel.mesh import padded_cohort

    padded = padded_cohort(num_samples, samples, pack=pack)
    n_local = padded // samples

    def build() -> Tuple[Callable[..., Any], Tuple[Any, ...]]:
        import jax
        import jax.numpy as jnp
        from jax.sharding import AbstractMesh

        from spark_examples_tpu.ops.devicegen import _ring_update
        from spark_examples_tpu.parallel.mesh import DATA_AXIS, SAMPLES_AXIS

        mesh = AbstractMesh(((DATA_AXIS, data), (SAMPLES_AXIS, samples)))
        pops = np.zeros(padded, dtype=np.int32)
        update = _ring_update.__wrapped__(
            (0x5EED,),
            pops.tobytes(),
            0xFACADE,
            100,
            0.1,
            None,
            block_size,
            blocks_per_dispatch,
            "int8",
            num_samples,
            padded,
            1,
            mesh,
            None,
            pack,
        )
        G = jax.ShapeDtypeStruct((data, padded, padded), jnp.int32)
        rows = jax.ShapeDtypeStruct((data, 1), jnp.int64)
        kept = jax.ShapeDtypeStruct((data,), jnp.int64)
        offsets = jax.ShapeDtypeStruct((data,), jnp.int64)
        valids = jax.ShapeDtypeStruct((data,), jnp.int64)
        return update, (G, rows, kept, offsets, valids)

    return KernelSpec(
        name=(
            f"devicegen-ring[data={data},samples={samples},N={num_samples},"
            f"B={block_size},K={blocks_per_dispatch},"
            f"pack={'on' if pack else 'off'}]"
        ),
        build=build,
        samples_axis=samples,
        total_devices=data * samples,
        packed=pack,
        ring=True,
        ring_passes=blocks_per_dispatch,
        rows_per_call=data * blocks_per_dispatch * block_size,
        n_local=n_local,
        acc_invar=0,
        donation=DonationSite(
            _devicegen_file(), "_ring_update", "ops/devicegen.py"
        ),
        liveness_scope="per-device",
    )


def devicegen_hier_spec(
    data: int,
    hosts: int,
    devices_per_host: int,
    num_samples: int,
    block_size: int,
    blocks_per_dispatch: int,
    pack: bool = True,
) -> KernelSpec:
    """The fused generation ring under the hierarchical two-level schedule
    — ``ops/devicegen.py:_ring_update`` traced over an abstract
    ``data x hosts x samples`` mesh (the mesh in the memo key selects the
    schedule, exactly as at runtime). The ring contracts hold UNCHANGED
    with ``samples_axis = hosts x devices_per_host``: ``(H-1) + H x (D-1)
    = S - 1`` permutes per pass (GI006) and flat-equal total bytes
    (GI005), split across link classes by ``check/sched.py``."""
    from spark_examples_tpu.parallel.mesh import padded_cohort

    samples = hosts * devices_per_host
    padded = padded_cohort(num_samples, samples, pack=pack)
    n_local = padded // samples

    def build() -> Tuple[Callable[..., Any], Tuple[Any, ...]]:
        import jax
        import jax.numpy as jnp
        from jax.sharding import AbstractMesh

        from spark_examples_tpu.ops.devicegen import _ring_update
        from spark_examples_tpu.parallel.mesh import (
            DATA_AXIS,
            HOST_AXIS,
            SAMPLES_AXIS,
        )

        mesh = AbstractMesh(
            (
                (DATA_AXIS, data),
                (HOST_AXIS, hosts),
                (SAMPLES_AXIS, devices_per_host),
            )
        )
        pops = np.zeros(padded, dtype=np.int32)
        update = _ring_update.__wrapped__(
            (0x5EED,),
            pops.tobytes(),
            0xFACADE,
            100,
            0.1,
            None,
            block_size,
            blocks_per_dispatch,
            "int8",
            num_samples,
            padded,
            1,
            mesh,
            None,
            pack,
        )
        G = jax.ShapeDtypeStruct((data, padded, padded), jnp.int32)
        rows = jax.ShapeDtypeStruct((data, 1), jnp.int64)
        kept = jax.ShapeDtypeStruct((data,), jnp.int64)
        offsets = jax.ShapeDtypeStruct((data,), jnp.int64)
        valids = jax.ShapeDtypeStruct((data,), jnp.int64)
        return update, (G, rows, kept, offsets, valids)

    return KernelSpec(
        name=(
            f"devicegen-hier[data={data},hosts={hosts},"
            f"devices={devices_per_host},N={num_samples},B={block_size},"
            f"K={blocks_per_dispatch},pack={'on' if pack else 'off'}]"
        ),
        build=build,
        samples_axis=samples,
        total_devices=data * samples,
        packed=pack,
        ring=True,
        ring_passes=blocks_per_dispatch,
        rows_per_call=data * blocks_per_dispatch * block_size,
        n_local=n_local,
        acc_invar=0,
        donation=DonationSite(
            _devicegen_file(), "_ring_update", "ops/devicegen.py"
        ),
        liveness_scope="per-device",
    )


#: The default mesh matrix: enough shapes that an axis-size-dependent
#: regression (a hardcoded D, a ragged-width assumption) cannot hide.
DEFAULT_MESHES: Tuple[Tuple[int, int], ...] = ((1, 2), (1, 4), (2, 2))


def default_specs(
    num_samples: int = 64,
    ragged_samples: int = 100,
    block_size: int = 8,
    meshes: Sequence[Tuple[int, int]] = DEFAULT_MESHES,
    topologies: Sequence[Tuple[int, int]] = (),
) -> List[KernelSpec]:
    """The shipped audit matrix: dense + counts kernels per data-axis size,
    the ring kernel over every mesh shape x {packed, unpacked} x
    {aligned, ragged} cohort, and the device-generation ring.
    ``topologies`` (``--topology hosts,devices_per_host`` pairs) append the
    hierarchical two-level kernel per topology, packed + unpacked — the
    same GI contracts proven on the pod-scale schedule."""
    specs: List[KernelSpec] = []
    for data in sorted({d for d, _ in meshes}):
        specs.append(dense_kernel_spec(data, num_samples, block_size))
        specs.append(counts_kernel_spec(data, num_samples, block_size))
    # The fused batch groups' stacked program, at a small and a larger
    # group size: same body as dense, jobs axis in the batch slot.
    for jobs in (2, 4):
        specs.append(stacked_kernel_spec(jobs, num_samples, block_size))
    for data, samples in meshes:
        if samples < 2:
            continue
        for pack in (True, False):
            specs.append(
                ring_kernel_spec(data, samples, num_samples, block_size, pack)
            )
        specs.append(
            ring_kernel_spec(data, samples, ragged_samples, block_size, True)
        )
    for data, samples in meshes:
        if samples < 2:
            continue
        specs.append(
            devicegen_ring_spec(data, samples, num_samples, block_size, 2)
        )
    for hosts, per_host in topologies:
        if hosts * per_host < 2:
            continue
        for pack in (True, False):
            specs.append(
                hier_kernel_spec(
                    1, hosts, per_host, num_samples, block_size, pack
                )
            )
        specs.append(
            devicegen_hier_spec(
                1, hosts, per_host, num_samples, block_size, 2
            )
        )
    return specs


@dataclass
class IrReport:
    """Every kernel audit of one ``graftcheck ir`` run."""

    audits: List[KernelAudit] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return all(a.ok for a in self.audits)

    @property
    def findings(self) -> List[Finding]:
        return [f for a in self.audits for f in a.findings]

    def to_json(self) -> str:
        return json.dumps(
            {
                "tool": "graftcheck-ir",
                "ok": self.ok,
                "kernel_count": len(self.audits),
                "finding_count": len(self.findings),
                "kernels": [a.to_json() for a in self.audits],
            },
            indent=2,
        )

    def format(self) -> str:
        lines = []
        for a in self.audits:
            if a.ok:
                bits = []
                if "permute_executions" in a.facts:
                    bits.append(
                        f"permutes {a.facts['permute_executions']}"
                        f"/{a.facts['permute_executions_expected']}"
                    )
                if a.facts.get("ring_overlap_independent"):
                    bits.append("overlap independent")
                if "ring_bytes_jaxpr" in a.facts:
                    bits.append(
                        f"ring bytes {a.facts['ring_bytes_jaxpr']} == formula"
                    )
                if "accumulator_donated" in a.facts:
                    bits.append(
                        "donated"
                        if a.facts["accumulator_donated"]
                        else "non-donation justified"
                    )
                bits.append(
                    f"peak live {a.facts.get('peak_live_bytes', 0)} B "
                    f"({a.facts.get('liveness_scope')})"
                )
                lines.append(f"  audited: {a.name}: " + ", ".join(bits))
            else:
                for f in a.findings:
                    lines.append(f"  {f.format()}")
        verdict = (
            "clean" if self.ok else f"{len(self.findings)} finding(s)"
        )
        lines.append(f"graftcheck ir: {len(self.audits)} kernel(s), {verdict}")
        return "\n".join(lines)


def run_audit(specs: Optional[Sequence[KernelSpec]] = None) -> IrReport:
    """Audit ``specs`` (default: the shipped matrix). Pure tracing — zero
    device buffers survive the call (test-asserted)."""
    report = IrReport()
    for spec in specs if specs is not None else default_specs():
        report.audits.append(audit_kernel(spec))
    return report


__all__ = [
    "DonationSite",
    "IrReport",
    "KernelAudit",
    "KernelSpec",
    "audit_kernel",
    "counts_kernel_spec",
    "default_specs",
    "dense_kernel_spec",
    "devicegen_hier_spec",
    "devicegen_ring_spec",
    "gc005_justified_functions",
    "hier_kernel_spec",
    "peak_live_bytes",
    "ring_kernel_spec",
    "run_audit",
    "stacked_kernel_spec",
    "trace_kernel",
]

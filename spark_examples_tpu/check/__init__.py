"""``graftcheck`` — the static-analysis subsystem.

Six parts, one CLI (``python -m spark_examples_tpu graftcheck ...``),
layered by how deep they look:

- ``lint``   — AST-walking JAX-pitfall linter tuned to this repo
  (``linter.py``; rule catalogue in ``rules.py``). The concurrent ingest
  engine and the device pipeline fail *silently* (host-sync stalls,
  recompilation storms, data races), so the failure classes tier-1 cannot
  observe are pinned as lint rules instead.
- ``ir``     — jaxpr-level kernel auditor (``ir.py``): traces the REAL
  Gramian kernels (dense, ring, device-generation) over ``AbstractMesh``es
  and proves the contracts source text cannot show — the ring's
  communication/compute overlap (D-1 independent ppermutes), the
  accumulator donation contract cross-checked against the AST disables,
  packed-uint8-until-unpack dtype flow, no f64, and jaxpr-derived ring
  traffic equal to ``parallel/mesh.py:ring_traffic_bytes`` exactly.
- ``lockgraph`` — static lock-acquisition-order analysis of the threaded
  ingest/telemetry layer (``lockgraph.py``): rejects order cycles and
  locks held across device syncs / blocking queue ops; emits the graph
  as a DOT artifact.
- ``plan``   — device-free pipeline dry-run (``plan.py``): the full flag
  surface is validated with ``jax.eval_shape`` over ``ShapeDtypeStruct``
  operands and an ``AbstractMesh``, so a 2-hour whole-genome run cannot die
  at minute 90 on a config error.
- ``sanitize`` — ASAN/UBSAN/TSAN replay of the VCF fuzz corpus against the
  native parser (``sanitize.py``), turning the PR-1 concurrency claims into
  continuously-checked invariants.
- ``typecheck`` — baseline-gated mypy, two tiers (``config.py``
  permissive; ``check/`` + ``obs/`` ``--strict``): new type errors fail,
  committed debt does not.
"""

from spark_examples_tpu.check.rules import Finding, Rule, RULES
from spark_examples_tpu.check.linter import lint_paths, lint_source

__all__ = ["Finding", "Rule", "RULES", "lint_paths", "lint_source"]

"""``graftcheck`` — the static-analysis subsystem.

Three parts, one CLI (``python -m spark_examples_tpu graftcheck ...``):

- ``lint``   — AST-walking JAX-pitfall linter tuned to this repo
  (``linter.py``; rule catalogue in ``rules.py``). The concurrent ingest
  engine and the device pipeline fail *silently* (host-sync stalls,
  recompilation storms, data races), so the failure classes tier-1 cannot
  observe are pinned as lint rules instead.
- ``plan``   — device-free pipeline dry-run (``plan.py``): the full flag
  surface is validated with ``jax.eval_shape`` over ``ShapeDtypeStruct``
  operands and an ``AbstractMesh``, so a 2-hour whole-genome run cannot die
  at minute 90 on a config error.
- ``sanitize`` — ASAN/UBSAN/TSAN replay of the VCF fuzz corpus against the
  native parser (``sanitize.py``), turning the PR-1 concurrency claims into
  continuously-checked invariants.
- ``typecheck`` — baseline-gated mypy over ``config.py`` + ``check/``
  (``typecheck.py``): new type errors fail, committed debt does not.
"""

from spark_examples_tpu.check.rules import Finding, Rule, RULES
from spark_examples_tpu.check.linter import lint_paths, lint_source

__all__ = ["Finding", "Rule", "RULES", "lint_paths", "lint_source"]

"""Baseline-gated static typing (``graftcheck typecheck``).

``mypy`` over the typed core, gated by a COMMITTED baseline
(``check/mypy_baseline.txt``): errors present in the baseline are existing
debt and pass; any error NOT in the baseline fails the gate. The baseline
stores normalized lines (``path: severity: message [code]`` — no line
numbers, so unrelated edits that shift lines don't invalidate it). Shrink
the baseline as debt is paid by re-running with ``--update-baseline``.

Two tiers, one gate:

- ``TARGETS`` (``config.py``) run with the permissive flag set — the
  user-facing flag contract, annotated but not yet strict;
- ``STRICT_TARGETS`` (the whole ``check/`` subsystem and ``obs/``) run
  under ``--strict``: the checker that gates everyone else's code and the
  telemetry layer hold themselves to the highest tier.

Images without mypy (the seed image is one) skip with a notice and exit 0
— the lint stage must not fail on a missing optional tool — unless
``--strict`` says the environment is supposed to have it.
"""

from __future__ import annotations

import os
import re
import subprocess
import sys
from typing import List, Optional, Sequence, Tuple  # noqa: F401

_CHECK_DIR = os.path.dirname(os.path.abspath(__file__))
_PACKAGE_DIR = os.path.dirname(_CHECK_DIR)
BASELINE_PATH = os.path.join(_CHECK_DIR, "mypy_baseline.txt")

#: The permissive tier: config parsing (the user-facing contract); the
#: numerics modules earn coverage as annotations land.
TARGETS = (
    os.path.join(_PACKAGE_DIR, "config.py"),
    os.path.join(_PACKAGE_DIR, "parallel", "mesh.py"),
)

#: The ``--strict`` tier: the checker itself (it gates everyone else's
#: code, so it holds itself to the highest standard — ``check/hostmem.py``
#: rides in with the directory), the telemetry subsystem (its
#: registry/manifest types ARE its wire contract), and the ONE windowed
#: stream abstraction (``sources/stream.py`` — every ingest path's
#: residency proof rests on it, so its types are load-bearing).
#: ``parallel/mesh.py`` joins the permissive tier below for its two
#: audited formulas (``ring_traffic_bytes``, ``host_peak_bytes``) whose
#: argument types are plan-validator contract.
STRICT_TARGETS = (
    _CHECK_DIR,
    os.path.join(_PACKAGE_DIR, "obs"),
    os.path.join(_PACKAGE_DIR, "sources", "stream.py"),
)

_MYPY_FLAGS = (
    "--ignore-missing-imports",
    "--no-error-summary",
    "--no-color-output",
    "--hide-error-context",
)

#: ``--strict`` minus the follow-imports noise: strict targets import the
#: (unannotated) numerics modules, whose debt belongs to THEIR tier, not
#: this one.
_STRICT_FLAGS = _MYPY_FLAGS + (
    "--strict",
    "--follow-imports=silent",
)

_LINE_RE = re.compile(r"^(?P<path>[^:]+):(?P<line>\d+):(?:\d+:)?\s*(?P<rest>.*)$")


def _normalize(raw_line: str) -> Optional[str]:
    """``path:123: error: msg [code]`` → ``path: error: msg [code]`` with
    the path made repo-relative (so the committed baseline matches across
    checkouts); None for non-diagnostic lines."""
    m = _LINE_RE.match(raw_line.strip())
    if not m:
        return None
    path = m.group("path")
    if os.path.isabs(path):
        # mypy echoes the absolute TARGETS verbatim; anchor to the repo
        # root (the package's parent) so baselines are machine-portable.
        repo_root = os.path.dirname(_PACKAGE_DIR)
        try:
            path = os.path.relpath(path, repo_root)
        except ValueError:
            pass  # different drive (Windows); keep as-is
    path = path.replace(os.sep, "/")
    if path.startswith("./"):
        path = path[2:]
    return f"{path}: {m.group('rest')}"


def _load_baseline() -> List[str]:
    if not os.path.exists(BASELINE_PATH):
        return []
    with open(BASELINE_PATH, "r", encoding="utf-8") as f:
        return [
            line.strip()
            for line in f
            if line.strip() and not line.startswith("#")
        ]


def _mypy_invocation(
    flags: Sequence[str], targets: Sequence[str]
) -> Optional[Tuple[List[str], str]]:
    cmd = [sys.executable, "-m", "mypy", *flags, *targets]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=600
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        return [f"<mypy invocation failed: {e}>"], str(e)
    if "No module named mypy" in (proc.stderr or ""):
        return None  # not installed (CPython reports it with rc=1)
    if proc.returncode not in (0, 1):
        return (
            [f"<mypy crashed rc={proc.returncode}>"],
            (proc.stderr or proc.stdout or "")[-2000:],
        )
    diagnostics = []
    for line in (proc.stdout or "").splitlines():
        norm = _normalize(line)
        if norm is not None and ": error:" in norm:
            diagnostics.append(norm)
    return diagnostics, proc.stdout or ""


def _run_mypy() -> Optional[Tuple[List[str], str]]:
    """→ (normalized diagnostics from both tiers, raw output), or None when
    mypy is not installed. The strict tier's diagnostics merge into the one
    baseline — a single gate, two strictness levels."""
    base = _mypy_invocation(_MYPY_FLAGS, TARGETS)
    if base is None:
        return None
    strict = _mypy_invocation(_STRICT_FLAGS, STRICT_TARGETS)
    if strict is None:
        return base
    return base[0] + strict[0], base[1] + strict[1]


def run_typecheck(strict: bool = False, update_baseline: bool = False) -> int:
    result = _run_mypy()
    if result is None:
        print(
            "graftcheck typecheck: SKIP (mypy not installed; "
            "`pip install mypy` to enable the gate)"
        )
        return 2 if strict else 0
    diagnostics, _raw = result
    if update_baseline:
        with open(BASELINE_PATH, "w", encoding="utf-8") as f:
            f.write(
                "# mypy baseline for graftcheck typecheck — existing debt,\n"
                "# line-number-free (see check/typecheck.py). Regenerate\n"
                "# with: python -m spark_examples_tpu graftcheck typecheck "
                "--update-baseline\n"
            )
            for line in sorted(set(diagnostics)):
                f.write(line + "\n")
        print(
            f"graftcheck typecheck: baseline updated "
            f"({len(set(diagnostics))} entries)"
        )
        return 0
    baseline = set(_load_baseline())
    new = [d for d in diagnostics if d not in baseline]
    fixed = sorted(baseline - set(diagnostics))
    if fixed:
        print(
            f"graftcheck typecheck: {len(fixed)} baseline entr"
            f"{'y is' if len(fixed) == 1 else 'ies are'} fixed — shrink "
            "check/mypy_baseline.txt (--update-baseline)"
        )
    if new:
        print(f"graftcheck typecheck: {len(new)} NEW error(s):")
        for line in new:
            print(f"  {line}")
        return 1
    print(
        f"graftcheck typecheck: OK ({len(diagnostics)} diagnostic(s), "
        f"all in baseline)"
    )
    return 0


__all__ = ["BASELINE_PATH", "STRICT_TARGETS", "TARGETS", "run_typecheck"]

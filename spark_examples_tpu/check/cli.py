"""The ``graftcheck`` CLI front-end.

Dispatched from the package CLI (``python -m spark_examples_tpu graftcheck
<sub> ...``); subcommand exit codes propagate so ``ci.sh`` stages can gate
on them:

    graftcheck lint [PATH...] [--json]        0 clean / 1 findings
    graftcheck ir [--json] [--mesh D,S ...] [--topology H,D ...]
                  [--num-samples N]
                  [--block-size B]           0 clean / 1 findings
    graftcheck ranges [--json] [--mesh D,S ...] [--topology H,D ...]
                  [--num-samples N]
                  [--block-size B]           0 proven / 1 findings
    graftcheck sched [--json] [--topology H,D ...] [--num-samples N]
                  [--block-size B] [--reduce-schedule auto|flat|hier]
                  [--sched-budget-seconds S] 0 proven / 1 findings
    graftcheck lockgraph [PATH...] [--json] [--dot FILE]
                                              0 acyclic+clean / 1 findings
    graftcheck hostmem [PATH...] [--json]     0 clean (declared sites
                                              allowed) / 1 findings
    graftcheck plan [--analysis pca|grm|ld|assoc] <verb flags>
                  [--plan-devices N]
                  [--host-mem-budget BYTES] [--json]
                                              0 plan OK / 2 rejected
    graftcheck proto [--replicas N] [--jobs N] [--crashes N]
                  [--stalls N] [--max-states N] [--mutations] [--json]
                                              0 clean (or every planted
                                              bug caught) / 1 findings
    graftcheck sanitize [--modes m1,m2] [--strict]
                                              0 clean or skipped / 1 FAIL
    graftcheck typecheck [--strict] [--update-baseline]
                                              0 ok or skipped / 1 new errors
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional, Sequence


def _default_lint_root() -> str:
    """The installed package directory — so ``graftcheck lint`` with no
    argument lints this package regardless of the working directory."""
    import spark_examples_tpu

    return os.path.dirname(os.path.abspath(spark_examples_tpu.__file__))


def _cmd_lint(argv: Sequence[str]) -> int:
    from spark_examples_tpu.check.linter import json_report, lint_paths

    parser = argparse.ArgumentParser(prog="graftcheck lint")
    parser.add_argument(
        "paths",
        nargs="*",
        help="Files or package trees to lint (default: this package).",
    )
    parser.add_argument(
        "--json", action="store_true", help="Emit the machine-readable report."
    )
    ns = parser.parse_args(list(argv))
    paths = ns.paths or [_default_lint_root()]
    for path in paths:
        if not os.path.exists(path):
            print(f"graftcheck lint: no such path {path!r}", file=sys.stderr)
            return 2
    findings, checked = lint_paths(paths)
    if ns.json:
        print(json_report(findings, checked))
    else:
        for f in findings:
            print(f.format())
        verdict = "clean" if not findings else f"{len(findings)} finding(s)"
        print(f"graftcheck lint: {checked} file(s), {verdict}")
    return 1 if findings else 0


def _parse_audit_args(prog: str, argv: Sequence[str], extra=None):
    """The shared ``--json/--mesh/--topology/--num-samples/--block-size``
    surface of the kernel-audit subcommands (``ir``, ``ranges``, and
    ``sched``) — ONE parser, ONE mesh-pair validation, and ONE
    ``--topology hosts,devices_per_host`` spelling, so the three cannot
    drift. ``extra`` (a callback receiving the parser) registers
    subcommand-specific flags before parsing. Returns
    ``(ns, meshes, topologies)`` or ``None`` after printing the grammar
    error."""
    parser = argparse.ArgumentParser(prog=prog)
    parser.add_argument(
        "--json", action="store_true", help="Emit the machine-readable report."
    )
    parser.add_argument(
        "--mesh",
        action="append",
        default=None,
        metavar="D,S",
        help=(
            "Abstract mesh shape(s) to audit (repeatable, e.g. --mesh 1,4 "
            "--mesh 2,2). Default: the shipped matrix (1,2), (1,4), (2,2)."
        ),
    )
    parser.add_argument(
        "--topology",
        action="append",
        default=None,
        metavar="H,D",
        help=(
            "Declared topology (hosts,devices_per_host — repeatable, e.g. "
            "--topology 32,8) to audit the hierarchical two-level schedule "
            "on; the topology never has to exist. ir/ranges append the "
            "hierarchical kernel per topology; sched audits its full "
            "matrix (default: (1,2), (1,4), (2,4), (4,8), (32,8))."
        ),
    )
    parser.add_argument(
        "--num-samples",
        type=int,
        default=64,
        help="Aligned cohort width for the audit geometry (default 64).",
    )
    parser.add_argument(
        "--block-size",
        type=int,
        default=8,
        help="Variant block size for the audit geometry (default 8).",
    )
    if extra is not None:
        extra(parser)
    ns = parser.parse_args(list(argv))
    meshes = None
    if ns.mesh:
        try:
            meshes = tuple(
                tuple(int(p) for p in spec.split(",")) for spec in ns.mesh
            )
            if any(len(m) != 2 or m[0] < 1 or m[1] < 1 for m in meshes):
                raise ValueError(meshes)
        except ValueError:
            print(
                f"{prog}: --mesh expects positive 'data,samples' "
                f"pairs, got {ns.mesh}",
                file=sys.stderr,
            )
            return None
    topologies = None
    if ns.topology:
        from spark_examples_tpu.parallel.mesh import parse_topology

        topologies = []
        for spec in ns.topology:
            try:
                topo = parse_topology(spec)
            except ValueError as e:
                print(f"{prog}: {e}", file=sys.stderr)
                return None
            topologies.append((topo.hosts, topo.devices_per_host))
        topologies = tuple(topologies)
    return ns, meshes, topologies


def _cmd_ir(argv: Sequence[str]) -> int:
    from spark_examples_tpu.check.ir import default_specs, run_audit

    parsed = _parse_audit_args("graftcheck ir", argv)
    if parsed is None:
        return 2
    ns, meshes, topologies = parsed
    specs = default_specs(
        num_samples=ns.num_samples,
        ragged_samples=ns.num_samples + 36,
        block_size=ns.block_size,
        **({"meshes": meshes} if meshes is not None else {}),
        **({"topologies": topologies} if topologies is not None else {}),
    )
    report = run_audit(specs)
    print(report.to_json() if ns.json else report.format())
    return 0 if report.ok else 1


def _cmd_ranges(argv: Sequence[str]) -> int:
    from spark_examples_tpu.check.ranges import default_specs, run_audit

    parsed = _parse_audit_args("graftcheck ranges", argv)
    if parsed is None:
        return 2
    ns, meshes, topologies = parsed
    specs = default_specs(
        num_samples=ns.num_samples,
        block_size=ns.block_size,
        **({"meshes": meshes} if meshes is not None else {}),
        **({"topologies": topologies} if topologies is not None else {}),
    )
    report = run_audit(specs)
    print(report.to_json() if ns.json else report.format())
    return 0 if report.ok else 1


def _cmd_sched(argv: Sequence[str]) -> int:
    from spark_examples_tpu.check.sched import run_audit

    def extra(parser):
        parser.add_argument(
            "--reduce-schedule",
            choices=["auto", "flat", "hier"],
            default="auto",
            help=(
                "Which schedule selection to prove per topology (the "
                "runtime flag's resolution rule; auto = hier iff hosts "
                "> 1). Forcing flat on a multi-host topology demonstrates "
                "GS001."
            ),
        )
        parser.add_argument(
            "--sched-budget-seconds",
            type=float,
            default=None,
            metavar="S",
            help=(
                "Declared critical-path budget per flush: a topology "
                "whose predicted schedule-limited time exceeds it is a "
                "GS005 finding."
            ),
        )

    parsed = _parse_audit_args("graftcheck sched", argv, extra=extra)
    if parsed is None:
        return 2
    ns, meshes, topologies = parsed
    if meshes is not None:
        # A silently-ignored flag would let the user believe they
        # constrained the audit matrix; sched audits topologies, not
        # data x samples meshes.
        print(
            "graftcheck sched: --mesh does not apply here — the schedule "
            "matrix is selected with --topology hosts,devices_per_host",
            file=sys.stderr,
        )
        return 2
    if ns.sched_budget_seconds is not None and ns.sched_budget_seconds <= 0:
        # Same positivity contract graftcheck plan enforces for the flag:
        # a non-positive budget is a usage error, not a GS005 finding on
        # every topology.
        print(
            f"graftcheck sched: --sched-budget-seconds must be positive, "
            f"got {ns.sched_budget_seconds}",
            file=sys.stderr,
        )
        return 2
    report = run_audit(
        topologies=topologies,
        num_samples=ns.num_samples,
        block_size=ns.block_size,
        reduce_schedule=ns.reduce_schedule,
        budget_seconds=ns.sched_budget_seconds,
    )
    print(report.to_json() if ns.json else report.format())
    return 0 if report.ok else 1


def _cmd_lockgraph(argv: Sequence[str]) -> int:
    from spark_examples_tpu.check.lockgraph import (
        build_lock_graph,
        default_lock_paths,
    )

    parser = argparse.ArgumentParser(prog="graftcheck lockgraph")
    parser.add_argument(
        "paths",
        nargs="*",
        help="Files or package trees to analyze (default: this package).",
    )
    parser.add_argument(
        "--json", action="store_true", help="Emit the machine-readable report."
    )
    parser.add_argument(
        "--dot",
        default=None,
        metavar="FILE",
        help="Write the acquisition-order graph as a DOT artifact.",
    )
    ns = parser.parse_args(list(argv))
    paths = ns.paths or default_lock_paths()
    for path in paths:
        if not os.path.exists(path):
            print(
                f"graftcheck lockgraph: no such path {path!r}", file=sys.stderr
            )
            return 2
    graph = build_lock_graph(paths)
    if ns.dot:
        try:
            with open(ns.dot, "w", encoding="utf-8") as f:
                f.write(graph.to_dot())
        except OSError as e:
            print(
                f"graftcheck lockgraph: cannot write --dot {ns.dot!r}: {e}",
                file=sys.stderr,
            )
            return 2
    print(graph.to_json() if ns.json else graph.format())
    return 0 if graph.ok else 1


def _cmd_hostmem(argv: Sequence[str]) -> int:
    from spark_examples_tpu.check.hostmem import (
        audit_paths,
        default_hostmem_paths,
    )

    parser = argparse.ArgumentParser(prog="graftcheck hostmem")
    parser.add_argument(
        "paths",
        nargs="*",
        help=(
            "Files or trees to audit (default: this package's host-staging "
            "layers — sources/, pipeline/, ops/)."
        ),
    )
    parser.add_argument(
        "--json", action="store_true", help="Emit the machine-readable report."
    )
    ns = parser.parse_args(list(argv))
    paths = ns.paths or default_hostmem_paths()
    for path in paths:
        if not os.path.exists(path):
            print(f"graftcheck hostmem: no such path {path!r}", file=sys.stderr)
            return 2
    report = audit_paths(paths)
    print(report.to_json() if ns.json else report.format())
    return 0 if report.ok else 1


def _cmd_plan(argv: Sequence[str]) -> int:
    from spark_examples_tpu.check.plan import parse_plan_args, validate_plan

    try:
        (
            conf,
            plan_devices,
            json_out,
            host_mem_budget,
            analysis,
            topology,
            sched_budget_seconds,
        ) = parse_plan_args(argv)
    except ValueError as e:
        # Cross-flag contract violations from PcaConf._from_namespace are
        # plan rejections in their own right (e.g. --blocks-per-dispatch 0).
        print(f"  ERROR [flag-contract] {e}")
        print("plan REJECTED")
        return 2
    report = validate_plan(
        conf,
        plan_devices,
        host_mem_budget=host_mem_budget,
        analysis=analysis,
        topology=topology,
        sched_budget_seconds=sched_budget_seconds,
    )
    print(report.to_json() if json_out else report.format())
    return 0 if report.ok else 2


def _cmd_proto(argv: Sequence[str]) -> int:
    from spark_examples_tpu.check.proto import (
        check_protocol,
        run_mutation_harness,
    )

    parser = argparse.ArgumentParser(prog="graftcheck proto")
    parser.add_argument(
        "--replicas",
        type=int,
        default=None,
        help="Replica bound for the explored state space (default 2).",
    )
    parser.add_argument(
        "--jobs",
        type=int,
        default=None,
        help="Job bound for the explored state space (default 2).",
    )
    parser.add_argument(
        "--crashes",
        type=int,
        default=None,
        help="Crash budget (process or host crashes, default 2).",
    )
    parser.add_argument(
        "--stalls",
        type=int,
        default=None,
        help=(
            "Lease-clock aging budget: each stall ages one live lease "
            "one notch on the live/lapsed/stale abstract clock "
            "(clean-run default 0 — pair with a --jobs 1 --stalls 2 "
            "run for the expiry/steal dimension; with --mutations, "
            "each planted bug defaults to its own witness bounds)."
        ),
    )
    parser.add_argument(
        "--max-states",
        type=int,
        default=2_000_000,
        help=(
            "Safety cap on explored states; hitting it means the run "
            "was NOT exhaustive and fails (default 2000000)."
        ),
    )
    parser.add_argument(
        "--mutations",
        action="store_true",
        help=(
            "Run the mutation harness instead of the clean check: each "
            "planted single-decision bug must trip its matching GP rule."
        ),
    )
    parser.add_argument(
        "--json", action="store_true", help="Emit the machine-readable report."
    )
    ns = parser.parse_args(list(argv))
    if any(
        bound is not None and bound < floor
        for bound, floor in (
            (ns.replicas, 1),
            (ns.jobs, 1),
            (ns.crashes, 0),
            (ns.stalls, 0),
        )
    ):
        print(
            "graftcheck proto: bounds must be >= 1 replica/job and >= 0 "
            "crashes/stalls",
            file=sys.stderr,
        )
        return 2
    if ns.mutations:
        import json as _json

        outcomes = run_mutation_harness(
            replicas=ns.replicas,
            jobs=ns.jobs,
            crashes=ns.crashes,
            stalls=ns.stalls,
            max_states=ns.max_states,
        )
        if ns.json:
            print(_json.dumps([o.to_json() for o in outcomes], indent=2))
        else:
            for o in outcomes:
                verdict = "caught" if o.caught else "MISSED"
                bounds = ",".join(
                    f"{k}={v}" for k, v in sorted(o.bounds.items())
                )
                print(
                    f"  {verdict:6s} {o.name}: expected {o.expected}, "
                    f"tripped {','.join(o.tripped) or '(none)'} "
                    f"({o.states} states at [{bounds}])"
                )
            caught = sum(1 for o in outcomes if o.caught)
            print(
                f"graftcheck proto: {caught}/{len(outcomes)} planted "
                f"bugs caught"
            )
        return 0 if all(o.caught for o in outcomes) else 1
    report = check_protocol(
        **{
            name: value
            for name, value in (
                ("replicas", ns.replicas),
                ("jobs", ns.jobs),
                ("crashes", ns.crashes),
                ("stalls", ns.stalls),
            )
            if value is not None
        },
        max_states=ns.max_states,
    )
    print(report.to_json() if ns.json else report.format())
    return 0 if report.ok else 1


def _cmd_sanitize(argv: Sequence[str]) -> int:
    from spark_examples_tpu.check.sanitize import DEFAULT_MODES, run_sanitize

    parser = argparse.ArgumentParser(prog="graftcheck sanitize")
    parser.add_argument(
        "--modes",
        default=",".join(DEFAULT_MODES),
        help=f"Comma-separated sanitizer modes (default {','.join(DEFAULT_MODES)}).",
    )
    parser.add_argument(
        "--strict",
        action="store_true",
        help="Fail (not skip) when the toolchain is missing a mode.",
    )
    ns = parser.parse_args(list(argv))
    modes = [m.strip() for m in ns.modes.split(",") if m.strip()]
    return run_sanitize(modes, strict=ns.strict)


def _cmd_typecheck(argv: Sequence[str]) -> int:
    from spark_examples_tpu.check.typecheck import run_typecheck

    parser = argparse.ArgumentParser(prog="graftcheck typecheck")
    parser.add_argument(
        "--strict",
        action="store_true",
        help="Fail (not skip) when mypy is not installed.",
    )
    parser.add_argument(
        "--update-baseline",
        action="store_true",
        help="Rewrite check/mypy_baseline.txt from the current diagnostics.",
    )
    ns = parser.parse_args(list(argv))
    return run_typecheck(strict=ns.strict, update_baseline=ns.update_baseline)


_SUBCOMMANDS = {
    "lint": _cmd_lint,
    "ir": _cmd_ir,
    "ranges": _cmd_ranges,
    "sched": _cmd_sched,
    "lockgraph": _cmd_lockgraph,
    "hostmem": _cmd_hostmem,
    "plan": _cmd_plan,
    "proto": _cmd_proto,
    "sanitize": _cmd_sanitize,
    "typecheck": _cmd_typecheck,
}


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__.strip())
        return 0
    sub, rest = argv[0], argv[1:]
    if sub not in _SUBCOMMANDS:
        print(
            f"graftcheck: unknown subcommand {sub!r} "
            f"(have: {', '.join(sorted(_SUBCOMMANDS))})",
            file=sys.stderr,
        )
        return 2
    return _SUBCOMMANDS[sub](rest)


if __name__ == "__main__":
    raise SystemExit(main())

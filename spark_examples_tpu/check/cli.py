"""The ``graftcheck`` CLI front-end.

Dispatched from the package CLI (``python -m spark_examples_tpu graftcheck
<sub> ...``); subcommand exit codes propagate so ``ci.sh`` stages can gate
on them:

    graftcheck lint [PATH...] [--json]        0 clean / 1 findings
    graftcheck ir [--json] [--mesh D,S ...] [--num-samples N]
                  [--block-size B]           0 clean / 1 findings
    graftcheck ranges [--json] [--mesh D,S ...] [--num-samples N]
                  [--block-size B]           0 proven / 1 findings
    graftcheck lockgraph [PATH...] [--json] [--dot FILE]
                                              0 acyclic+clean / 1 findings
    graftcheck hostmem [PATH...] [--json]     0 clean (declared sites
                                              allowed) / 1 findings
    graftcheck plan [--analysis pca|grm|ld|assoc] <verb flags>
                  [--plan-devices N]
                  [--host-mem-budget BYTES] [--json]
                                              0 plan OK / 2 rejected
    graftcheck sanitize [--modes m1,m2] [--strict]
                                              0 clean or skipped / 1 FAIL
    graftcheck typecheck [--strict] [--update-baseline]
                                              0 ok or skipped / 1 new errors
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional, Sequence


def _default_lint_root() -> str:
    """The installed package directory — so ``graftcheck lint`` with no
    argument lints this package regardless of the working directory."""
    import spark_examples_tpu

    return os.path.dirname(os.path.abspath(spark_examples_tpu.__file__))


def _cmd_lint(argv: Sequence[str]) -> int:
    from spark_examples_tpu.check.linter import json_report, lint_paths

    parser = argparse.ArgumentParser(prog="graftcheck lint")
    parser.add_argument(
        "paths",
        nargs="*",
        help="Files or package trees to lint (default: this package).",
    )
    parser.add_argument(
        "--json", action="store_true", help="Emit the machine-readable report."
    )
    ns = parser.parse_args(list(argv))
    paths = ns.paths or [_default_lint_root()]
    for path in paths:
        if not os.path.exists(path):
            print(f"graftcheck lint: no such path {path!r}", file=sys.stderr)
            return 2
    findings, checked = lint_paths(paths)
    if ns.json:
        print(json_report(findings, checked))
    else:
        for f in findings:
            print(f.format())
        verdict = "clean" if not findings else f"{len(findings)} finding(s)"
        print(f"graftcheck lint: {checked} file(s), {verdict}")
    return 1 if findings else 0


def _parse_audit_args(prog: str, argv: Sequence[str]):
    """The shared ``--json/--mesh/--num-samples/--block-size`` surface of
    the kernel-audit subcommands (``ir`` and ``ranges``) — ONE parser and
    ONE mesh-pair validation, so the two cannot drift. Returns
    ``(ns, meshes)`` or ``None`` after printing the mesh grammar error."""
    parser = argparse.ArgumentParser(prog=prog)
    parser.add_argument(
        "--json", action="store_true", help="Emit the machine-readable report."
    )
    parser.add_argument(
        "--mesh",
        action="append",
        default=None,
        metavar="D,S",
        help=(
            "Abstract mesh shape(s) to audit (repeatable, e.g. --mesh 1,4 "
            "--mesh 2,2). Default: the shipped matrix (1,2), (1,4), (2,2)."
        ),
    )
    parser.add_argument(
        "--num-samples",
        type=int,
        default=64,
        help="Aligned cohort width for the audit geometry (default 64).",
    )
    parser.add_argument(
        "--block-size",
        type=int,
        default=8,
        help="Variant block size for the audit geometry (default 8).",
    )
    ns = parser.parse_args(list(argv))
    meshes = None
    if ns.mesh:
        try:
            meshes = tuple(
                tuple(int(p) for p in spec.split(",")) for spec in ns.mesh
            )
            if any(len(m) != 2 or m[0] < 1 or m[1] < 1 for m in meshes):
                raise ValueError(meshes)
        except ValueError:
            print(
                f"{prog}: --mesh expects positive 'data,samples' "
                f"pairs, got {ns.mesh}",
                file=sys.stderr,
            )
            return None
    return ns, meshes


def _cmd_ir(argv: Sequence[str]) -> int:
    from spark_examples_tpu.check.ir import default_specs, run_audit

    parsed = _parse_audit_args("graftcheck ir", argv)
    if parsed is None:
        return 2
    ns, meshes = parsed
    specs = default_specs(
        num_samples=ns.num_samples,
        ragged_samples=ns.num_samples + 36,
        block_size=ns.block_size,
        **({"meshes": meshes} if meshes is not None else {}),
    )
    report = run_audit(specs)
    print(report.to_json() if ns.json else report.format())
    return 0 if report.ok else 1


def _cmd_ranges(argv: Sequence[str]) -> int:
    from spark_examples_tpu.check.ranges import default_specs, run_audit

    parsed = _parse_audit_args("graftcheck ranges", argv)
    if parsed is None:
        return 2
    ns, meshes = parsed
    specs = default_specs(
        num_samples=ns.num_samples,
        block_size=ns.block_size,
        **({"meshes": meshes} if meshes is not None else {}),
    )
    report = run_audit(specs)
    print(report.to_json() if ns.json else report.format())
    return 0 if report.ok else 1


def _cmd_lockgraph(argv: Sequence[str]) -> int:
    from spark_examples_tpu.check.lockgraph import (
        build_lock_graph,
        default_lock_paths,
    )

    parser = argparse.ArgumentParser(prog="graftcheck lockgraph")
    parser.add_argument(
        "paths",
        nargs="*",
        help="Files or package trees to analyze (default: this package).",
    )
    parser.add_argument(
        "--json", action="store_true", help="Emit the machine-readable report."
    )
    parser.add_argument(
        "--dot",
        default=None,
        metavar="FILE",
        help="Write the acquisition-order graph as a DOT artifact.",
    )
    ns = parser.parse_args(list(argv))
    paths = ns.paths or default_lock_paths()
    for path in paths:
        if not os.path.exists(path):
            print(
                f"graftcheck lockgraph: no such path {path!r}", file=sys.stderr
            )
            return 2
    graph = build_lock_graph(paths)
    if ns.dot:
        try:
            with open(ns.dot, "w", encoding="utf-8") as f:
                f.write(graph.to_dot())
        except OSError as e:
            print(
                f"graftcheck lockgraph: cannot write --dot {ns.dot!r}: {e}",
                file=sys.stderr,
            )
            return 2
    print(graph.to_json() if ns.json else graph.format())
    return 0 if graph.ok else 1


def _cmd_hostmem(argv: Sequence[str]) -> int:
    from spark_examples_tpu.check.hostmem import (
        audit_paths,
        default_hostmem_paths,
    )

    parser = argparse.ArgumentParser(prog="graftcheck hostmem")
    parser.add_argument(
        "paths",
        nargs="*",
        help=(
            "Files or trees to audit (default: this package's host-staging "
            "layers — sources/, pipeline/, ops/)."
        ),
    )
    parser.add_argument(
        "--json", action="store_true", help="Emit the machine-readable report."
    )
    ns = parser.parse_args(list(argv))
    paths = ns.paths or default_hostmem_paths()
    for path in paths:
        if not os.path.exists(path):
            print(f"graftcheck hostmem: no such path {path!r}", file=sys.stderr)
            return 2
    report = audit_paths(paths)
    print(report.to_json() if ns.json else report.format())
    return 0 if report.ok else 1


def _cmd_plan(argv: Sequence[str]) -> int:
    from spark_examples_tpu.check.plan import parse_plan_args, validate_plan

    try:
        conf, plan_devices, json_out, host_mem_budget, analysis = (
            parse_plan_args(argv)
        )
    except ValueError as e:
        # Cross-flag contract violations from PcaConf._from_namespace are
        # plan rejections in their own right (e.g. --blocks-per-dispatch 0).
        print(f"  ERROR [flag-contract] {e}")
        print("plan REJECTED")
        return 2
    report = validate_plan(
        conf, plan_devices, host_mem_budget=host_mem_budget, analysis=analysis
    )
    print(report.to_json() if json_out else report.format())
    return 0 if report.ok else 2


def _cmd_sanitize(argv: Sequence[str]) -> int:
    from spark_examples_tpu.check.sanitize import DEFAULT_MODES, run_sanitize

    parser = argparse.ArgumentParser(prog="graftcheck sanitize")
    parser.add_argument(
        "--modes",
        default=",".join(DEFAULT_MODES),
        help=f"Comma-separated sanitizer modes (default {','.join(DEFAULT_MODES)}).",
    )
    parser.add_argument(
        "--strict",
        action="store_true",
        help="Fail (not skip) when the toolchain is missing a mode.",
    )
    ns = parser.parse_args(list(argv))
    modes = [m.strip() for m in ns.modes.split(",") if m.strip()]
    return run_sanitize(modes, strict=ns.strict)


def _cmd_typecheck(argv: Sequence[str]) -> int:
    from spark_examples_tpu.check.typecheck import run_typecheck

    parser = argparse.ArgumentParser(prog="graftcheck typecheck")
    parser.add_argument(
        "--strict",
        action="store_true",
        help="Fail (not skip) when mypy is not installed.",
    )
    parser.add_argument(
        "--update-baseline",
        action="store_true",
        help="Rewrite check/mypy_baseline.txt from the current diagnostics.",
    )
    ns = parser.parse_args(list(argv))
    return run_typecheck(strict=ns.strict, update_baseline=ns.update_baseline)


_SUBCOMMANDS = {
    "lint": _cmd_lint,
    "ir": _cmd_ir,
    "ranges": _cmd_ranges,
    "lockgraph": _cmd_lockgraph,
    "hostmem": _cmd_hostmem,
    "plan": _cmd_plan,
    "sanitize": _cmd_sanitize,
    "typecheck": _cmd_typecheck,
}


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__.strip())
        return 0
    sub, rest = argv[0], argv[1:]
    if sub not in _SUBCOMMANDS:
        print(
            f"graftcheck: unknown subcommand {sub!r} "
            f"(have: {', '.join(sorted(_SUBCOMMANDS))})",
            file=sys.stderr,
        )
        return 2
    return _SUBCOMMANDS[sub](rest)


if __name__ == "__main__":
    raise SystemExit(main())

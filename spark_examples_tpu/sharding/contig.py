"""Genomic coordinate ranges ("contigs") and their shard math.

The reference delegates this to ``com.google.cloud.genomics.utils.Contig``
(used at ``rdd/VariantsRDD.scala:252-262`` and ``GenomicsConf.scala:59-97``);
the behavior reimplemented here:

- a contig is ``reference_name:[start, end)``;
- ``get_shards(bases_per_shard)`` splits it into fixed-base windows — the
  reference's long-axis ("sequence length") scaling mechanism: whole-genome
  scale means more windows, not bigger ones (``README.md:134-135``);
- ``parse_contigs`` parses the ``--references`` grammar
  ``ref:start:end,ref:start:end,...`` (``GenomicsConf.scala:40-43``);
- ``SexChromosomeFilter.EXCLUDE_XY`` supports ``--all-references``
  (``GenomicsConf.scala:66-73``).

This coordinate axis is the "sequence" dimension of the TPU build: shard
windows are streamed as genotype blocks onto the device mesh's data axis.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Callable, Iterable, List, Optional

#: Default shard width, matching genomics-utils
#: ``Contig.DEFAULT_NUMBER_OF_BASES_PER_SHARD`` (used via
#: ``GenomicsConf.scala:30-32``).
DEFAULT_BASES_PER_SHARD = 1_000_000

#: The default --references value (``GenomicsConf.scala:40``): the BRCA1 gene.
BRCA1 = "17:41196311:41277499"


class SexChromosomeFilter(enum.Enum):
    """``Contig.SexChromosomeFilter`` (used at ``GenomicsConf.scala:26,67``)."""

    INCLUDE_XY = "include_xy"
    EXCLUDE_XY = "exclude_xy"


@dataclass(frozen=True, order=True)
class Contig:
    """A half-open coordinate range on a reference sequence."""

    reference_name: str
    start: int
    end: int

    @property
    def range(self) -> int:
        return self.end - self.start

    def get_shards(self, bases_per_shard: int = DEFAULT_BASES_PER_SHARD) -> List["Contig"]:
        """Split into fixed-width windows (``rdd/VariantsRDD.scala:256-261``)."""
        if bases_per_shard <= 0:
            raise ValueError(f"bases_per_shard must be positive, got {bases_per_shard}")
        shards = []
        pos = self.start
        while pos < self.end:
            shards.append(
                Contig(self.reference_name, pos, min(pos + bases_per_shard, self.end))
            )
            pos += bases_per_shard
        return shards


def parse_contigs(spec: str) -> List[Contig]:
    """Parse ``ref:start:end,...`` (``GenomicsConf.scala:40-43,59-63``)."""
    contigs = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) != 3:
            raise ValueError(
                f"bad contig spec {part!r}: expected reference:start:end"
            )
        contigs.append(Contig(fields[0], int(fields[1]), int(fields[2])))
    return contigs


def partition_contigs_by_host(
    contigs: Iterable[Contig],
    num_hosts: int,
    weight: Optional[Callable[[Contig], int]] = None,
) -> List[List[Contig]]:
    """THE host → contig-partition split of pod-scale ingest: every host
    process of a multi-process run reads ONLY its partition, so a pod's
    aggregate ingest bandwidth scales linearly with hosts while the merged
    Gramian stays byte-identical (``G += XᵀX`` commutes over any partition
    of the row set).

    The split rule — deterministic, contig-ordered, balanced by declared
    sites:

    - contigs are walked IN THE GIVEN ORDER and never reordered or split:
      partitions are contiguous runs, so each host's read pattern stays
      sequential per contig and the concatenation of all partitions is the
      original list (the order every accounting surface assumes);
    - ``weight(contig)`` declares each contig's site count (default: its
      base range — exact for the synthetic source's uniform grid up to
      rounding, the honest prior for files). Host ``h`` closes its
      partition once the cumulative weight reaches the ``(h+1)``-th
      fair-share boundary ``(h+1)·total/H`` — compared in exact integer
      arithmetic (``cum·H >= (h+1)·total``), never floats;
    - TIE RULE: a contig landing cumulative weight EXACTLY on the boundary
      belongs to the EARLIER host (it closes that host's partition) — the
      maximal-prefix reading of "stay within the fair share";
    - zero-weight contigs ride the partition open at their position; when
      EVERY weight is zero the walk degenerates to one contig per host in
      order (extras on the last host) — still deterministic, still
      ordered.

    Every process computes the SAME partition from the same inputs (pure
    integer arithmetic over the shared contig list — no RNG, no
    process-local state), which is what lets H processes agree on the
    split without a collective. Hosts past the contig supply receive empty
    partitions (valid: their partial Gramian is zero).
    """
    if num_hosts < 1:
        raise ValueError(f"num_hosts must be >= 1, got {num_hosts}")
    ordered = list(contigs)
    weigh = weight if weight is not None else (lambda c: max(0, c.range))
    weights = [int(weigh(c)) for c in ordered]
    for c, w in zip(ordered, weights):
        if w < 0:
            raise ValueError(
                f"negative declared weight {w} for contig "
                f"{c.reference_name}:{c.start}:{c.end}"
            )
    total = sum(weights)
    parts: List[List[Contig]] = [[] for _ in range(num_hosts)]
    if total == 0:
        # Every weight zero: no fair share exists to balance, so the walk
        # degenerates to one contig per host in order (extras ride the
        # last host) — deterministic, ordered, and each host still reads
        # a contiguous run.
        for i, c in enumerate(ordered):
            parts[min(i, num_hosts - 1)].append(c)
        return parts
    host = 0
    cum = 0
    for c, w in zip(ordered, weights):
        parts[host].append(c)
        cum += w
        # Exact-integer fair-share comparison; ties close the EARLIER
        # host. The while (not if) lets one giant contig span several
        # fair shares — the hosts it covers simply receive empty
        # partitions (a contig is never split).
        while host < num_hosts - 1 and cum * num_hosts >= (host + 1) * total:
            host += 1
    return parts


def host_partition(
    contigs: Iterable[Contig],
    process_index: int,
    process_count: int,
    weight: Optional[Callable[[Contig], int]] = None,
) -> List[Contig]:
    """This host's slice of :func:`partition_contigs_by_host` — the one
    call sites use (``process_index``/``process_count`` spell the jax
    multi-process identity without importing jax here)."""
    if not 0 <= process_index < process_count:
        raise ValueError(
            f"process_index {process_index} outside [0, {process_count})"
        )
    return partition_contigs_by_host(contigs, process_count, weight)[
        process_index
    ]


_SEX_CHROMOSOMES = frozenset({"X", "Y", "chrX", "chrY", "x", "y"})


def filter_sex_chromosomes(
    contigs: Iterable[Contig], sex_filter: SexChromosomeFilter
) -> List[Contig]:
    """Drop X/Y when ``EXCLUDE_XY`` (the ``--all-references`` behavior,
    ``GenomicsConf.scala:83-97``)."""
    if sex_filter is SexChromosomeFilter.INCLUDE_XY:
        return list(contigs)
    return [c for c in contigs if c.reference_name not in _SEX_CHROMOSOMES]


__all__ = [
    "BRCA1",
    "DEFAULT_BASES_PER_SHARD",
    "Contig",
    "SexChromosomeFilter",
    "filter_sex_chromosomes",
    "host_partition",
    "parse_contigs",
    "partition_contigs_by_host",
]

"""Genomic coordinate ranges ("contigs") and their shard math.

The reference delegates this to ``com.google.cloud.genomics.utils.Contig``
(used at ``rdd/VariantsRDD.scala:252-262`` and ``GenomicsConf.scala:59-97``);
the behavior reimplemented here:

- a contig is ``reference_name:[start, end)``;
- ``get_shards(bases_per_shard)`` splits it into fixed-base windows — the
  reference's long-axis ("sequence length") scaling mechanism: whole-genome
  scale means more windows, not bigger ones (``README.md:134-135``);
- ``parse_contigs`` parses the ``--references`` grammar
  ``ref:start:end,ref:start:end,...`` (``GenomicsConf.scala:40-43``);
- ``SexChromosomeFilter.EXCLUDE_XY`` supports ``--all-references``
  (``GenomicsConf.scala:66-73``).

This coordinate axis is the "sequence" dimension of the TPU build: shard
windows are streamed as genotype blocks onto the device mesh's data axis.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Iterable, List

#: Default shard width, matching genomics-utils
#: ``Contig.DEFAULT_NUMBER_OF_BASES_PER_SHARD`` (used via
#: ``GenomicsConf.scala:30-32``).
DEFAULT_BASES_PER_SHARD = 1_000_000

#: The default --references value (``GenomicsConf.scala:40``): the BRCA1 gene.
BRCA1 = "17:41196311:41277499"


class SexChromosomeFilter(enum.Enum):
    """``Contig.SexChromosomeFilter`` (used at ``GenomicsConf.scala:26,67``)."""

    INCLUDE_XY = "include_xy"
    EXCLUDE_XY = "exclude_xy"


@dataclass(frozen=True, order=True)
class Contig:
    """A half-open coordinate range on a reference sequence."""

    reference_name: str
    start: int
    end: int

    @property
    def range(self) -> int:
        return self.end - self.start

    def get_shards(self, bases_per_shard: int = DEFAULT_BASES_PER_SHARD) -> List["Contig"]:
        """Split into fixed-width windows (``rdd/VariantsRDD.scala:256-261``)."""
        if bases_per_shard <= 0:
            raise ValueError(f"bases_per_shard must be positive, got {bases_per_shard}")
        shards = []
        pos = self.start
        while pos < self.end:
            shards.append(
                Contig(self.reference_name, pos, min(pos + bases_per_shard, self.end))
            )
            pos += bases_per_shard
        return shards


def parse_contigs(spec: str) -> List[Contig]:
    """Parse ``ref:start:end,...`` (``GenomicsConf.scala:40-43,59-63``)."""
    contigs = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) != 3:
            raise ValueError(
                f"bad contig spec {part!r}: expected reference:start:end"
            )
        contigs.append(Contig(fields[0], int(fields[1]), int(fields[2])))
    return contigs


_SEX_CHROMOSOMES = frozenset({"X", "Y", "chrX", "chrY", "x", "y"})


def filter_sex_chromosomes(
    contigs: Iterable[Contig], sex_filter: SexChromosomeFilter
) -> List[Contig]:
    """Drop X/Y when ``EXCLUDE_XY`` (the ``--all-references`` behavior,
    ``GenomicsConf.scala:83-97``)."""
    if sex_filter is SexChromosomeFilter.INCLUDE_XY:
        return list(contigs)
    return [c for c in contigs if c.reference_name not in _SEX_CHROMOSOMES]


__all__ = [
    "BRCA1",
    "DEFAULT_BASES_PER_SHARD",
    "Contig",
    "SexChromosomeFilter",
    "filter_sex_chromosomes",
    "parse_contigs",
]

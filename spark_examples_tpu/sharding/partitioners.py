"""Partitioners: genomic ranges → independent shards ("partitions").

Reference parity:

- ``VariantsPartitioner`` / ``VariantsPartition`` mirror
  ``rdd/VariantsRDD.scala:229-262``: each contig is split into fixed-base
  windows, one partition per window, each carrying the search range for its
  variant set.
- ``ReadsPartitioner`` / ``ReadsPartition`` mirror
  ``rdd/ReadsPartitioner.scala:24-64``: a ``{sequence: (start, end)}`` map is
  split per-sequence by a pluggable :class:`SequenceSplitter` policy
  (``FixedSplits`` / ``TargetSizeSplits``, ``rdd/ReadsPartitioner.scala:69-90``),
  with a stable sequence→starting-partition offset table so partition indices
  are globally unique and ordered by sequence name.
- ``ReadsPartitioner.get_partition`` maps a ``ReadKey`` to its partition index.
  The reference's formula (``rdd/ReadsPartitioner.scala:44``) divides by
  ``len / position`` using the *absolute* position, which misassigns keys for
  ranges not starting at 0; we implement the intended inverse of
  ``get_partitions``' span layout instead (documented divergence — bug fix).

In the TPU build partitions are the unit of host-side streaming: each shard's
records are packed into device blocks and dispatched round-robin onto the mesh
data axis, the moral equivalent of Spark executors pulling their own shards.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Sequence, Tuple

from spark_examples_tpu.sharding.contig import Contig, DEFAULT_BASES_PER_SHARD


@dataclass(frozen=True)
class VariantsPartition:
    """A search range over a contig (``rdd/VariantsRDD.scala:232-240``)."""

    index: int
    variant_set_id: str
    contig: Contig

    def get_variants_request(self) -> Dict:
        """The SearchVariants request body for this shard
        (``rdd/VariantsRDD.scala:235-237``)."""
        return {
            "variantSetIds": [self.variant_set_id],
            "referenceName": self.contig.reference_name,
            "start": self.contig.start,
            "end": self.contig.end,
        }

    @property
    def range(self) -> int:
        return self.contig.range


class VariantsPartitioner:
    """Contigs → fixed-base-window partitions (``rdd/VariantsRDD.scala:252-262``)."""

    def __init__(
        self,
        contigs: Sequence[Contig],
        bases_per_partition: int = DEFAULT_BASES_PER_SHARD,
    ):
        self.contigs = list(contigs)
        self.bases_per_partition = int(bases_per_partition)

    def get_partitions(self, variant_set_id: str) -> List[VariantsPartition]:
        shards = [
            shard
            for contig in self.contigs
            for shard in contig.get_shards(self.bases_per_partition)
        ]
        return [
            VariantsPartition(index, variant_set_id, shard)
            for index, shard in enumerate(shards)
        ]


class SequenceSplitter:
    """How a sequence should be partitioned (``rdd/ReadsPartitioner.scala:69-71``)."""

    def splits(self, sequence_length: int) -> int:
        raise NotImplementedError


@dataclass(frozen=True)
class FixedSplits(SequenceSplitter):
    """A fixed number of partitions (``rdd/ReadsPartitioner.scala:76-78``)."""

    num_splits: int

    def splits(self, sequence_length: int) -> int:
        return int(min(sequence_length, self.num_splits))


@dataclass(frozen=True)
class TargetSizeSplits(SequenceSplitter):
    """Partition count from estimated data volume per base
    (``rdd/ReadsPartitioner.scala:84-90``): bytes ≈ (len / read_length) ×
    read_depth × read_size, divided into ``partition_size`` chunks."""

    read_length: int
    read_depth: int
    read_size: int
    partition_size: int

    def splits(self, sequence_length: int) -> int:
        return 1 + int(
            ((sequence_length // self.read_length) * self.read_depth * self.read_size)
            // (self.partition_size + 1)
        )


@dataclass(frozen=True)
class ReadsPartition:
    """A search range over a named sequence (``rdd/ReadsRDD.scala:123-128``)."""

    index: int
    read_group_set_ids: Tuple[str, ...]
    sequence: str
    start: int
    end: int

    def get_reads_request(self) -> Dict:
        """The SearchReads request body (``rdd/ReadsRDD.scala:111-115``)."""
        return {
            "readGroupSetIds": list(self.read_group_set_ids),
            "referenceName": self.sequence,
            "start": self.start,
            "end": self.end,
        }


class ReadsPartitioner:
    """Sequences → per-sequence span partitions (``rdd/ReadsPartitioner.scala:24-64``)."""

    def __init__(
        self,
        sequences: Dict[str, Tuple[int, int]],
        splitter: SequenceSplitter,
    ):
        self.sequences = dict(sequences)
        self.splitter = splitter
        # Sequence → partition count, ordered by sequence name (the reference
        # uses a TreeMap, ``rdd/ReadsPartitioner.scala:27-28``).
        self.parts: Dict[str, int] = {
            name: splitter.splits(rng[1] - rng[0])
            for name, rng in sorted(self.sequences.items())
        }
        # Total partition count (``:31``).
        self.count = sum(self.parts.values())
        # Sequence → starting partition index (``:34-35``).
        self.steps: Dict[str, int] = {}
        offset = 0
        for name, n in self.parts.items():
            self.steps[name] = offset
            offset += n

    @property
    def num_partitions(self) -> int:
        return self.count

    def get_partition(self, sequence: str, position: int) -> int:
        """Partition index owning ``position`` on ``sequence``.

        Intended inverse of :meth:`get_partitions`' span layout (the
        reference's formula at ``rdd/ReadsPartitioner.scala:44`` is broken for
        ranges not starting at 0 — see module docstring).
        """
        start, end = self.sequences[sequence]
        n = self.parts[sequence]
        span = (end - start) // n
        if span <= 0:
            return self.steps[sequence]
        i = min(n - 1, max(0, (position - start) // span))
        return self.steps[sequence] + int(i)

    def get_partitions(self, read_group_set_ids: Sequence[str]) -> List[ReadsPartition]:
        """All partitions for all sequences (``rdd/ReadsPartitioner.scala:50-63``).

        Matches the reference's layout exactly: each sequence's range is cut
        into ``n`` spans of ``(end - start) / n`` bases (integer division, so
        trailing remainder bases beyond ``start + n*span`` are dropped, as in
        the reference).
        """
        ids = tuple(read_group_set_ids)
        partitions = []
        for name, (start, end) in sorted(self.sequences.items()):
            idx = self.steps[name]
            n = self.parts[name]
            span = (end - start) // n
            for i in range(n):
                s = start + i * span
                partitions.append(ReadsPartition(idx, ids, name, s, s + span))
                idx += 1
        partitions.sort(key=lambda p: p.index)
        return partitions


__all__ = [
    "VariantsPartition",
    "VariantsPartitioner",
    "SequenceSplitter",
    "FixedSplits",
    "TargetSizeSplits",
    "ReadsPartition",
    "ReadsPartitioner",
]

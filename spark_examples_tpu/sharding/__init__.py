from spark_examples_tpu.sharding.contig import (
    BRCA1,
    DEFAULT_BASES_PER_SHARD,
    Contig,
    SexChromosomeFilter,
    parse_contigs,
)
from spark_examples_tpu.sharding.partitioners import (
    FixedSplits,
    ReadsPartition,
    ReadsPartitioner,
    SequenceSplitter,
    TargetSizeSplits,
    VariantsPartition,
    VariantsPartitioner,
)

__all__ = [
    "BRCA1",
    "DEFAULT_BASES_PER_SHARD",
    "Contig",
    "SexChromosomeFilter",
    "parse_contigs",
    "FixedSplits",
    "ReadsPartition",
    "ReadsPartitioner",
    "SequenceSplitter",
    "TargetSizeSplits",
    "VariantsPartition",
    "VariantsPartitioner",
]

"""Composable public API: the PCoA pipeline as library functions.

Mirrors the reference's Python decomposition
(``src/main/python/variants_pca.py:19-152``) — ``prepare_call_data`` →
``calculate_similarity_matrix`` → ``center_matrix`` → ``perform_pca`` — with
the PySpark/py4j machinery replaced by jit-composable device stages. The
full flag-driven driver remains available as :func:`pca` (the counterpart of
``variants_pca.py:pca``, ``:154-201``).

Example (synthetic cohort, BRCA1 region)::

    >>> from spark_examples_tpu import api
    >>> from spark_examples_tpu.sharding.contig import Contig
    >>> from spark_examples_tpu.sources.synthetic import SyntheticGenomicsSource
    >>> source = SyntheticGenomicsSource(num_samples=12, seed=5)
    >>> callsets = source.search_callsets(["vs"])
    >>> id_to_index = {c["id"]: i for i, c in enumerate(callsets)}
    >>> variants = (
    ...     record
    ...     for record in source.client().search_variants(
    ...         {"variantSetIds": ["vs"], "referenceName": "17",
    ...          "start": 41196311, "end": 41216311}
    ...     )
    ... )
    >>> calls = api.prepare_call_data(variants, id_to_index)
    >>> S = api.calculate_similarity_matrix(calls, len(id_to_index))
    >>> B = api.center_matrix(S)
    >>> components = api.perform_pca(B, num_pc=2)
    >>> components.shape
    (12, 2)

Each stage accepts and returns device arrays where possible, so stages fuse
under an enclosing ``jax.jit`` and nothing round-trips through the host
until :func:`perform_pca` returns the (N, num_pc) result.
"""

from __future__ import annotations

from typing import Dict, Iterable, Iterator, List, Mapping, Optional, Sequence

import numpy as np

from spark_examples_tpu.ops.centering import gower_center
from spark_examples_tpu.ops.gramian import (
    GramianAccumulator,
    accumulate_index_rows,
)
from spark_examples_tpu.ops.pca import principal_components_subspace


def prepare_call_data(
    variants: Iterable[Mapping],
    id_to_index: Dict[str, int],
    use_names: bool = True,
) -> Iterator[List[int]]:
    """Wire variant records → per-variant lists of varying column indices.

    The counterpart of ``variants_pca.py:prepare_call_data`` (``:19-52``):
    keep calls with any non-zero genotype, drop empty rows, map callset
    names (or ids, ``use_names=False``) to matrix columns.
    """
    key = "callSetName" if use_names else "callSetId"
    for record in variants:
        calls = record.get("calls", []) if isinstance(record, Mapping) else [
            {
                "callSetName": c.callset_name,
                "callSetId": c.callset_id,
                "genotype": c.genotype,
            }
            for c in (record.calls or [])
        ]
        row = [
            id_to_index[c[key]]
            for c in calls
            # Variation means a strictly positive allele (Call.has_variation,
            # ``VariantsPca.scala:67``) — no-call encodings like -1 don't count.
            if any(g > 0 for g in c["genotype"]) and c[key] in id_to_index
        ]
        if row:
            yield row


def calculate_similarity_matrix(
    call_rows: Iterable[Sequence[int]],
    matrix_size: int,
    block_size: int = 1024,
    mesh=None,
    exact_int: bool = False,
):
    """Per-variant index rows → similarity counts ``G = XᵀX`` on device.

    The counterpart of ``variants_pca.py:calculate_similarity_matrix``
    (``:54-82``), with the per-partition NumPy Gramian + ``reduceByKey``
    replaced by blockwise MXU accumulation (``ops/gramian.py``). Returns the
    device-resident (N, N) matrix.
    """
    acc = GramianAccumulator(
        matrix_size, mesh=mesh, block_size=block_size, exact_int=exact_int
    )
    accumulate_index_rows(acc, call_rows, matrix_size, block_size)
    return acc.finalize_device()


def center_matrix(similarity):
    """Gower double-centering on device, the counterpart of
    ``variants_pca.py:center_matrix`` (``:84-121``) — the row-sums collect,
    broadcast, and per-row centering collapse into one fused kernel
    (``ops/centering.py``).

    The input dtype is preserved into the kernel and the arithmetic runs in
    float64, exactly like the driver path (``pipeline/pca_driver.py:
    compute_pca`` dense branch): integer similarity counts center through
    the ``ops/centering.py:_dtypes`` policy, so counts past f32's 2^24
    exact range stay exact instead of being truncated by an up-front f32
    cast. The output is f32 — the eigensolve's dtype — unless the caller
    passed f64 in."""
    import jax
    import jax.numpy as jnp

    with jax.enable_x64(True):
        return gower_center(jnp.asarray(similarity))


def perform_pca(centered, num_pc: int = 2) -> np.ndarray:
    """Top principal components of the centered similarity matrix, the
    counterpart of ``variants_pca.py:perform_pca`` (``:123-152``): MLlib's
    ``RowMatrix.computePrincipalComponents`` becomes on-device subspace
    iteration (``ops/pca.py``); only the (N, num_pc) result lands on host.
    """
    import jax

    components, _ = principal_components_subspace(centered, num_pc)
    return np.asarray(jax.device_get(components), dtype=np.float64)


def pca(argv: Optional[Sequence[str]] = None) -> List[str]:
    """The full flag-driven pipeline (``variants_pca.py:pca``, ``:154-201``):
    parses the reference's flag grammar, runs the driver end to end, returns
    the emitted TSV lines."""
    from spark_examples_tpu.pipeline.pca_driver import run

    return run(list(argv) if argv is not None else [])


__all__ = [
    "prepare_call_data",
    "calculate_similarity_matrix",
    "center_matrix",
    "perform_pca",
    "pca",
]

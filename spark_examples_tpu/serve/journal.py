"""Append-only job-table journal: accepted jobs survive daemon restarts.

PR 9's watchdog extended the crash story from "a job dies" to "a worker
thread dies"; this journal extends it to "the PROCESS dies". Every
admission decision the daemon acknowledges to a client is durably
recorded BEFORE the 202 leaves the socket, so a SIGKILL'd daemon can be
restarted against the same run directory and finish what it accepted:

- ``accepted`` — the job's wire request document (the same versioned
  protocol form the client posted; replay re-validates it through the
  REAL parsers, never a pickled internal object), its admission class,
  id, and timestamps;
- ``began`` — device work started: the requeue-once boundary. A job
  journaled ``began`` is NOT re-run after a restart (device state under
  a crashed update cannot be trusted for a silent retry — the same
  policy the in-process watchdog applies); it is failed with a
  structured ``daemon-restarted`` error instead. A job accepted but not
  begun replays into the queue with its one requeue consumed;
- ``terminal`` — done/failed/cancelled: the record that lets replay drop
  the job.

Wire format: one JSON object per line, ``fsync``'d per record (atomic at
the record level: a torn final line from a mid-write kill is detected and
skipped at replay — the client of THAT job never received its 202, so
nothing acknowledged is lost). On startup the daemon replays the journal
and compacts it (atomic rewrite holding only still-pending records), so
journal size is O(pending + jobs since restart), not O(jobs ever served).
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

#: Journal filename under the service run directory.
JOURNAL_BASENAME = "jobs.journal.jsonl"


def journal_path(run_dir: str) -> str:
    return os.path.join(run_dir, JOURNAL_BASENAME)


@dataclass
class PendingJob:
    """One replayed accepted-but-unfinished job."""

    job_id: str
    request_doc: Dict
    job_class: str
    submitted_unix: float
    deadline_unix: Optional[float]
    device_began: bool = False
    accepted_record: Dict = field(default_factory=dict)


class JobJournal:
    """Appender half: the daemon's durable admission log."""

    def __init__(self, path: str):
        self.path = path
        # lock order: journal lock is a leaf — nothing else is acquired
        # while holding it (machine-checked by `graftcheck lockgraph`);
        # it serializes appends so records never interleave mid-line.
        self._lock = threading.Lock()
        self._file = None

    def _append(self, record: Dict, fsync: bool = True) -> None:
        line = json.dumps(record, sort_keys=True) + "\n"
        with self._lock:
            if self._file is None:
                os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
                self._file = open(self.path, "a", encoding="utf-8")
            self._file.write(line)
            self._file.flush()
            if fsync:
                os.fsync(self._file.fileno())

    # ------------------------------------------------------------- records

    def accepted(
        self,
        job_id: str,
        request_doc: Dict,
        job_class: str,
        submitted_unix: float,
        deadline_unix: Optional[float],
    ) -> None:
        self._append(
            {
                "event": "accepted",
                "id": job_id,
                "request": request_doc,
                "job_class": job_class,
                "submitted_unix": submitted_unix,
                "deadline_unix": deadline_unix,
            }
        )

    def began(self, job_id: str) -> None:
        self._append({"event": "began", "id": job_id})

    def terminal(self, job_id: str, status: str) -> None:
        # done/failed terminals flush without fsync — it is the worker's
        # hot path (every batched job pays it), and losing one in a crash
        # only downgrades a finished job's post-restart status to the
        # `began`-pinned daemon-restarted failure (never a re-run, never
        # a resurrection; the per-job manifest on disk keeps the truth).
        # A lost CANCELLED record would be worse — the job would replay
        # and RUN after the user cancelled it — so cancels stay fsync'd,
        # as do the admission-path tombstones ("rejected").
        self._append(
            {"event": "terminal", "id": job_id, "status": status},
            fsync=status not in ("done", "failed"),
        )

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None


# ---------------------------------------------------------------- replay


def _iter_records(path: str):
    """Yield parsed journal records; a torn/corrupt line (mid-write kill)
    is skipped — by the write protocol it can only be the LAST line a
    crashed appender produced, and its client never got the 202."""
    try:
        f = open(path, "r", encoding="utf-8")
    except FileNotFoundError:
        return
    with f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(record, dict) and "event" in record:
                yield record


def replay_journal(path: str) -> Tuple[List[PendingJob], int]:
    """Fold the journal into ``(pending_jobs, max_seq)``: every accepted
    job without a terminal record, in admission order, with its
    ``device_began`` flag; and the highest numeric job id seen (the
    restarted daemon's id sequence must continue past it — replayed ids
    stay stable for clients polling across the restart).

    The fold is ORDER-INSENSITIVE across events of one job: ``began``/
    ``terminal`` count even when they precede the ``accepted`` record in
    the file (the appenders are concurrent threads serialized only per
    record, so a fast worker's events can land first) — a job with any
    terminal record is settled, and a ``began`` record always pins the
    no-silent-re-run policy."""
    pending: Dict[str, PendingJob] = {}
    began: set = set()
    settled: set = set()
    max_seq = 0
    for record in _iter_records(path):
        job_id = record.get("id")
        if not isinstance(job_id, str):
            continue
        if job_id.startswith("job-"):
            try:
                max_seq = max(max_seq, int(job_id[len("job-"):]))
            except ValueError:
                pass
        event = record["event"]
        if event == "accepted":
            request = record.get("request")
            job_class = record.get("job_class")
            if not isinstance(request, dict) or not isinstance(
                job_class, str
            ):
                continue
            pending[job_id] = PendingJob(
                job_id=job_id,
                request_doc=request,
                job_class=job_class,
                submitted_unix=float(record.get("submitted_unix") or 0.0),
                deadline_unix=(
                    float(record["deadline_unix"])
                    if record.get("deadline_unix") is not None
                    else None
                ),
                accepted_record=record,
            )
        elif event == "began":
            began.add(job_id)
        elif event == "terminal":
            settled.add(job_id)
    survivors = []
    for job in pending.values():
        if job.job_id in settled:
            continue
        job.device_began = job.job_id in began
        survivors.append(job)
    return survivors, max_seq


def compact_journal(path: str, pending: List[PendingJob]) -> None:
    """Atomically rewrite the journal to hold only the still-pending
    accepted records (+ their began flags): replay cost and journal size
    stay bounded by the live job table, not the daemon's lifetime."""
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        for job in pending:
            f.write(json.dumps(job.accepted_record, sort_keys=True) + "\n")
            if job.device_began:
                f.write(
                    json.dumps(
                        {"event": "began", "id": job.job_id}, sort_keys=True
                    )
                    + "\n"
                )
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


__all__ = [
    "JOURNAL_BASENAME",
    "JobJournal",
    "PendingJob",
    "journal_path",
    "replay_journal",
    "compact_journal",
]

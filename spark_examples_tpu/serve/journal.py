"""Shared job-table journal + lease substrate: N replicas, one filesystem.

PR 9's watchdog extended the crash story from "a job dies" to "a worker
thread dies"; PR 12's journal extended it to "the PROCESS dies". This
module extends it to "the HOST dies": the journal is no longer one
daemon's private replay log but the coordination substrate for N
independent replica daemons sharing a run directory. Three cooperating
pieces:

- **the journal** (:class:`JobJournal` / :func:`replay_journal`): one
  JSON record per line, ``fsync``'d per record. Every admission decision
  a replica acknowledges to a client is durably recorded BEFORE the 202
  leaves the socket. With concurrent writers, appends take a SHARED
  ``flock`` on a side lock file (``<journal>.lock``) and re-check the
  journal's inode before each write — so a compaction (which holds the
  EXCLUSIVE lock, see below) can atomically replace the file without a
  concurrent appender's record landing in the dead inode and vanishing;
- **leases** (:class:`LeaseStore`): time-bounded, epoch-fenced ownership
  of accepted jobs. A lease is a file ``leases/<job>.e<epoch>`` created
  with ``os.link`` from a fully-written, fsync'd temp file — link fails
  atomically when the name exists, so exactly ONE replica wins each
  (job, epoch) and two replicas can never both own a job. Renewals
  rewrite the owner's own epoch file via ``os.replace`` (atomic content
  swap; owner-exclusive by construction). A replica **steals** a job
  whose lease expired past the grace window — its owner died — by
  link-claiming epoch+1: the same exactly-once primitive, so two
  concurrent stealers race to a single winner. Each successful claim or
  steal also appends a fsync'd ``lease`` record to the journal: the
  fold's fencing input;
- **the fenced fold**: ``terminal`` records written by a replica carry
  its lease epoch. At fold time a terminal whose epoch is below the
  job's highest journaled lease epoch is IGNORED — a deposed zombie
  replica's late write cannot settle (or double-complete) a job the
  stealer now owns; the stolen run's terminal wins. Epoch-less records
  (single-replica mode) fold exactly as before. The journaled
  ``device_began`` flag keeps enforcing requeue-once across replica
  lives: a stolen job that already touched the devices is failed with a
  structured error, never silently re-run.

Compaction under concurrent writers is lease-aware
(:func:`compact_journal_shared`): only the holder of the journal's
exclusive compaction ``flock`` compacts (others skip — a no-op, not an
error), the fold re-reads the journal UNDER the lock so no record
appended between a replica's startup replay and its compaction can be
lost, and the rewrite preserves each pending job's highest lease epoch
so fencing survives the rewrite. A torn final line (kill mid-append) is
skipped at fold and dropped by compaction — by the write protocol it can
only be the last line a crashed appender produced, and the client of
THAT record never received its 202.

The run-dir guard (:func:`acquire_run_dir_lock`) makes the sharing
contract explicit: a daemon WITHOUT ``--replica-id`` holds the run dir's
``serve.lock`` exclusively (a second such daemon exits 2 instead of
silently corrupting the journal); replicas hold it SHARED — they coexist
with each other, conflict with a solo daemon — plus an exclusive
per-replica lock so a duplicated ``--replica-id`` is rejected too.
"""

from __future__ import annotations

import fcntl
import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import (
    Callable,
    Dict,
    Iterable,
    Iterator,
    List,
    Optional,
    Set,
    Tuple,
)

#: Journal filename under the service run directory.
JOURNAL_BASENAME = "jobs.journal.jsonl"

#: Side lock file next to the journal: appenders hold it SHARED per
#: record, compaction holds it EXCLUSIVE across read+rewrite+replace.
#: Never itself replaced, so every process locks the same inode.
JOURNAL_LOCK_SUFFIX = ".lock"

#: Lease files (``<job>.e<epoch>``) live here under the run dir.
LEASE_DIRNAME = "leases"

#: Per-replica heartbeat files (``<replica>.json``) live here.
HEARTBEAT_DIRNAME = "replicas"

#: Run-dir ownership guard (``flock``; see :func:`acquire_run_dir_lock`).
RUN_DIR_LOCK_BASENAME = "serve.lock"

#: Default lease time-to-live. A healthy replica renews every TTL/3, so
#: an expiry means the owner missed three consecutive renewal ticks.
DEFAULT_LEASE_SECONDS = 5.0


def journal_path(run_dir: str) -> str:
    return os.path.join(run_dir, JOURNAL_BASENAME)


@dataclass
class PendingJob:
    """One replayed accepted-but-unfinished job."""

    job_id: str
    request_doc: Dict
    job_class: str
    submitted_unix: float
    deadline_unix: Optional[float]
    device_began: bool = False
    accepted_record: Dict = field(default_factory=dict)
    #: Highest journaled lease epoch (0 = never leased) and the replica
    #: that holds it — the fencing facts a stealer needs to claim
    #: epoch+1 and to name the dead owner in a structured failure.
    lease_epoch: int = 0
    lease_replica: Optional[str] = None
    #: Trace id minted at submit (rides the ``accepted`` record, so one
    #: job stays one span tree across replica steals; ``None`` on
    #: journals written before tracing existed).
    trace_id: Optional[str] = None
    #: Admission-time cost prediction
    #: (``obs/costmodel.py:CostPrediction.to_dict``) — rides the
    #: ``accepted`` record like the trace id, so a stolen or replayed
    #: job keeps the prediction its original admission computed (the
    #: calibration pair must compare against THAT estimate, not a
    #: re-prediction under the adopter's warm state). ``None`` on
    #: journals written before the cost observatory existed.
    cost: Optional[Dict] = None


# -------------------------------------------------------- protocol core
#
# Pure transition functions — the single source of truth for every
# protocol decision. The runtime halves below (JobJournal / LeaseStore /
# serve/daemon.py) delegate here; `graftcheck proto` (check/proto.py)
# runs the SAME functions unchanged against an in-memory filesystem
# model, so what the model checker proves is what the fleet ships.
# Nothing in this section touches the filesystem or a clock: records in,
# decisions out.


def stamped_record(
    record: Dict, replica: Optional[str], epoch: Optional[int]
) -> Dict:
    """Stamp the writing replica and its lease epoch onto a record
    (``None`` replica = single-replica mode: records stay epoch-less and
    the fold applies no fencing)."""
    if replica is not None:
        record["replica"] = replica
    if epoch is not None:
        record["epoch"] = int(epoch)
    return record


def accepted_record(
    job_id: str,
    request_doc: Dict,
    job_class: str,
    submitted_unix: float,
    deadline_unix: Optional[float],
    replica: Optional[str] = None,
    trace_id: Optional[str] = None,
    cost: Optional[Dict] = None,
) -> Dict:
    """The durable admission fact. The replica stamp lets the steal scan
    attribute a job that was accepted but never leased (its owner died
    in the one-record window between this append and the lease claim) to
    a dead peer via the heartbeat file instead of leaving it orphaned.
    The trace id and cost prediction ride the same record so a stolen
    job keeps ONE span tree and ONE admission estimate across replica
    lives (compaction rewrites accepted records verbatim, so both
    survive every rewrite for free)."""
    record: Dict = {
        "event": "accepted",
        "id": job_id,
        "request": request_doc,
        "job_class": job_class,
        "submitted_unix": submitted_unix,
        "deadline_unix": deadline_unix,
    }
    if trace_id is not None:
        record["trace"] = trace_id
    if cost is not None:
        record["cost"] = dict(cost)
    return stamped_record(record, replica, None)


def began_record(
    job_id: str,
    replica: Optional[str] = None,
    epoch: Optional[int] = None,
    fused_size: Optional[int] = None,
) -> Dict:
    """The requeue-once boundary. ``fused_size`` (additive, >1 only for
    stacked-group members) is stamped here rather than on the accepted
    record: group membership is a DISPATCH fact — it does not exist at
    admission time, and a replayed/stolen job may re-run serial."""
    record: Dict = {"event": "began", "id": job_id}
    if fused_size is not None and fused_size > 1:
        record["fused_size"] = int(fused_size)
    return stamped_record(record, replica, epoch)


def terminal_record(
    job_id: str,
    status: str,
    replica: Optional[str] = None,
    epoch: Optional[int] = None,
) -> Dict:
    return stamped_record(
        {"event": "terminal", "id": job_id, "status": status}, replica, epoch
    )


def lease_record(
    job_id: str,
    epoch: int,
    replica: Optional[str] = None,
    stolen: bool = False,
) -> Dict:
    """One successful lease claim/steal — the fold's fencing input."""
    record = stamped_record({"event": "lease", "id": job_id}, replica, epoch)
    if stolen:
        record["stolen"] = True
    return record


def terminal_fsync(status: str) -> bool:
    """The terminal durability policy: done/failed terminals flush
    without fsync — it is the worker's hot path (every batched job pays
    it), and losing one in a crash only downgrades a finished job's
    post-restart status to the ``began``-pinned structured failure
    (never a re-run, never a resurrection; the per-job manifest on disk
    keeps the truth). A lost CANCELLED record would be worse — the job
    would replay and RUN after the user cancelled it — so cancels stay
    fsync'd, as do the admission-path tombstones ("rejected"). The model
    checker reads this SAME predicate to decide which journal suffix a
    crash may drop."""
    return status not in ("done", "failed")


class _FoldTables:
    """The fold's intermediate per-job tables, computed in ONE pass and
    consumed by both readers: :func:`fold_records` (the replay) and
    :func:`protocol_summary` (the post-mortem / model-checker view).
    Keeping one accumulator guarantees the proof and the report can
    never disagree about what a journal means."""

    def __init__(self, records: Iterable[Dict]):
        self.pending: Dict[str, PendingJob] = {}
        self.began: Set[str] = set()
        #: Per job: every terminal as ``(status, epoch)`` in file order.
        self.terminals: Dict[str, List[Tuple[Optional[str], Optional[int]]]]
        self.terminals = {}
        self.lease_epoch: Dict[str, int] = {}
        self.lease_replica: Dict[str, str] = {}
        self.steals: Dict[str, int] = {}
        self.lease_records: Dict[str, int] = {}
        self.max_seq = 0
        for record in records:
            job_id = record.get("id")
            if not isinstance(job_id, str):
                continue
            if job_id.startswith("job-"):
                # Both id grammars: solo `job-000042` and replica-stamped
                # `job-<replica>-000042` — the sequence is the last
                # segment.
                try:
                    self.max_seq = max(
                        self.max_seq, int(job_id.rsplit("-", 1)[-1])
                    )
                except ValueError:
                    pass
            event = record["event"]
            if event == "accepted":
                request = record.get("request")
                job_class = record.get("job_class")
                if not isinstance(request, dict) or not isinstance(
                    job_class, str
                ):
                    continue
                trace = record.get("trace")
                cost = record.get("cost")
                self.pending[job_id] = PendingJob(
                    job_id=job_id,
                    request_doc=request,
                    job_class=job_class,
                    submitted_unix=float(
                        record.get("submitted_unix") or 0.0
                    ),
                    deadline_unix=(
                        float(record["deadline_unix"])
                        if record.get("deadline_unix") is not None
                        else None
                    ),
                    accepted_record=record,
                    trace_id=trace if isinstance(trace, str) else None,
                    cost=cost if isinstance(cost, dict) else None,
                )
            elif event == "began":
                self.began.add(job_id)
            elif event == "terminal":
                epoch = record.get("epoch")
                status = record.get("status")
                self.terminals.setdefault(job_id, []).append(
                    (
                        status if isinstance(status, str) else None,
                        int(epoch) if isinstance(epoch, int) else None,
                    )
                )
            elif event == "lease":
                epoch = record.get("epoch")
                if not isinstance(epoch, int):
                    continue
                self.lease_records[job_id] = (
                    self.lease_records.get(job_id, 0) + 1
                )
                if record.get("stolen"):
                    self.steals[job_id] = self.steals.get(job_id, 0) + 1
                if epoch > self.lease_epoch.get(job_id, 0):
                    self.lease_epoch[job_id] = epoch
                    replica = record.get("replica")
                    if isinstance(replica, str):
                        self.lease_replica[job_id] = replica

    def effective(self, job_id: str, epoch: Optional[int]) -> bool:
        """Does a terminal at ``epoch`` survive fencing? Valid iff
        epoch-less (no fencing in play) or at/above the job's highest
        journaled lease epoch — decided after the full read, so a
        steal's lease record fences a terminal that landed earlier in
        the file."""
        fence = self.lease_epoch.get(job_id, 0)
        return epoch is None or epoch >= fence

    def settled(self) -> Set[str]:
        return {
            job_id
            for job_id, terms in self.terminals.items()
            if any(self.effective(job_id, e) for _status, e in terms)
        }


def fold_records(records: Iterable[Dict]) -> Tuple[List[PendingJob], int]:
    """Fold raw journal records into ``(pending_jobs, max_seq)`` — the
    pure core of :func:`replay_journal` (same contract; see there). The
    model checker calls THIS directly on its in-memory journal."""
    tables = _FoldTables(records)
    settled = tables.settled()
    survivors = []
    for job in tables.pending.values():
        if job.job_id in settled:
            continue
        job.device_began = job.job_id in tables.began
        job.lease_epoch = tables.lease_epoch.get(job.job_id, 0)
        job.lease_replica = tables.lease_replica.get(job.job_id)
        survivors.append(job)
    return survivors, tables.max_seq


def protocol_summary(records: Iterable[Dict]) -> Dict:
    """Per-run protocol facts from the SAME one-pass fold tables the
    replay uses: per job its fence epoch, every terminal with its
    fencing verdict, began/steal counts; plus run totals. ``obs report``
    renders this for post-mortems and ``graftcheck proto`` asserts
    invariants over it (GP001's "two effective terminals" is literally a
    filter over ``jobs[*].terminals[*].effective``) — one code path for
    the proof and the report."""
    tables = _FoldTables(records)
    settled = tables.settled()
    job_ids = sorted(
        set(tables.pending)
        | set(tables.terminals)
        | set(tables.lease_epoch)
        | tables.began
    )
    jobs: Dict[str, Dict] = {}
    effective_total = 0
    fenced_total = 0
    for job_id in job_ids:
        terminals = [
            {
                "status": status,
                "epoch": epoch,
                "effective": tables.effective(job_id, epoch),
            }
            for status, epoch in tables.terminals.get(job_id, [])
        ]
        effective = sum(1 for t in terminals if t["effective"])
        effective_total += effective
        fenced_total += len(terminals) - effective
        jobs[job_id] = {
            "fence": tables.lease_epoch.get(job_id, 0),
            "owner": tables.lease_replica.get(job_id),
            "began": job_id in tables.began,
            "settled": job_id in settled,
            "steals": tables.steals.get(job_id, 0),
            "leases": tables.lease_records.get(job_id, 0),
            "terminals": terminals,
        }
    return {
        "jobs": jobs,
        "totals": {
            "accepted": len(tables.pending),
            "settled": len(settled),
            "pending": len(tables.pending) - len(tables.pending.keys() & settled),
            "began": len(tables.began),
            "terminals": sum(len(t) for t in tables.terminals.values()),
            "effective_terminals": effective_total,
            "fenced_terminals": fenced_total,
            "steals": sum(tables.steals.values()),
            "max_lease_epoch": max(tables.lease_epoch.values(), default=0),
        },
    }


def arbitrate_claim(
    view: Optional["LeaseView"],
    replica: str,
    now: float,
    grace_seconds: float,
    steal: bool = False,
    min_epoch: int = 0,
    min_replica: Optional[str] = None,
) -> Tuple[str, int]:
    """Pure lease-claim arbitration: given the job's current on-disk
    lease view (highest epoch, or ``None``), decide what ``replica`` may
    do. Returns one of:

    - ``("deny", 0)`` — the job is someone else's (live foreign lease,
      or expired-past-grace without ``steal``);
    - ``("adopt", epoch)`` — our own UNEXPIRED lease (a fast restart of
      THIS replica id): adopt it at its epoch and renew, no new link;
    - ``("claim", epoch)`` — link-claim this epoch: fresh job (epoch 1),
      our own expired lease (epoch+1), or a foreign lease expired past
      the grace window with ``steal=True`` (epoch+1; exactly one
      concurrent stealer wins the link race).

    ``min_epoch`` is the job's highest JOURNALED lease epoch as the
    caller folded it, and ``min_replica`` the replica that journaled it:
    a granted claim always exceeds ``min_epoch``, so a claim made from a
    stale fold (the previous owner settled and unlinked its lease files
    meanwhile) can never re-issue a fenced epoch. Adopting our own
    unexpired lease keeps its epoch — but ONLY while the journaled fence
    is consistent with it (below our epoch, or at our epoch and
    journaled by US). An own live link at an epoch some OTHER replica
    already journaled is the debris of a stale-fold claim that never got
    revalidated (the claimant crashed in the post-claim window): its
    epoch is fenced, so it is re-claimed above the fence instead of
    adopted — found by `graftcheck proto` (GP004 witness: accepter
    stalls across a peer's adopt-and-settle, links the settled epoch,
    host-crash drops the terminal, restart adopts the leftover link)."""
    if view is None:
        epoch = 1
    elif view.replica == replica:
        if now <= view.expires_unix and (
            view.epoch > int(min_epoch)
            or (view.epoch == int(min_epoch) and min_replica == replica)
        ):
            return ("adopt", view.epoch)
        epoch = view.epoch + 1
    elif now > view.expires_unix + grace_seconds:
        if not steal:
            return ("deny", 0)
        epoch = view.epoch + 1
    else:
        return ("deny", 0)
    return ("claim", max(epoch, int(min_epoch) + 1))


def owner_valid(
    view: Optional["LeaseView"], replica: str, epoch: int, now: float
) -> bool:
    """The ownership fence: does ``replica`` hold the job's HIGHEST
    epoch, unexpired, right now? Checked before every renewal, every
    terminal write and every result publication — a deposed or expired
    owner abandons."""
    return (
        view is not None
        and view.epoch == epoch
        and view.replica == replica
        and now <= view.expires_unix
    )


def foreign_expired(
    view: "LeaseView", replica: str, now: float, grace_seconds: float
) -> bool:
    """Steal-candidate predicate: the lease belongs to another replica
    and expired past the grace window (its owner died — a healthy owner
    renews at TTL/3 and abandons at expiry, so the asymmetric window
    keeps an owner's last-moment publish and a stealer's claim from
    overlapping under skewed clocks)."""
    return (
        view.replica != replica
        and now > view.expires_unix + grace_seconds
    )


def revalidate_pending(
    pending: List[PendingJob], job_id: str, epoch: int
) -> Optional[PendingJob]:
    """Post-claim fence against a STALE FOLD: between the fold a steal
    decision was made from and the claim itself, the job's previous
    holder may have settled it and released its lease — which is exactly
    what would have made the claim succeed at a fresh epoch. The
    settle's terminal write strictly precedes the lease unlink, so a
    re-fold AFTER a successful claim necessarily sees it. Given the
    RE-FOLDED pending set, returns the record to adopt, or ``None`` —
    settled (absent) or fenced above our epoch — in which case the
    caller must release the claim before any work is adopted."""
    for record in pending:
        if record.job_id == job_id:
            if record.lease_epoch <= epoch:
                return record
            break
    return None


def adoption_action(device_began: bool) -> str:
    """What adopting a replayed/stolen pending job does: ``"requeue"``
    (re-enter the queue with the one free retry consumed) — unless the
    journal says device work began, in which case ``"fail"`` with a
    structured error: the requeue-once boundary holds ACROSS replica
    lives, and device state under a crashed update cannot be trusted
    for a silent retry."""
    return "fail" if device_began else "requeue"


def steal_candidates(
    pending: List[PendingJob],
    expired: Set[str],
    replica: str,
    alive_peers: Set[str],
    lease_present: Callable[[str], bool],
) -> List[PendingJob]:
    """Which pending jobs may ``replica`` try to steal? The journal fold
    (NOT the lease file) decides live-ness of the job itself: a lease
    left behind by a settled job never appears in ``pending``. Two
    flavors, in file order:

    - ``expired`` — jobs whose highest lease is foreign and expired past
      grace (:func:`foreign_expired`): the normal steal;
    - orphans — accepted but never leased (``lease_epoch == 0``), whose
      accepting replica is not us, not heartbeating, and left no lease
      file: the owner died in the one-record window between the
      accepted append and its lease claim (or a solo daemon's journal
      was adopted by replicas)."""
    candidates = []
    for record in pending:
        if record.job_id in expired:
            candidates.append(record)
            continue
        owner = record.accepted_record.get("replica")
        if (
            record.lease_epoch == 0
            and owner != replica
            and owner not in alive_peers
            and not lease_present(record.job_id)
        ):
            candidates.append(record)
    return candidates


def compacted_records(pending: List[PendingJob]) -> List[Dict]:
    """The rewrite set for compaction: each still-pending job's accepted
    record VERBATIM (trace + cost ride along), its began flag, and (when
    the job was ever leased) ONE lease record at the highest epoch —
    fencing must survive the rewrite or a zombie's late terminal would
    settle a compacted job."""
    records: List[Dict] = []
    for job in pending:
        records.append(job.accepted_record)
        if job.device_began:
            records.append(began_record(job.job_id))
        if job.lease_epoch > 0:
            records.append(
                lease_record(
                    job.job_id,
                    job.lease_epoch,
                    replica=job.lease_replica,
                )
            )
    return records


class JobJournal:
    """Appender half: one replica's durable admission log. ``replica``
    stamps every ``began``/``terminal``/``lease`` record this appender
    writes (``None`` = single-replica mode: records stay epoch-less and
    the fold applies no fencing)."""

    def __init__(self, path: str, replica: Optional[str] = None):
        self.path = path
        self.replica = replica
        # Serializes this process's appends so records never interleave
        # mid-line; cross-process serialization is the shared flock.
        # lock order: journal lock is a leaf — nothing else is acquired
        # while holding it (machine-checked by `graftcheck lockgraph`).
        self._lock = threading.Lock()
        self._file = None
        self._lock_fd: Optional[int] = None

    def _ensure_open_locked(self) -> None:
        """(Re)open the journal if unopened or if compaction swapped the
        file out from under our handle (inode changed): an append into a
        replaced inode would vanish."""
        if self._file is not None:
            try:
                if (
                    os.stat(self.path).st_ino
                    == os.fstat(self._file.fileno()).st_ino
                ):
                    return
            except OSError:
                pass
            self._file.close()
            self._file = None
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._file = open(self.path, "a", encoding="utf-8")

    def _append(self, record: Dict, fsync: bool = True) -> None:
        line = json.dumps(record, sort_keys=True) + "\n"
        with self._lock:
            if self._lock_fd is None:
                os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
                self._lock_fd = os.open(
                    self.path + JOURNAL_LOCK_SUFFIX,
                    os.O_CREAT | os.O_RDWR,
                    0o644,
                )
            # Shared vs a compactor's exclusive hold: an append either
            # completes before the rewrite reads the journal (the record
            # survives into the compacted file) or starts after the
            # os.replace (the inode re-check opens the new file). Held
            # only for this one buffered write+flush — bounded.
            fcntl.flock(self._lock_fd, fcntl.LOCK_SH)
            try:
                self._ensure_open_locked()
                self._file.write(line)
                self._file.flush()
                if fsync:
                    os.fsync(self._file.fileno())
            finally:
                fcntl.flock(self._lock_fd, fcntl.LOCK_UN)

    # ------------------------------------------------------------- records

    def accepted(
        self,
        job_id: str,
        request_doc: Dict,
        job_class: str,
        submitted_unix: float,
        deadline_unix: Optional[float],
        trace_id: Optional[str] = None,
        cost: Optional[Dict] = None,
    ) -> None:
        self._append(
            accepted_record(
                job_id,
                request_doc,
                job_class,
                submitted_unix,
                deadline_unix,
                replica=self.replica,
                trace_id=trace_id,
                cost=cost,
            )
        )

    def began(
        self,
        job_id: str,
        epoch: Optional[int] = None,
        fused_size: Optional[int] = None,
    ) -> None:
        self._append(
            began_record(
                job_id,
                replica=self.replica,
                epoch=epoch,
                fused_size=fused_size,
            )
        )

    def terminal(
        self, job_id: str, status: str, epoch: Optional[int] = None
    ) -> None:
        # Durability policy (and its rationale): :func:`terminal_fsync`.
        self._append(
            terminal_record(
                job_id, status, replica=self.replica, epoch=epoch
            ),
            fsync=terminal_fsync(status),
        )

    def lease(
        self, job_id: str, epoch: int, stolen: bool = False
    ) -> None:
        """One successful lease claim/steal — the fold's fencing input,
        always fsync'd (a stale-epoch zombie write is only provably
        stale if the higher lease record is durable)."""
        self._append(
            lease_record(
                job_id, epoch, replica=self.replica, stolen=stolen
            )
        )

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None
            if self._lock_fd is not None:
                os.close(self._lock_fd)
                self._lock_fd = None


# ---------------------------------------------------------------- replay


def _iter_records(path: str) -> Iterator[Dict]:
    """Yield parsed journal records; a torn/corrupt line (mid-write kill)
    is skipped — by the write protocol it can only be the LAST line a
    crashed appender produced, and its client never got the 202."""
    try:
        f = open(path, "r", encoding="utf-8")
    except FileNotFoundError:
        return
    with f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(record, dict) and "event" in record:
                yield record


def iter_journal_records(path: str) -> Iterator[Dict]:
    """Public raw-record iterator (the ``trace export`` verb correlates the
    journal's admission/lease/terminal facts with flight-recorder events;
    the fold below stays the replay semantics)."""
    return _iter_records(path)


def replay_journal(path: str) -> Tuple[List[PendingJob], int]:
    """Fold the journal into ``(pending_jobs, max_seq)``: every accepted
    job without a VALID terminal record, in admission order, with its
    ``device_began`` flag and highest lease epoch; and the highest
    numeric job-id sequence seen (a restarted replica's id sequence must
    continue past it — replayed ids stay stable for clients polling
    across the restart).

    The fold is ORDER-INSENSITIVE across events of one job: ``began``/
    ``terminal``/``lease`` count even when they precede the ``accepted``
    record in the file (appenders are concurrent threads AND concurrent
    replica processes serialized only per record). **Epoch fencing**: a
    terminal record carrying a lease epoch below the job's highest
    journaled lease epoch is a deposed replica's late write — ignored,
    so the job it failed to settle is settled (or re-run) by its current
    owner instead, and never double-completed. Epoch-less terminals
    (single-replica mode) always count. A ``began`` record pins the
    no-silent-re-run policy regardless of which replica's life wrote it.

    The fold itself is the pure :func:`fold_records`; this wrapper only
    binds it to a file."""
    return fold_records(_iter_records(path))


# ----------------------------------------------------------- compaction


def _rewrite_journal(path: str, pending: List[PendingJob]) -> None:
    """Atomic rewrite holding only still-pending jobs' records: the
    accepted record, the began flag, and (when the job was ever leased)
    one lease record at the highest epoch — fencing must survive the
    rewrite or a zombie's late terminal would settle a compacted job.
    The record set is the pure :func:`compacted_records`."""
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        for record in compacted_records(pending):
            f.write(json.dumps(record, sort_keys=True) + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def compact_journal(path: str, pending: List[PendingJob]) -> None:
    """Single-writer compaction (the solo daemon's startup path, and
    tests): rewrite the journal to hold only ``pending``. Takes the
    exclusive compaction flock for symmetry with the shared-append
    protocol — in solo mode it is uncontended."""
    lock_fd = os.open(
        path + JOURNAL_LOCK_SUFFIX, os.O_CREAT | os.O_RDWR, 0o644
    )
    try:
        fcntl.flock(lock_fd, fcntl.LOCK_EX)
        _rewrite_journal(path, pending)
    finally:
        os.close(lock_fd)


def compact_journal_shared(
    path: str, lease_dir: Optional[str] = None
) -> bool:
    """Lease-aware compaction for concurrent writers: only the holder of
    the journal's exclusive compaction flock compacts — a replica that
    loses the race (or arrives while another replica is mid-compaction)
    SKIPS, returning ``False``, instead of rewriting a journal it does
    not own. The winner re-folds the journal UNDER the lock (no appender
    can race the read: appends hold the lock shared), rewrites it to the
    pending set, and — when ``lease_dir`` is given — sweeps settled
    jobs' lease files so the lease directory stays O(pending) too."""
    lock_fd = os.open(
        path + JOURNAL_LOCK_SUFFIX, os.O_CREAT | os.O_RDWR, 0o644
    )
    try:
        try:
            fcntl.flock(lock_fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            return False
        pending, _max_seq = replay_journal(path)
        _rewrite_journal(path, pending)
        if lease_dir is not None:
            _sweep_lease_files(
                lease_dir, keep={job.job_id for job in pending}
            )
        return True
    finally:
        os.close(lock_fd)


def _sweep_lease_files(lease_dir: str, keep: set) -> None:
    try:
        names = os.listdir(lease_dir)
    except FileNotFoundError:
        return
    for name in names:
        job_id, _sep, _epoch = name.rpartition(".e")
        if job_id and job_id not in keep:
            try:
                os.unlink(os.path.join(lease_dir, name))
            except OSError:
                pass  # a concurrent sweep won the unlink — same outcome


# -------------------------------------------------------------- leases


@dataclass(frozen=True)
class LeaseView:
    """One job's current lease as read from disk (its highest epoch)."""

    job_id: str
    replica: str
    epoch: int
    expires_unix: float


class LeaseStore:
    """One replica's half of the lease protocol; see the module
    docstring for the claim/renew/steal file semantics."""

    def __init__(
        self,
        run_dir: str,
        replica: str,
        lease_seconds: float = DEFAULT_LEASE_SECONDS,
        grace_seconds: Optional[float] = None,
        clock: Callable[[], float] = time.time,
    ):
        if not replica:
            raise ValueError("LeaseStore needs a non-empty replica id")
        if lease_seconds <= 0:
            raise ValueError(
                f"lease_seconds must be > 0, got {lease_seconds}"
            )
        self.run_dir = run_dir
        self.replica = replica
        self.lease_seconds = float(lease_seconds)
        #: Clock-skew allowance: a foreign lease is stealable only past
        #: expiry PLUS this window, while the owner abandons at expiry —
        #: the asymmetry that keeps an owner's last-moment publish and a
        #: stealer's claim from overlapping under skewed clocks.
        self.grace_seconds = (
            float(grace_seconds)
            if grace_seconds is not None
            else float(lease_seconds)
        )
        self.lease_dir = os.path.join(run_dir, LEASE_DIRNAME)
        self.heartbeat_dir = os.path.join(run_dir, HEARTBEAT_DIRNAME)
        self._clock = clock
        # lock order: lease-store lock is a leaf — it guards only the
        # owned-epoch dict; every file operation happens outside it.
        self._lock = threading.Lock()
        self._owned: Dict[str, int] = {}
        os.makedirs(self.lease_dir, exist_ok=True)
        os.makedirs(self.heartbeat_dir, exist_ok=True)

    # ------------------------------------------------------------- files

    def _path(self, job_id: str, epoch: int) -> str:
        return os.path.join(self.lease_dir, f"{job_id}.e{epoch}")

    def _write_tmp(self, doc: Dict) -> str:
        tmp = os.path.join(
            self.lease_dir,
            f".tmp.{self.replica}.{os.getpid()}.{threading.get_ident()}",
        )
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        return tmp

    def _lease_doc(self, job_id: str, epoch: int) -> Dict:
        return {
            "job": job_id,
            "replica": self.replica,
            "epoch": epoch,
            "expires_unix": self._clock() + self.lease_seconds,
        }

    def _try_claim_file(self, job_id: str, epoch: int) -> bool:
        """The exactly-once primitive: link a fully-written temp file to
        the (job, epoch) name — atomic in existence AND content; the
        loser of a race gets ``FileExistsError``, never a torn read."""
        tmp = self._write_tmp(self._lease_doc(job_id, epoch))
        try:
            os.link(tmp, self._path(job_id, epoch))
            return True
        except FileExistsError:
            return False
        finally:
            os.unlink(tmp)

    def current(self, job_id: str) -> Optional[LeaseView]:
        """The job's highest-epoch lease on disk, or ``None``."""
        views = self._scan(prefix=f"{job_id}.e")
        return views.get(job_id)

    def _scan(self, prefix: Optional[str] = None) -> Dict[str, LeaseView]:
        """Highest-epoch lease view per job (optionally one job only)."""
        try:
            names = os.listdir(self.lease_dir)
        except FileNotFoundError:
            return {}
        best: Dict[str, Tuple[int, str]] = {}
        for name in names:
            if name.startswith(".tmp."):
                continue
            if prefix is not None and not name.startswith(prefix):
                continue
            job_id, sep, epoch_text = name.rpartition(".e")
            if not sep or not job_id:
                continue
            try:
                epoch = int(epoch_text)
            except ValueError:
                continue
            if epoch > best.get(job_id, (0, ""))[0]:
                best[job_id] = (epoch, name)
        views: Dict[str, LeaseView] = {}
        for job_id, (epoch, name) in best.items():
            try:
                with open(
                    os.path.join(self.lease_dir, name), encoding="utf-8"
                ) as f:
                    doc = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue  # swept concurrently; claims are atomic-content
            replica = doc.get("replica")
            expires = doc.get("expires_unix")
            if not isinstance(replica, str) or not isinstance(
                expires, (int, float)
            ):
                continue
            views[job_id] = LeaseView(
                job_id=job_id,
                replica=replica,
                epoch=epoch,
                expires_unix=float(expires),
            )
        return views

    # ------------------------------------------------------------ protocol

    def claim(
        self,
        job_id: str,
        steal: bool = False,
        min_epoch: int = 0,
        min_replica: Optional[str] = None,
    ) -> Optional[int]:
        """Acquire the job's lease; returns the held epoch or ``None``.

        - no lease on disk → claim epoch 1 (fresh admission / replay of
          a never-leased journal);
        - our own UNEXPIRED lease (a fast restart of THIS replica id) →
          adopt it at its epoch and renew; our own EXPIRED lease →
          re-claim at epoch+1 (a stealer may already be mid-claim at
          that epoch — the link race decides, never both);
        - a foreign live lease → ``None`` (the job is theirs);
        - a foreign lease expired past the grace window → with
          ``steal=True``, link-claim epoch+1 (exactly one concurrent
          stealer wins); without, ``None`` — admission never steals.

        ``min_epoch``/``min_replica`` are the job's highest JOURNALED
        lease epoch and its journaling replica as the caller folded
        them: the granted epoch always exceeds ``min_epoch``, so a
        claim made from a stale fold (the previous owner settled and
        unlinked its lease files meanwhile) can never re-issue a fenced
        epoch — and an own live link at an epoch journaled by a
        DIFFERENT replica is re-claimed above it, not adopted (see
        :func:`arbitrate_claim`). Stale-fold claims are additionally
        re-validated against the journal by the caller
        (``serve/daemon.py``) before any work is adopted.

        The decision itself is the pure :func:`arbitrate_claim`; this
        method only binds it to the on-disk view and the link file."""
        verdict, epoch = arbitrate_claim(
            self.current(job_id),
            self.replica,
            self._clock(),
            self.grace_seconds,
            steal=steal,
            min_epoch=min_epoch,
            min_replica=min_replica,
        )
        if verdict == "deny":
            return None
        if verdict == "adopt":
            with self._lock:
                self._owned[job_id] = epoch
            self.renew(job_id)
            return epoch
        if not self._try_claim_file(job_id, epoch):
            return None
        with self._lock:
            self._owned[job_id] = epoch
        return epoch

    def renew(self, job_id: str) -> bool:
        """Extend our lease's expiry (atomic content swap of our own
        epoch file). Returns ``False`` — the lease is LOST, abandon the
        job — when we no longer hold it: a higher epoch exists (stolen),
        the file vanished, or our own expiry already passed (a renewal
        thread stalled past the TTL must not resurrect itself: by then a
        stealer may legitimately be mid-claim inside the grace window).
        Validity is the same :func:`owner_valid` fence the publish path
        checks."""
        with self._lock:
            epoch = self._owned.get(job_id)
        if epoch is None:
            return False
        view = self.current(job_id)
        if not owner_valid(view, self.replica, epoch, self._clock()):
            self.forget(job_id)
            return False
        tmp = self._write_tmp(self._lease_doc(job_id, epoch))
        os.replace(tmp, self._path(job_id, epoch))
        return True

    def still_owner(self, job_id: str) -> bool:
        """The pre-publish fence: do we hold the job's HIGHEST epoch,
        unexpired, right now? Checked before every terminal write and
        result publication — a deposed or expired owner abandons. The
        predicate is the pure :func:`owner_valid`."""
        with self._lock:
            epoch = self._owned.get(job_id)
        if epoch is None:
            return False
        return owner_valid(
            self.current(job_id), self.replica, epoch, self._clock()
        )

    def owned_jobs(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._owned)

    def epoch_of(self, job_id: str) -> Optional[int]:
        with self._lock:
            return self._owned.get(job_id)

    def forget(self, job_id: str) -> None:
        """Drop local ownership bookkeeping (lease lost or released)."""
        with self._lock:
            self._owned.pop(job_id, None)

    def release(self, job_id: str) -> None:
        """Job settled: unlink our lease file(s) up to our epoch and
        forget it. A higher (stolen) epoch file is never touched."""
        with self._lock:
            epoch = self._owned.pop(job_id, None)
        if epoch is None:
            return
        for e in range(1, epoch + 1):
            try:
                os.unlink(self._path(job_id, e))
            except OSError:
                pass

    def expired_foreign(self) -> List[LeaseView]:
        """Steal candidates: every job whose HIGHEST lease belongs to
        another replica and expired past the grace window — the pure
        :func:`foreign_expired` over every on-disk view."""
        now = self._clock()
        return [
            view
            for view in self._scan().values()
            if foreign_expired(view, self.replica, now, self.grace_seconds)
        ]

    # ---------------------------------------------------------- liveness

    def heartbeat(self) -> None:
        """Atomic publish of this replica's liveness (peers read the
        written clock, not mtime — one host, one clock domain)."""
        doc = {
            "replica": self.replica,
            "pid": os.getpid(),
            "unix": self._clock(),
        }
        tmp = os.path.join(
            self.heartbeat_dir, f".tmp.{self.replica}.{os.getpid()}"
        )
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(
            tmp, os.path.join(self.heartbeat_dir, f"{self.replica}.json")
        )

    def retire(self) -> None:
        """Clean shutdown: withdraw this replica's heartbeat file so
        peers see an intentionally departed member (absent) rather than
        a dead one (stale) — a drained replica must not leave the pool
        reporting ``degraded`` forever."""
        try:
            os.unlink(
                os.path.join(self.heartbeat_dir, f"{self.replica}.json")
            )
        except OSError:
            pass

    def peers(self, stale_after: Optional[float] = None) -> List[Dict]:
        """Every OTHER replica's last heartbeat: ``{id, age_seconds,
        alive}`` (alive = age within ``stale_after``, default 3×TTL)."""
        horizon = (
            float(stale_after)
            if stale_after is not None
            else 3.0 * self.lease_seconds
        )
        now = self._clock()
        try:
            names = os.listdir(self.heartbeat_dir)
        except FileNotFoundError:
            return []
        # Keyed by replica id: the accumulation is bounded by how many
        # daemons share the run dir, never by any input's size.
        ages: Dict[str, float] = {}
        for name in sorted(names):
            if not name.endswith(".json") or name.startswith(".tmp."):
                continue
            replica = name[: -len(".json")]
            if replica == self.replica:
                continue
            try:
                with open(
                    os.path.join(self.heartbeat_dir, name), encoding="utf-8"
                ) as f:
                    doc = json.load(f)
                ages[replica] = now - float(doc["unix"])
            except (OSError, json.JSONDecodeError, KeyError, TypeError,
                    ValueError):
                continue
        return [
            {
                "id": replica,
                "age_seconds": age,
                "alive": age <= horizon,
            }
            for replica, age in sorted(ages.items())
        ]

    def alive_count(self, stale_after: Optional[float] = None) -> int:
        """Replicas currently heartbeating, self included."""
        return 1 + sum(
            1 for p in self.peers(stale_after=stale_after) if p["alive"]
        )


# ------------------------------------------------------- run-dir guard


class RunDirBusy(RuntimeError):
    """Another daemon owns (part of) this run directory; see
    :func:`acquire_run_dir_lock`. The CLI maps this to exit 2."""


class RunDirLock:
    """Held ``flock`` descriptors for one daemon's run-dir claim."""

    def __init__(self, fds: List[int]):
        self._fds = fds

    def release(self) -> None:
        fds, self._fds = self._fds, []
        for fd in fds:
            try:
                os.close(fd)  # closing drops the flock
            except OSError:
                pass


def acquire_run_dir_lock(
    run_dir: str, replica_id: Optional[str] = None
) -> RunDirLock:
    """Claim a service run dir, or raise :class:`RunDirBusy`.

    A solo daemon (no replica id) holds ``serve.lock`` EXCLUSIVELY: a
    second daemon pointed at the same ``--run-dir`` without
    ``--replica-id`` is refused instead of silently corrupting the
    journal. Replicas hold ``serve.lock`` SHARED (they coexist by
    design, but conflict with a solo daemon in either order) plus an
    exclusive per-replica ``serve.<id>.lock`` so a duplicated replica id
    — two daemons claiming the same identity, epochs and heartbeats
    colliding — is refused too."""
    os.makedirs(run_dir, exist_ok=True)
    fds: List[int] = []

    def _locked(basename: str, operation: int, message: str) -> None:
        fd = os.open(
            os.path.join(run_dir, basename), os.O_CREAT | os.O_RDWR, 0o644
        )
        try:
            fcntl.flock(fd, operation | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            for held in fds:
                os.close(held)
            raise RunDirBusy(message) from None
        fds.append(fd)

    if replica_id is None:
        _locked(
            RUN_DIR_LOCK_BASENAME,
            fcntl.LOCK_EX,
            f"run dir {run_dir!r} is already owned by another daemon; a "
            "second daemon on the same --run-dir would corrupt the job "
            "journal — to run multiple replicas against one run dir, "
            "give each a distinct --replica-id",
        )
    else:
        _locked(
            RUN_DIR_LOCK_BASENAME,
            fcntl.LOCK_SH,
            f"run dir {run_dir!r} is owned exclusively by a daemon "
            "running without --replica-id; stop it (or move it to a "
            "replica id) before attaching replicas",
        )
        _locked(
            f"serve.{replica_id}.lock",
            fcntl.LOCK_EX,
            f"replica id {replica_id!r} is already running against run "
            f"dir {run_dir!r}; every replica needs a distinct "
            "--replica-id",
        )
    return RunDirLock(fds)


__all__ = [
    "DEFAULT_LEASE_SECONDS",
    "HEARTBEAT_DIRNAME",
    "JOURNAL_BASENAME",
    "JOURNAL_LOCK_SUFFIX",
    "LEASE_DIRNAME",
    "RUN_DIR_LOCK_BASENAME",
    "JobJournal",
    "LeaseStore",
    "LeaseView",
    "PendingJob",
    "RunDirBusy",
    "RunDirLock",
    "accepted_record",
    "acquire_run_dir_lock",
    "adoption_action",
    "arbitrate_claim",
    "began_record",
    "compact_journal",
    "compact_journal_shared",
    "compacted_records",
    "fold_records",
    "foreign_expired",
    "iter_journal_records",
    "journal_path",
    "lease_record",
    "owner_valid",
    "protocol_summary",
    "replay_journal",
    "revalidate_pending",
    "stamped_record",
    "steal_candidates",
    "terminal_fsync",
    "terminal_record",
]

"""Bounded two-class admission queue of the resident PCA service.

One serial worker owns the devices, so scheduling is a pure ordering
decision — and the ordering contract is: **small-region queries are never
starved by whole-genome jobs**. Jobs are classified at admission
(:func:`classify_conf`) into ``small`` (statically-bounded synthetic site
count at or under :data:`SMALL_JOB_MAX_SITES` — the 0.229 s BRCA1 shape)
and ``large`` (everything else: whole-genome ``--all-references``, file
and checkpoint cohorts whose size only the data knows). The worker drains
every queued small job before starting the next large one, so a queued
whole-genome run delays cheap queries by at most the job currently on
the devices — never by other queued long jobs.

Both classes are bounded; an admission past capacity raises
:class:`QueueFull`, which the HTTP layer surfaces as 429 backpressure
(the client retries with backoff; the service never buffers unboundedly
— the host-memory discipline of ``graftcheck hostmem`` applied to the
control plane). Queued jobs can be cancelled and carry optional
deadlines: a job still unstarted past its deadline fails at dequeue time
without touching the devices.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, Optional

from spark_examples_tpu.serve.protocol import JobRequest

SMALL_CLASS = "small"
LARGE_CLASS = "large"

#: Largest statically-bounded candidate-site count still admitted as a
#: small-region query. The synthetic grid has one candidate site per
#: ``sources/synthetic.py:DEFAULT_VARIANT_SPACING`` (100) bases, so this
#: is ~25 Mb of reference — two orders of magnitude above the BRCA1
#: window (~812 sites) and two below a whole genome (~28.9 M sites).
SMALL_JOB_MAX_SITES = 250_000

#: Default class capacities: small queries are cheap to hold (they drain
#: between large jobs), large jobs each pin minutes-to-hours of device
#: time so a short queue IS the honest backpressure.
DEFAULT_SMALL_CAPACITY = 16
DEFAULT_LARGE_CAPACITY = 4


class QueueFull(Exception):
    """Admission past a class's capacity (HTTP 429)."""

    def __init__(self, job_class: str, capacity: int):
        super().__init__(
            f"{job_class} admission queue is full ({capacity} queued)"
        )
        self.job_class = job_class
        self.capacity = capacity


class QueueClosed(Exception):
    """Admission after drain began (HTTP 503)."""


@dataclass
class Job:
    """One admitted job. Mutable state (status, timestamps, result) is
    guarded by the owning service's table lock (``serve/daemon.py``) —
    the queue only ever holds jobs whose status is ``queued``."""

    id: str
    request: JobRequest
    conf: object
    job_class: str
    submitted_unix: float
    deadline_unix: Optional[float] = None
    plan_geometry: Dict = field(default_factory=dict)
    status: str = "queued"
    started_unix: Optional[float] = None
    finished_unix: Optional[float] = None
    seconds: Optional[float] = None
    error: Optional[str] = None
    result: Optional[Dict] = None
    manifest_path: Optional[str] = None
    compile_cache: Optional[str] = None
    #: Worker-crash recovery bookkeeping (``serve/daemon.py`` watchdog):
    #: once ``device_began`` flips, a crashed job is failed, never
    #: requeued — device state under a crashed update cannot be trusted;
    #: ``requeues`` bounds the one retry a not-yet-begun job may ride.
    device_began: bool = False
    requeues: int = 0


def classify_conf(conf) -> str:
    """``small`` iff the configuration's candidate-site count is
    statically bounded (synthetic source, explicit ``--references``, no
    checkpoint resume) at or under :data:`SMALL_JOB_MAX_SITES`; every
    cohort whose size only the data knows is ``large`` — the conservative
    direction: misclassifying a big job as small starves real small jobs,
    misclassifying a small job as large only queues it fairly."""
    if (
        getattr(conf, "source", "synthetic") != "synthetic"
        or getattr(conf, "all_references", False)
        or getattr(conf, "input_path", None)
    ):
        return LARGE_CLASS
    try:
        from spark_examples_tpu.sources.synthetic import DEFAULT_VARIANT_SPACING

        sites = sum(
            (contig.end - contig.start) // DEFAULT_VARIANT_SPACING + 1
            for contigs in conf.get_references()
            for contig in contigs
        )
    except (ValueError, TypeError, AttributeError):
        return LARGE_CLASS
    return SMALL_CLASS if sites <= SMALL_JOB_MAX_SITES else LARGE_CLASS


class BoundedJobQueue:
    """Two bounded FIFO lanes + one condition variable. ``pop`` always
    serves the small lane first (the batching contract); within a lane,
    admission order is preserved."""

    def __init__(
        self,
        small_capacity: int = DEFAULT_SMALL_CAPACITY,
        large_capacity: int = DEFAULT_LARGE_CAPACITY,
    ):
        if small_capacity < 1 or large_capacity < 1:
            raise ValueError(
                f"queue capacities must be >= 1, got small={small_capacity} "
                f"large={large_capacity}"
            )
        self.small_capacity = int(small_capacity)
        self.large_capacity = int(large_capacity)
        # lock order: queue lock is a leaf — nothing else is acquired
        # while holding it (machine-checked by `graftcheck lockgraph`).
        self._lock = threading.Lock()
        # lock order: the condition shares the queue leaf lock above.
        self._nonempty = threading.Condition(self._lock)
        self._small: Deque[Job] = deque()
        self._large: Deque[Job] = deque()
        self._closed = False

    # ------------------------------------------------------------ admission

    def put(self, job: Job) -> None:
        """Admit one queued job; raises :class:`QueueClosed` after drain
        began and :class:`QueueFull` past the class capacity. Never
        blocks — backpressure is the caller's 429, not a stalled socket."""
        with self._nonempty:
            if self._closed:
                raise QueueClosed("service is draining; no new jobs")
            lane, capacity = (
                (self._small, self.small_capacity)
                if job.job_class == SMALL_CLASS
                else (self._large, self.large_capacity)
            )
            if len(lane) >= capacity:
                raise QueueFull(job.job_class, capacity)
            lane.append(job)
            self._nonempty.notify()

    # -------------------------------------------------------------- worker

    def pop(self, timeout: Optional[float] = None) -> Optional[Job]:
        """Next job for the worker — every queued small job ahead of any
        large one. Returns ``None`` on timeout or when the queue is
        closed and empty (check :meth:`drained` to distinguish)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._nonempty:
            while not self._small and not self._large:
                if self._closed:
                    return None
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return None
                self._nonempty.wait(remaining)
            lane = self._small if self._small else self._large
            return lane.popleft()

    # ---------------------------------------------------------- management

    def remove(self, job_id: str) -> Optional[Job]:
        """Pull one still-queued job out (cancellation); ``None`` when the
        worker already claimed it."""
        with self._lock:
            for lane in (self._small, self._large):
                for job in lane:
                    if job.id == job_id:
                        lane.remove(job)
                        return job
        return None

    def close(self) -> None:
        """Stop admission (drain): pending jobs still pop; new puts raise
        :class:`QueueClosed`; blocked pops wake."""
        with self._nonempty:
            self._closed = True
            self._nonempty.notify_all()

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    @property
    def drained(self) -> bool:
        """Closed AND empty — the worker's exit condition."""
        with self._lock:
            return self._closed and not self._small and not self._large

    def depth(self) -> Dict[str, int]:
        with self._lock:
            return {
                SMALL_CLASS: len(self._small),
                LARGE_CLASS: len(self._large),
            }

    def total_depth(self) -> int:
        with self._lock:
            return len(self._small) + len(self._large)


__all__ = [
    "SMALL_CLASS",
    "LARGE_CLASS",
    "SMALL_JOB_MAX_SITES",
    "DEFAULT_SMALL_CAPACITY",
    "DEFAULT_LARGE_CAPACITY",
    "QueueFull",
    "QueueClosed",
    "Job",
    "classify_conf",
    "BoundedJobQueue",
]

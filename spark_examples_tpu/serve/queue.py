"""Bounded two-class admission queue of the resident PCA service.

Scheduling contract: **small-region queries are never starved by
whole-genome jobs**. Jobs are classified at admission
(:func:`classify_conf`) into ``small`` (statically-bounded synthetic site
count at or under the configured small-site limit, default
:data:`SMALL_JOB_MAX_SITES` — the 0.229 s BRCA1 shape) and ``large``
(everything else: whole-genome ``--all-references``, file and checkpoint
cohorts whose size only the data knows). Each executor slice's worker
pops only the classes its slice serves (``pop``'s ``classes`` filter);
a shared single-slice worker drains every queued small job before the
next large one, and a dedicated small slice never even sees large jobs
— a queued whole-genome run delays cheap queries by at most the job
currently on the SMALL slice's own devices.

**Continuous batching** (:meth:`BoundedJobQueue.pop_batch`): when a
worker frees, every queued small job whose batch fingerprint
(``utils/cache.py:batch_compile_fingerprint`` — region-invariant compile
geometry) matches the head job coalesces into one dispatch group, up to
``max_batch`` jobs, optionally lingering up to ``linger_seconds`` for
more compatible arrivals. The linger clock is anchored at the FIRST
group member's enqueue time, not the pop call: a group that is already
full (or whose head already waited out the window in the queue) is
dispatched immediately — the latency budget is spent once per job, not
once per pop. Both bounds are hard: latency is traded for throughput
only inside the declared window, never unboundedly. A group runs as ONE
stacked device program when eligible (``serve/executor.py:
execute_fused_batch``) and back to back on warm jit caches otherwise;
either way every job keeps its individual result/manifest
(byte-identical to serial execution — CI-asserted), so batching is a
scheduling decision, not a semantics change.

**Cost-ordered scheduling** (``ordering="cost"``, the default): within
each class lane the queue serves the job with the smallest calibrated
cost estimate first (shortest-job-first — the admission-time
``CostPrediction`` stamped on ``Job.cost_estimate_seconds``), jobs
carrying a deadline sort ahead by slack (deadline minus now minus
estimate — the job closest to missing its promise runs first), and a
job queued longer than ``age_cap_seconds`` jumps to the front of its
lane outright, so SJF can never starve an expensive job behind an
endless stream of cheap ones. Ties break FIFO on the admission sequence
number, so ordering is deterministic: the same queue state always pops
the same job. ``ordering="fifo"`` keeps the historical arrival order
(the bench harness's control arm).

Both classes are bounded; an admission past capacity raises
:class:`QueueFull`, which the HTTP layer surfaces as 429 backpressure
(the client retries with backoff; the service never buffers unboundedly
— the host-memory discipline of ``graftcheck hostmem`` applied to the
control plane). Queued jobs can be cancelled and carry optional
deadlines: a job still unstarted past its deadline fails at dequeue time
without touching the devices.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Sequence

from spark_examples_tpu.serve.protocol import JobRequest

SMALL_CLASS = "small"
LARGE_CLASS = "large"

#: Largest statically-bounded candidate-site count still admitted as a
#: small-region query BY DEFAULT (``--serve-small-site-limit`` overrides,
#: validated at daemon startup). The synthetic grid has one candidate
#: site per ``sources/synthetic.py:DEFAULT_VARIANT_SPACING`` (100) bases,
#: so this is ~25 Mb of reference — two orders of magnitude above the
#: BRCA1 window (~812 sites) and two below a whole genome (~28.9 M sites).
SMALL_JOB_MAX_SITES = 250_000

#: Default class capacities: small queries are cheap to hold (they drain
#: between large jobs), large jobs each pin minutes-to-hours of device
#: time so a short queue IS the honest backpressure.
DEFAULT_SMALL_CAPACITY = 16
DEFAULT_LARGE_CAPACITY = 4

#: Continuous-batching bounds: at most this many small jobs per dispatch
#: group, and by default no linger (a freed worker takes what is queued
#: NOW; a positive ``--batch-linger-seconds`` trades that much latency
#: for larger groups under bursty traffic).
DEFAULT_BATCH_MAX_JOBS = 8
DEFAULT_BATCH_LINGER_SECONDS = 0.0

#: Starvation guard for cost-ordered lanes: a job queued at least this
#: long outranks every estimate-ordered peer in its lane (FIFO among the
#: aged), so shortest-job-first degrades gracefully to FIFO under
#: sustained cheap-job pressure instead of parking expensive jobs
#: forever. ``--serve-age-cap-seconds`` overrides.
DEFAULT_AGE_CAP_SECONDS = 30.0


class QueueFull(Exception):
    """Admission past a class's capacity (HTTP 429)."""

    def __init__(self, job_class: str, capacity: int):
        super().__init__(
            f"{job_class} admission queue is full ({capacity} queued)"
        )
        self.job_class = job_class
        self.capacity = capacity


class QueueClosed(Exception):
    """Admission after drain began (HTTP 503)."""


@dataclass
class Job:
    """One admitted job. Mutable state (status, timestamps, result) is
    guarded by the owning service's table lock (``serve/daemon.py``) —
    the queue only ever holds jobs whose status is ``queued``."""

    id: str
    request: JobRequest
    conf: object
    job_class: str
    submitted_unix: float
    deadline_unix: Optional[float] = None
    plan_geometry: Dict = field(default_factory=dict)
    status: str = "queued"
    started_unix: Optional[float] = None
    finished_unix: Optional[float] = None
    seconds: Optional[float] = None
    error: Optional[str] = None
    result: Optional[Dict] = None
    manifest_path: Optional[str] = None
    compile_cache: Optional[str] = None
    #: Worker-crash recovery bookkeeping (``serve/daemon.py`` watchdog):
    #: once ``device_began`` flips, a crashed job is failed, never
    #: requeued — device state under a crashed update cannot be trusted;
    #: ``requeues`` bounds the one retry a not-yet-begun job may ride.
    device_began: bool = False
    requeues: int = 0
    #: Continuous-batching compatibility key
    #: (``utils/cache.py:batch_compile_fingerprint``), computed once at
    #: admission; ``None`` never coalesces.
    batch_key: Optional[str] = None
    #: Execution attribution, set when a slice worker claims the job:
    #: which executor slice ran it and how many jobs rode its dispatch
    #: group (1 = unbatched).
    slice: Optional[str] = None
    batch_size: int = 1
    #: The claiming slice's jax devices (set by the worker just before
    #: execution; opaque here — this module must stay jax-free). The
    #: executor passes them into ``run_pipeline(devices=...)`` so the job
    #: runs on its slice's sub-mesh only.
    slice_devices: Optional[object] = None
    #: Distributed-tracing id (``obs/trace.py``): minted at client submit
    #: (or at admission when the client sent none), journaled with the
    #: accepted record, stamped on every flight-recorder event — one job
    #: is one span tree across restarts and replica steals.
    trace_id: Optional[str] = None
    #: Admission-time cost prediction
    #: (``obs/costmodel.py:CostPrediction``, opaque here — this module
    #: must stay obs-free): stamped at submit, journaled with the
    #: accepted record, compared against the measured wall clock at the
    #: terminal (the calibration ledger's input pair).
    cost_prediction: Optional[object] = None
    #: The prediction's calibrated best-estimate seconds, copied out by
    #: the daemon at admission so the queue can ORDER on it without
    #: reaching into the opaque prediction object (this module stays
    #: obs-free). ``None`` sorts last within its tier.
    cost_estimate_seconds: Optional[float] = None
    #: Monotonic clock at FIRST admission, stamped by :meth:`put` and
    #: preserved across requeues/steals within a process: the linger
    #: anchor (a group member's latency budget starts when it queued,
    #: not when a worker popped) and the age-cap starvation guard both
    #: read it.
    enqueued_monotonic: Optional[float] = None
    #: Process-wide admission sequence number (stamped with
    #: ``enqueued_monotonic``): the deterministic FIFO tiebreak of the
    #: cost ordering — equal keys pop in admission order, always.
    enqueue_seq: int = -1
    #: How many jobs shared this job's FUSED device program (1 = ran as
    #: its own program, even inside a back-to-back group). Distinct from
    #: ``batch_size`` (the dispatch-group size): a group can be popped
    #: together yet fall back to serial execution.
    fused_size: int = 1
    #: When a worker dequeued the job (the queue-wait measurement's end;
    #: ``submitted_unix`` is its start). Distinct from ``started_unix``
    #: so batched jobs that ride a group but execute back-to-back keep
    #: an honest wait-vs-run split.
    dequeued_unix: Optional[float] = None
    #: Measured queue wait (``dequeued_unix - submitted_unix``), stamped
    #: by the worker so the terminal envelope and the calibration ledger
    #: read one number instead of re-deriving it.
    queue_wait_seconds: Optional[float] = None


def classify_conf(conf, small_site_limit: int = SMALL_JOB_MAX_SITES) -> str:
    """``small`` iff the configuration's candidate-site count is
    statically bounded (synthetic source, explicit ``--references``, no
    checkpoint resume) at or under ``small_site_limit`` (default
    :data:`SMALL_JOB_MAX_SITES`; the daemon's ``--serve-small-site-limit``
    overrides); every cohort whose size only the data knows is ``large``
    — the conservative direction: misclassifying a big job as small
    starves real small jobs, misclassifying a small job as large only
    queues it fairly."""
    if (
        getattr(conf, "source", "synthetic") != "synthetic"
        or getattr(conf, "all_references", False)
        or getattr(conf, "input_path", None)
    ):
        return LARGE_CLASS
    try:
        from spark_examples_tpu.sources.synthetic import DEFAULT_VARIANT_SPACING

        sites = sum(
            (contig.end - contig.start) // DEFAULT_VARIANT_SPACING + 1
            for contigs in conf.get_references()
            for contig in contigs
        )
    except (ValueError, TypeError, AttributeError):
        return LARGE_CLASS
    return SMALL_CLASS if sites <= int(small_site_limit) else LARGE_CLASS


class BoundedJobQueue:
    """Two bounded class lanes + one condition variable. ``pop`` always
    serves the small lane first (the batching contract); within a lane,
    ``ordering="cost"`` (default) serves by calibrated estimate —
    deadline slack first, then shortest-job-first, age-capped, FIFO
    tiebreak — and ``ordering="fifo"`` preserves admission order."""

    def __init__(
        self,
        small_capacity: int = DEFAULT_SMALL_CAPACITY,
        large_capacity: int = DEFAULT_LARGE_CAPACITY,
        ordering: str = "cost",
        age_cap_seconds: float = DEFAULT_AGE_CAP_SECONDS,
    ):
        if small_capacity < 1 or large_capacity < 1:
            raise ValueError(
                f"queue capacities must be >= 1, got small={small_capacity} "
                f"large={large_capacity}"
            )
        if ordering not in ("cost", "fifo"):
            raise ValueError(
                f"queue ordering must be 'cost' or 'fifo', got {ordering!r}"
            )
        if age_cap_seconds <= 0:
            raise ValueError(
                f"age cap must be > 0 seconds, got {age_cap_seconds}"
            )
        self.ordering = ordering
        self.age_cap_seconds = float(age_cap_seconds)
        self.small_capacity = int(small_capacity)
        self.large_capacity = int(large_capacity)
        self._enqueue_seq = 0
        # lock order: queue lock is a leaf — nothing else is acquired
        # while holding it (machine-checked by `graftcheck lockgraph`).
        self._lock = threading.Lock()
        # lock order: the condition shares the queue leaf lock above.
        self._nonempty = threading.Condition(self._lock)
        self._small: Deque[Job] = deque()
        self._large: Deque[Job] = deque()
        self._closed = False
        # Expired-deadline sweep sink (set by the owning daemon): a
        # queued job whose deadline already passed is dead weight — it
        # will fail at dequeue without touching the devices, but until
        # popped it OCCUPIES class capacity, so a full queue of expired
        # jobs 429s live traffic. ``put`` sweeps them out first and
        # hands them to this sink OUTSIDE the queue lock (the sink takes
        # the daemon's table lock; the queue lock stays a leaf). No sink
        # = no sweep: without an owner to settle them, removing queued
        # jobs here would strand them in "queued" forever.
        self._expired_sink = None

    # ------------------------------------------------------------ admission

    def put(self, job: Job, enforce_capacity: bool = True) -> None:
        """Admit one queued job; raises :class:`QueueClosed` after drain
        began and :class:`QueueFull` past the class capacity. Never
        blocks — backpressure is the caller's 429, not a stalled socket.
        ``enforce_capacity=False`` is for jobs that were ALREADY admitted
        once — journal replay and a crashed worker's un-run dispatch-group
        tail: their 202 was acknowledged, so capacity (which bounds NEW
        admissions) must not drop them; the transient overshoot is bounded
        by the previous incarnation's capacity + one dispatch group.

        Before the capacity check, queued jobs whose deadline has already
        expired are swept out (they would fail at dequeue anyway, but
        until popped they occupy capacity — a full queue of expired jobs
        must not 429 live traffic) and handed to the daemon's expired
        sink AFTER the lock is released."""
        swept: List[Job] = []
        try:
            with self._nonempty:
                if self._closed:
                    raise QueueClosed("service is draining; no new jobs")
                swept = self._sweep_expired_locked(time.time())
                lane, capacity = (
                    (self._small, self.small_capacity)
                    if job.job_class == SMALL_CLASS
                    else (self._large, self.large_capacity)
                )
                if enforce_capacity and len(lane) >= capacity:
                    raise QueueFull(job.job_class, capacity)
                # First-admission stamps only: a requeued (crashed-worker)
                # or stolen job keeps its original linger anchor, age
                # clock, and FIFO position — its latency budget was spent
                # from the moment the CLIENT's job first queued, and the
                # tiebreak must not reward a requeue with a newer slot.
                if job.enqueued_monotonic is None:
                    job.enqueued_monotonic = time.monotonic()
                if job.enqueue_seq < 0:
                    job.enqueue_seq = self._enqueue_seq
                    self._enqueue_seq += 1
                lane.append(job)
                # notify_all, not notify: per-slice workers wait for
                # DIFFERENT classes on this one condition, and waking only
                # one could wake a worker whose classes stay empty while
                # the right one sleeps.
                self._nonempty.notify_all()
        finally:
            # Outside the queue lock (leaf-lock discipline) and on BOTH
            # exits: a put that still 429s must not re-strand the expired
            # jobs it already removed from the lanes.
            sink = self._expired_sink
            if sink is not None:
                for expired in swept:
                    sink(expired)

    def set_expired_sink(self, sink) -> None:
        """Install the owning daemon's expired-deadline settler (called
        with each swept :class:`Job`, outside the queue lock)."""
        with self._lock:
            self._expired_sink = sink

    def _sweep_expired_locked(self, now: float) -> List[Job]:
        """Remove every queued job whose deadline already passed (both
        lanes — capacity relief for the class being admitted, honest
        accounting for the other). Caller holds the queue lock and owns
        delivering the swept jobs to the sink after releasing it."""
        if self._expired_sink is None:
            return []
        swept: List[Job] = []
        for lane in (self._small, self._large):
            expired = [
                queued
                for queued in lane
                if queued.deadline_unix is not None
                and now >= queued.deadline_unix
            ]
            for queued in expired:
                lane.remove(queued)
                swept.append(queued)
        return swept

    def inject_reclaimed(self, job: Job) -> None:
        """Admit a RECLAIMED job: one replayed from the journal by a
        restarted daemon, or stolen from a dead peer replica's expired
        lease (``serve/daemon.py`` replay + steal scan). Capacity-exempt
        by contract: the job's 202 was acknowledged by its original
        owner, so this daemon's admission capacity — which bounds NEW
        traffic — must not drop it; the transient overshoot is bounded
        by the previous owner's capacity. Raises :class:`QueueClosed`
        while draining (a draining replica must not adopt work it will
        never run)."""
        self.put(job, enforce_capacity=False)

    # -------------------------------------------------------------- worker

    def _lanes(self, classes: Optional[Sequence[str]]) -> List[Deque[Job]]:
        """Lanes in pop priority order (small first) for a class filter;
        ``None`` = both (the shared-slice worker)."""
        if classes is None:
            return [self._small, self._large]
        lanes = []
        if SMALL_CLASS in classes:
            lanes.append(self._small)
        if LARGE_CLASS in classes:
            lanes.append(self._large)
        if not lanes:
            raise ValueError(f"no known job class in {classes!r}")
        return lanes

    def _priority_key(self, job: Job, now_mono: float, now_unix: float):
        """The cost ordering's total order within one lane. Three tiers:

        - **0 — aged**: queued at least ``age_cap_seconds`` — FIFO among
          themselves (the starvation guard: an expensive job cannot wait
          forever behind a stream of cheap arrivals);
        - **1 — deadline**: sorted by slack (``deadline - now -
          estimate``): the job closest to breaking its promise first;
        - **2 — everything else**: shortest calibrated estimate first
          (``None`` — no prediction stamped — sorts last).

        Every tier tiebreaks on the admission sequence number, so equal
        keys pop in admission order — the ordering is a deterministic
        function of queue state, test- and CI-assertable."""
        seq = job.enqueue_seq
        queued_for = (
            now_mono - job.enqueued_monotonic
            if job.enqueued_monotonic is not None
            else 0.0
        )
        if queued_for >= self.age_cap_seconds:
            return (0, float(seq), seq)
        estimate = job.cost_estimate_seconds
        if job.deadline_unix is not None:
            slack = job.deadline_unix - now_unix - (estimate or 0.0)
            return (1, slack, seq)
        return (2, estimate if estimate is not None else float("inf"), seq)

    def _take_locked(self, lane: Deque[Job]) -> Job:
        """Remove and return the next job of one (non-empty) lane under
        the configured ordering. Caller holds the queue lock."""
        if self.ordering == "fifo":
            return lane.popleft()
        now_mono, now_unix = time.monotonic(), time.time()
        best = min(
            lane, key=lambda job: self._priority_key(job, now_mono, now_unix)
        )
        lane.remove(best)
        return best

    def pop(
        self,
        timeout: Optional[float] = None,
        classes: Optional[Sequence[str]] = None,
    ) -> Optional[Job]:
        """Next job for a worker serving ``classes`` (``None`` = both) —
        every queued small job ahead of any large one; within the lane,
        the configured ordering picks (see :meth:`_priority_key`).
        Returns ``None`` on timeout or when the queue is closed and empty
        of those classes (check :meth:`drained_for` to distinguish)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._nonempty:
            lanes = self._lanes(classes)
            while not any(lanes):
                if self._closed:
                    return None
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return None
                self._nonempty.wait(remaining)
            for lane in lanes:
                if lane:
                    return self._take_locked(lane)
            return None  # unreachable; keeps the type checker honest

    def pop_batch(
        self,
        timeout: Optional[float] = None,
        classes: Optional[Sequence[str]] = None,
        max_batch: int = DEFAULT_BATCH_MAX_JOBS,
        linger_seconds: float = DEFAULT_BATCH_LINGER_SECONDS,
    ) -> List[Job]:
        """One dispatch group: the next job plus, when it is a SMALL job
        with a batch key, every queued small job with the SAME key — up to
        ``max_batch`` jobs, lingering up to ``linger_seconds`` for more
        compatible arrivals when the group is not yet full. Large jobs
        never batch (group of one). Non-matching small jobs keep their
        queue order untouched. Returns ``[]`` on timeout/closed-empty.

        The linger clock anchors at the FIRST group member's enqueue
        time: a head job that already sat in the queue for the whole
        window (or a group already full at pop time) dispatches with ZERO
        added wait — the worker never re-spends a latency budget the job
        already paid queuing. ``pop_batch`` therefore never returns later
        than ``first-member-enqueue + linger_seconds`` (plus lock
        wakeups), regardless of when the worker called it."""
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        first = self.pop(timeout=timeout, classes=classes)
        if first is None:
            return []
        if (
            first.job_class != SMALL_CLASS
            or first.batch_key is None
            or max_batch == 1
        ):
            return [first]
        batch = [first]
        anchor = (
            first.enqueued_monotonic
            if first.enqueued_monotonic is not None
            else time.monotonic()
        )
        linger_deadline = anchor + max(0.0, float(linger_seconds))
        with self._nonempty:
            while len(batch) < max_batch:
                matched = [
                    job
                    for job in self._small
                    if job.batch_key == first.batch_key
                ]
                for job in matched[: max_batch - len(batch)]:
                    self._small.remove(job)
                    batch.append(job)
                if len(batch) >= max_batch or self._closed:
                    break
                remaining = linger_deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._nonempty.wait(remaining)
        return batch

    # ---------------------------------------------------------- management

    def remove(self, job_id: str) -> Optional[Job]:
        """Pull one still-queued job out (cancellation); ``None`` when the
        worker already claimed it."""
        with self._lock:
            for lane in (self._small, self._large):
                for job in lane:
                    if job.id == job_id:
                        lane.remove(job)
                        return job
        return None

    def close(self) -> None:
        """Stop admission (drain): pending jobs still pop; new puts raise
        :class:`QueueClosed`; blocked pops wake."""
        with self._nonempty:
            self._closed = True
            self._nonempty.notify_all()

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    @property
    def drained(self) -> bool:
        """Closed AND empty — the worker's exit condition."""
        with self._lock:
            return self._closed and not self._small and not self._large

    def drained_for(self, classes: Optional[Sequence[str]] = None) -> bool:
        """Closed AND empty of the given classes — a per-slice worker's
        exit condition (a small-slice worker must not keep spinning for a
        large backlog it will never pop)."""
        with self._lock:
            return self._closed and not any(self._lanes(classes))

    def depth(self) -> Dict[str, int]:
        with self._lock:
            return {
                SMALL_CLASS: len(self._small),
                LARGE_CLASS: len(self._large),
            }

    def total_depth(self) -> int:
        with self._lock:
            return len(self._small) + len(self._large)


__all__ = [
    "SMALL_CLASS",
    "LARGE_CLASS",
    "SMALL_JOB_MAX_SITES",
    "DEFAULT_SMALL_CAPACITY",
    "DEFAULT_LARGE_CAPACITY",
    "DEFAULT_BATCH_MAX_JOBS",
    "DEFAULT_BATCH_LINGER_SECONDS",
    "DEFAULT_AGE_CAP_SECONDS",
    "QueueFull",
    "QueueClosed",
    "Job",
    "classify_conf",
    "BoundedJobQueue",
]

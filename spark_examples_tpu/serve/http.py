"""Thin stdlib HTTP front-end of the resident PCA service.

No new dependencies: ``http.server.ThreadingHTTPServer`` carries the
JSON protocol (``serve/protocol.py``) onto :class:`PcaService`
(``serve/daemon.py``). Routes:

- ``POST /v1/jobs``            — submit (202 admitted; 400/413 plan
  rejection with the plan facts in the body; 429 backpressure; 503
  draining)
- ``GET  /v1/jobs/<id>``       — job status/result
- ``POST /v1/jobs/<id>/cancel``— cancel a queued job (409 once running)
- ``GET  /metrics``            — Prometheus text export of the service
  registry (``obs/metrics.py``)
- ``GET  /v1/fleet/stats``     — per-class latency quantiles + the fleet
  calibration fold (``serve/daemon.py:fleet_stats``)
- ``GET  /healthz``            — mesh/queue liveness JSON

``serve_main`` is the ``python -m spark_examples_tpu serve`` entry
point: it initializes the backend once, binds the server (``--port 0``
picks an ephemeral port; ``--endpoint-file`` publishes the bound URL for
scripts), and installs the graceful-drain signal handlers — SIGTERM (or
SIGINT) stops admission with 503, lets the worker finish every admitted
job, then exits 0.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence

from spark_examples_tpu.serve.daemon import (
    DEFAULT_TERMINAL_RETENTION,
    PcaService,
)
from spark_examples_tpu.serve.journal import (
    DEFAULT_LEASE_SECONDS,
    RunDirBusy,
)
from spark_examples_tpu.serve.protocol import error_doc
from spark_examples_tpu.serve.queue import (
    DEFAULT_AGE_CAP_SECONDS,
    DEFAULT_BATCH_LINGER_SECONDS,
    DEFAULT_BATCH_MAX_JOBS,
    DEFAULT_LARGE_CAPACITY,
    DEFAULT_SMALL_CAPACITY,
    SMALL_JOB_MAX_SITES,
)

#: Largest accepted request body: a flag list is hundreds of bytes; one
#: MiB of headroom keeps admission O(1) in host memory no matter what a
#: client posts (oversized bodies are 413 without being read further).
MAX_BODY_BYTES = 1 << 20

#: ``Retry-After`` hint on non-terminal job-status responses: the poll
#: cadence the server ASKS for (a small-job completion is sub-second
#: warm; half a second keeps the client snappy without hammering a
#: daemon mid-whole-genome-job).
POLL_RETRY_AFTER_SECONDS = 0.5


class ServeHandler(BaseHTTPRequestHandler):
    """One request; ``self.server.service`` is the :class:`PcaService`."""

    server_version = "spark-examples-tpu-serve/1"
    protocol_version = "HTTP/1.1"

    # ------------------------------------------------------------- plumbing

    def log_message(self, format: str, *args) -> None:  # noqa: A002
        if getattr(self.server, "verbose", False):
            sys.stderr.write(
                f"serve[{self.address_string()}]: {format % args}\n"
            )

    def _send_json(
        self, status: int, doc, retry_after: Optional[float] = None
    ) -> None:
        body = (json.dumps(doc, indent=2) + "\n").encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if retry_after is not None:
            self.send_header("Retry-After", f"{retry_after:g}")
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, status: int, text: str, content_type: str) -> None:
        body = text.encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_json_body(self):
        """The request body as parsed JSON, or ``None`` after an error
        response was already sent."""
        try:
            length = int(self.headers.get("Content-Length", "0"))
        except ValueError:
            length = -1
        if length < 0 or length > MAX_BODY_BYTES:
            # The oversized body stays unread; the connection cannot be
            # reused (leftover bytes would parse as the next request).
            self.close_connection = True
            self._send_json(
                413,
                error_doc(
                    "body-too-large",
                    f"request body must be <= {MAX_BODY_BYTES} bytes",
                ),
            )
            return None
        raw = self.rfile.read(length)
        try:
            return json.loads(raw.decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            self._send_json(
                400, error_doc("bad-json", f"request body is not JSON: {e}")
            )
            return None

    # --------------------------------------------------------------- routes

    def do_GET(self) -> None:  # noqa: N802 — http.server's spelling
        service: PcaService = self.server.service
        if self.path == "/healthz":
            self._send_json(200, service.healthz())
            return
        if self.path == "/metrics":
            self._send_text(
                200,
                service.metrics_text(),
                "text/plain; version=0.0.4; charset=utf-8",
            )
            return
        if self.path == "/v1/fleet/stats":
            self._send_json(200, service.fleet_stats())
            return
        if self.path.startswith("/v1/jobs/"):
            job_id = self.path[len("/v1/jobs/"):]
            if job_id and "/" not in job_id:
                status, doc = service.job_status(job_id)
                # A non-terminal job tells the poller WHEN to come back
                # (the shared utils/retry.py client arithmetic honors it)
                # — server-paced polling instead of client guesswork.
                job_state = (doc.get("job") or {}).get("status")
                self._send_json(
                    status,
                    doc,
                    retry_after=(
                        POLL_RETRY_AFTER_SECONDS
                        if status == 200
                        and job_state in ("queued", "running")
                        else None
                    ),
                )
                return
        self._send_json(
            404, error_doc("not-found", f"no route GET {self.path}")
        )

    def _drain_body(self) -> None:
        """Consume a request body this route ignores: on a keep-alive
        connection unread bytes would parse as the NEXT request line.
        Oversized bodies close the connection instead of being read."""
        try:
            length = int(self.headers.get("Content-Length", "0"))
        except ValueError:
            length = -1
        if length < 0 or length > MAX_BODY_BYTES:
            self.close_connection = True
            return
        if length:
            self.rfile.read(length)

    def do_POST(self) -> None:  # noqa: N802
        service: PcaService = self.server.service
        if self.path == "/v1/jobs":
            doc = self._read_json_body()
            if doc is None:
                return
            # Trace-context propagation (obs/trace.py): the client's
            # X-Trace-Id header rides into the admission, the journal,
            # and every flight-recorder event of the job's life — a
            # malformed or absent id gets a server-minted replacement
            # inside submit(), never a rejection.
            from spark_examples_tpu.obs.trace import TRACE_HEADER

            status, body = service.submit(
                doc, trace_id=self.headers.get(TRACE_HEADER)
            )
            self._send_json(status, body)
            return
        self._drain_body()
        if self.path.startswith("/v1/jobs/") and self.path.endswith("/cancel"):
            job_id = self.path[len("/v1/jobs/"):-len("/cancel")]
            if job_id and "/" not in job_id:
                status, body = service.cancel(job_id)
                self._send_json(status, body)
                return
        self._send_json(
            404, error_doc("not-found", f"no route POST {self.path}")
        )


class ServeServer(ThreadingHTTPServer):
    """Bound server carrying the service; request threads are daemons so
    a drain never waits on an idle keep-alive connection."""

    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, address, service: PcaService, verbose: bool = False):
        super().__init__(address, ServeHandler)
        self.service = service
        self.verbose = verbose

    @property
    def url(self) -> str:
        host, port = self.server_address[:2]
        return f"http://{host}:{port}"


def start_server(
    service: PcaService,
    host: str = "127.0.0.1",
    port: int = 0,
    verbose: bool = False,
) -> ServeServer:
    """Bind (port 0 = ephemeral) and serve in a background thread; the
    in-process form tests and embedders use. The caller owns shutdown:
    ``server.shutdown()`` then ``service.stop()``."""
    server = ServeServer((host, port), service, verbose=verbose)
    thread = threading.Thread(
        target=server.serve_forever, name="serve-http", daemon=True
    )
    thread.start()
    return server


def _write_endpoint_file(path: str, url: str) -> None:
    """Atomic publish of the bound URL (scripts poll for this file)."""
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(url + "\n")
    os.replace(tmp, path)


def serve_main(argv: Optional[Sequence[str]] = None) -> int:
    """The ``serve`` CLI verb (``python -m spark_examples_tpu serve``)."""
    parser = argparse.ArgumentParser(prog="spark_examples_tpu serve")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument(
        "--port",
        type=int,
        default=8765,
        help="Listen port (0 = ephemeral; see --endpoint-file).",
    )
    parser.add_argument(
        "--run-dir",
        default=None,
        help=(
            "Service run directory: per-job manifests and captured stdout "
            "land under <run-dir>/jobs/<job-id>/. Default: a fresh "
            "temporary directory (path printed at startup)."
        ),
    )
    parser.add_argument(
        "--queue-small",
        type=int,
        default=DEFAULT_SMALL_CAPACITY,
        help="Small-class admission queue capacity (default %(default)s).",
    )
    parser.add_argument(
        "--queue-large",
        type=int,
        default=DEFAULT_LARGE_CAPACITY,
        help="Large-class admission queue capacity (default %(default)s).",
    )
    parser.add_argument(
        "--host-mem-budget",
        type=int,
        default=None,
        metavar="BYTES",
        help=(
            "Admission host-RAM budget: every job kind (wire/JSONL/SAM "
            "included) resolves a finite static bound "
            "(parallel/mesh.py:host_peak_bytes); jobs whose bound "
            "exceeds the budget are rejected 413 at admission."
        ),
    )
    parser.add_argument(
        "--heartbeat-seconds",
        type=float,
        default=0.0,
        help="Service heartbeat interval on stderr (0 = off).",
    )
    parser.add_argument(
        "--terminal-retention",
        type=int,
        default=DEFAULT_TERMINAL_RETENTION,
        metavar="N",
        help=(
            "Completed jobs kept queryable in memory (default "
            "%(default)s); older terminal records are evicted — their "
            "per-job manifests stay on disk under --run-dir."
        ),
    )
    parser.add_argument(
        "--executor-slices",
        default="auto",
        metavar="N|auto",
        help=(
            "Small executor slices to carve off the device set (each its "
            "own mesh + worker, so small jobs run concurrently beside one "
            "large job). 'auto' (default) = 1 when a device can be "
            "spared, 0 on a single device; 0 = the shared serial worker."
        ),
    )
    parser.add_argument(
        "--small-slice-devices",
        type=int,
        default=1,
        metavar="D",
        help="Devices per small executor slice (default %(default)s).",
    )
    parser.add_argument(
        "--serve-small-site-limit",
        type=int,
        default=SMALL_JOB_MAX_SITES,
        metavar="SITES",
        help=(
            "Largest statically-bounded candidate-site count classified "
            "as a small job (default %(default)s); larger or unbounded "
            "configurations queue as large."
        ),
    )
    parser.add_argument(
        "--batch-max-jobs",
        type=int,
        default=DEFAULT_BATCH_MAX_JOBS,
        metavar="N",
        help=(
            "Continuous batching: at most this many compatible small "
            "jobs per dispatch group (default %(default)s; 1 disables "
            "coalescing)."
        ),
    )
    parser.add_argument(
        "--batch-linger-seconds",
        type=float,
        default=DEFAULT_BATCH_LINGER_SECONDS,
        metavar="S",
        help=(
            "Continuous batching: wait up to this long for more "
            "compatible small jobs before dispatching a non-full group "
            "(default %(default)s — dispatch what is queued now)."
        ),
    )
    parser.add_argument(
        "--no-batch-fuse",
        action="store_true",
        help=(
            "Run every batch group's jobs back to back as separate "
            "device programs instead of fusing an eligible group into "
            "ONE stacked program (fusion is on by default; results are "
            "byte-identical either way)."
        ),
    )
    parser.add_argument(
        "--serve-ordering",
        choices=("cost", "fifo"),
        default="cost",
        metavar="POLICY",
        help=(
            "Queue ordering within each class lane: 'cost' (default) "
            "serves by calibrated estimate — shortest-job-first, "
            "deadline jobs by slack, starvation-capped by "
            "--serve-age-cap-seconds; 'fifo' preserves admission order."
        ),
    )
    parser.add_argument(
        "--serve-age-cap-seconds",
        type=float,
        default=DEFAULT_AGE_CAP_SECONDS,
        metavar="S",
        help=(
            "Starvation bound for --serve-ordering=cost: a job queued "
            "this long jumps ahead of cost ordering (FIFO among aged "
            "jobs; default %(default)s)."
        ),
    )
    parser.add_argument(
        "--replica-id",
        default=None,
        metavar="ID",
        help=(
            "Join --run-dir as one of N replica daemons sharing its job "
            "journal: jobs are leased (time-bounded, epoch-fenced), "
            "liveness is heartbeated, and a job whose owning replica "
            "died is stolen by a survivor. Replicas need distinct ids; "
            "without this flag the daemon owns the run dir exclusively."
        ),
    )
    parser.add_argument(
        "--lease-seconds",
        type=float,
        default=DEFAULT_LEASE_SECONDS,
        metavar="S",
        help=(
            "Job-lease time-to-live with --replica-id (default "
            "%(default)s): a healthy replica renews 3x per TTL; a lease "
            "this stale marks its owner dead."
        ),
    )
    parser.add_argument(
        "--lease-grace-seconds",
        type=float,
        default=None,
        metavar="S",
        help=(
            "Clock-skew grace: peers steal only past expiry PLUS this "
            "window, while the owner abandons at expiry (default: the "
            "lease TTL)."
        ),
    )
    parser.add_argument(
        "--steal-interval-seconds",
        type=float,
        default=None,
        metavar="S",
        help=(
            "How often a replica scans for dead peers' expired leases "
            "(default: the lease TTL)."
        ),
    )
    parser.add_argument(
        "--no-deadline-feasibility",
        action="store_true",
        help=(
            "Queue jobs whose deadline_seconds is below the calibrated "
            "cost estimate instead of rejecting them 413 "
            "deadline-infeasible at admission."
        ),
    )
    parser.add_argument(
        "--no-persistent-cache",
        action="store_true",
        help=(
            "Do not persist warm state under --run-dir (neither the XLA "
            "compilation cache nor the warm-geometry ledger): a "
            "restarted daemon then recompiles from scratch and honestly "
            "reports every first geometry cold."
        ),
    )
    parser.add_argument(
        "--endpoint-file",
        default=None,
        metavar="PATH",
        help="Write the bound URL here once listening (atomic).",
    )
    parser.add_argument(
        "--verbose", action="store_true", help="Log every HTTP request."
    )
    ns = parser.parse_args(list(argv) if argv is not None else None)

    # Nonsense serving parameters must fail the daemon AT STARTUP with the
    # argparse contract (exit 2), never surface as a crash-looping worker
    # or a queue that silently misclassifies everything.
    if ns.serve_small_site_limit < 1:
        parser.error(
            f"--serve-small-site-limit must be >= 1 site, got "
            f"{ns.serve_small_site_limit}"
        )
    if ns.small_slice_devices < 1:
        parser.error(
            f"--small-slice-devices must be >= 1, got "
            f"{ns.small_slice_devices}"
        )
    if ns.batch_max_jobs < 1:
        parser.error(
            f"--batch-max-jobs must be >= 1, got {ns.batch_max_jobs}"
        )
    if ns.batch_linger_seconds < 0:
        parser.error(
            f"--batch-linger-seconds must be >= 0, got "
            f"{ns.batch_linger_seconds}"
        )
    if ns.serve_age_cap_seconds <= 0:
        parser.error(
            f"--serve-age-cap-seconds must be > 0, got "
            f"{ns.serve_age_cap_seconds}"
        )
    if ns.lease_seconds <= 0:
        parser.error(
            f"--lease-seconds must be > 0, got {ns.lease_seconds}"
        )
    if ns.lease_grace_seconds is not None and ns.lease_grace_seconds < 0:
        parser.error(
            f"--lease-grace-seconds must be >= 0, got "
            f"{ns.lease_grace_seconds}"
        )
    if ns.steal_interval_seconds is not None and ns.steal_interval_seconds <= 0:
        parser.error(
            f"--steal-interval-seconds must be > 0, got "
            f"{ns.steal_interval_seconds}"
        )
    if ns.executor_slices != "auto":
        try:
            slices_spec: Optional[int] = int(ns.executor_slices)
        except ValueError:
            parser.error(
                f"--executor-slices must be an integer or 'auto', got "
                f"{ns.executor_slices!r}"
            )
        if slices_spec < 0:
            parser.error(
                f"--executor-slices must be >= 0, got {slices_spec}"
            )
    else:
        slices_spec = None

    service = PcaService(
        run_dir=ns.run_dir,
        small_capacity=ns.queue_small,
        large_capacity=ns.queue_large,
        terminal_retention=ns.terminal_retention,
        host_mem_budget=ns.host_mem_budget,
        heartbeat_seconds=ns.heartbeat_seconds,
        small_slices=slices_spec,
        small_slice_devices=ns.small_slice_devices,
        small_site_limit=ns.serve_small_site_limit,
        batch_max_jobs=ns.batch_max_jobs,
        batch_linger_seconds=ns.batch_linger_seconds,
        batch_fuse=not ns.no_batch_fuse,
        ordering=ns.serve_ordering,
        age_cap_seconds=ns.serve_age_cap_seconds,
        persistent_cache=not ns.no_persistent_cache,
        replica_id=ns.replica_id,
        lease_seconds=ns.lease_seconds,
        lease_grace_seconds=ns.lease_grace_seconds,
        steal_interval_seconds=ns.steal_interval_seconds,
        deadline_feasibility=not ns.no_deadline_feasibility,
        # The CLI daemon always guards its run dir: a second daemon on
        # the same --run-dir without --replica-id exits 2 below instead
        # of silently corrupting the shared journal.
        guard_run_dir=True,
    )
    try:
        service.start()
    except RunDirBusy as e:
        print(f"serve: {e}", file=sys.stderr)
        return 2
    except ValueError as e:
        # A slice topology the device set cannot satisfy (e.g. every
        # device reserved for small slices) is a configuration error —
        # the same exit-2 contract as the flag checks above.
        print(f"serve: invalid configuration: {e}", file=sys.stderr)
        return 2
    server = ServeServer((ns.host, ns.port), service, verbose=ns.verbose)
    if ns.endpoint_file:
        _write_endpoint_file(ns.endpoint_file, server.url)

    def _drain_then_shutdown() -> None:
        service.wait_drained()
        server.shutdown()

    def _on_signal(signum, _frame) -> None:
        print(
            f"serve: received signal {signum}; draining "
            "(new jobs get 503, admitted jobs finish)",
            file=sys.stderr,
            flush=True,
        )
        service.begin_drain()
        threading.Thread(
            target=_drain_then_shutdown, name="serve-drain", daemon=True
        ).start()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    slices = ",".join(
        f"{w.spec.name}:{w.spec.device_count}" for w in service._workers
    )
    replica = (
        f" replica={service.replica_id}" if service.replica_id else ""
    )
    print(
        f"serve: listening on {server.url} "
        f"(devices={service.device_count} platform={service.platform} "
        f"slices=[{slices}]{replica} run_dir={service.run_dir})",
        file=sys.stderr,
        flush=True,
    )
    try:
        server.serve_forever()
    finally:
        server.server_close()
    drained = service.wait_drained(timeout=60.0)
    # The drain verdict is decided; a late duplicate SIGTERM (an impatient
    # supervisor re-signaling) must not flip the exit code to 143 during
    # interpreter teardown — the OS-level disposition outlives Python's
    # handler machinery.
    signal.signal(signal.SIGTERM, signal.SIG_IGN)
    signal.signal(signal.SIGINT, signal.SIG_IGN)
    print(
        "serve: drained cleanly"
        if drained
        else "serve: worker did not drain within 60s",
        file=sys.stderr,
        flush=True,
    )
    return 0 if drained else 1


__all__ = [
    "MAX_BODY_BYTES",
    "POLL_RETRY_AFTER_SECONDS",
    "ServeHandler",
    "ServeServer",
    "start_server",
    "serve_main",
]

"""Stdlib HTTP client for the resident PCA service + the ``submit`` verb.

``ServeClient`` is the scripting surface (the smoke test and
``tests/test_serve.py`` ride it); ``submit_main`` is the CLI verb::

    python -m spark_examples_tpu submit --url http://127.0.0.1:8765 \\
        -- --num-samples 64 --references 17:41196311:41277499

Everything after ``--`` is the EXISTING PCA flag namespace, forwarded
verbatim — a batch invocation becomes a served job by replacing
``variants-pca`` with ``submit --url ... --``. Waiting (``--wait``, the
default) polls ``GET /v1/jobs/<id>`` honoring the server's
``Retry-After`` hint with the shared ``utils/retry.py`` full-jitter
backoff between polls. Exit codes: 0 job done, 1 job
failed/cancelled/timed out, 2 rejected at admission (the rejection
body, including the plan facts, prints as JSON).

The client never imports jax: submitting from a laptop to a TPU-backed
daemon must not initialize a local backend.
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, Optional, Sequence, Tuple

from spark_examples_tpu.serve.protocol import (
    JOB_KINDS,
    RESERVED_KINDS,
    TERMINAL_STATUSES,
    request_doc,
)
from spark_examples_tpu.utils.retry import (
    full_jitter_delay,
    retry_after_seconds,
)

#: The submit verb's ``--kind`` choices, sourced from the protocol's own
#: tables (never a drifted copy). Reserved kinds pass argparse on purpose:
#: the server's structured ``reserved-kind`` 400 is the answer the user
#: should see, not an argparse usage error.
SUBMIT_KIND_CHOICES = tuple(JOB_KINDS) + tuple(RESERVED_KINDS)

#: Hard cap on response bodies (bounded read — a misbehaving server must
#: not stage unbounded bytes in client memory).
MAX_RESPONSE_BYTES = 64 << 20


class ServeError(Exception):
    """A non-2xx service response; carries the HTTP status and the parsed
    error body (``error.code``, ``error.message``, optional ``plan``)."""

    def __init__(self, status: int, body):
        code = None
        message = None
        if isinstance(body, dict):
            error = body.get("error") or {}
            code = error.get("code")
            message = error.get("message")
        super().__init__(
            f"HTTP {status}"
            + (f" [{code}]" if code else "")
            + (f": {message}" if message else "")
        )
        self.status = status
        self.body = body
        self.code = code


def _connection_refused(e: BaseException) -> bool:
    """Whether this transport error means the request NEVER reached a
    server (the kernel refused the connect) — the only failure class a
    single-shot POST may fail over on without risking a duplicate."""
    if isinstance(e, ConnectionRefusedError):
        return True
    return isinstance(
        getattr(e, "reason", None), ConnectionRefusedError
    )


class ServeClient:
    """``url`` may be a comma-separated endpoint list
    (``http://a:8765,http://b:8766`` — the multi-replica serving form):
    requests go to the current endpoint and fail over to the next when a
    connection is refused, so a client outlives any single replica."""

    def __init__(
        self,
        url: str,
        timeout: float = 30.0,
        max_retries: int = 3,
        backoff_base: float = 0.1,
        backoff_cap: float = 2.0,
        sleep: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
    ):
        self.urls = [
            u.strip().rstrip("/") for u in url.split(",") if u.strip()
        ]
        if not self.urls:
            raise ValueError(f"no endpoint in url {url!r}")
        self._endpoint = 0
        self.timeout = float(timeout)
        self.max_retries = int(max_retries)
        self.backoff_base = float(backoff_base)
        self.backoff_cap = float(backoff_cap)
        self._sleep = sleep
        self._rng = rng if rng is not None else random.Random()

    @property
    def url(self) -> str:
        """The endpoint requests currently target (rotates on failover)."""
        return self.urls[self._endpoint]

    # ------------------------------------------------------------ transport

    def _backoff(self, attempt: int, response_headers) -> None:
        """One bounded-backoff delay (the shared ``utils/retry.py``
        arithmetic): honor a server-sent ``Retry-After`` when present,
        full jitter otherwise; both capped by ``backoff_cap``."""
        delay = retry_after_seconds(response_headers, self.backoff_cap)
        if delay is None:
            delay = full_jitter_delay(
                attempt, self.backoff_base, self.backoff_cap, self._rng
            )
        self._sleep(delay)

    def _request(
        self,
        method: str,
        path: str,
        doc: Optional[Dict] = None,
        extra_headers: Optional[Dict[str, str]] = None,
    ) -> Tuple[int, object, str, Optional[Dict]]:
        """One HTTP exchange. GETs (``status``/``/metrics``/``/healthz``)
        retry connection resets and 5xx responses with bounded backoff —
        they are idempotent, and a daemon mid-worker-recovery must not
        look "down" to a poller that raced one refused connect. POSTs
        stay single-shot PER SERVER: a retried submit could enqueue the
        job twice — but a REFUSED connect provably never reached a
        server, so both verbs fail over to the next configured endpoint
        (once per extra endpoint per request) when one is given."""
        data = None
        headers = {"Accept": "application/json"}
        if extra_headers:
            headers.update(extra_headers)
        if doc is not None:
            data = json.dumps(doc).encode("utf-8")
            headers["Content-Type"] = "application/json"
        attempts = max(1, self.max_retries) if method == "GET" else 1
        failovers_left = len(self.urls) - 1
        attempt = 0
        while True:
            retryable = attempt + 1 < attempts
            req = urllib.request.Request(
                self.url + path, data=data, method=method, headers=headers
            )
            try:
                with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                    status = resp.status
                    raw = resp.read(MAX_RESPONSE_BYTES + 1)
                    content_type = resp.headers.get("Content-Type", "")
                    headers = dict(resp.headers)
            except urllib.error.HTTPError as e:
                if e.code >= 500 and retryable:
                    self._backoff(attempt, e.headers)
                    attempt += 1
                    continue
                status = e.code
                raw = e.read(MAX_RESPONSE_BYTES + 1)
                content_type = (
                    e.headers.get("Content-Type", "") if e.headers else ""
                )
                headers = dict(e.headers) if e.headers else None
            except (urllib.error.URLError, OSError) as e:
                if _connection_refused(e) and failovers_left > 0:
                    # This replica is down; move to the next endpoint
                    # immediately (no backoff, no attempt consumed — the
                    # request never left this host).
                    failovers_left -= 1
                    self._endpoint = (self._endpoint + 1) % len(self.urls)
                    continue
                # Connection reset (possibly mid-response): safe to
                # resend only because GETs are idempotent.
                if retryable:
                    self._backoff(attempt, None)
                    attempt += 1
                    continue
                raise
            break
        if len(raw) > MAX_RESPONSE_BYTES:
            raise ServeError(
                status,
                {
                    "error": {
                        "code": "response-too-large",
                        "message": f"response exceeds {MAX_RESPONSE_BYTES} bytes",
                    }
                },
            )
        text = raw.decode("utf-8", errors="replace")
        if "application/json" in content_type:
            try:
                return status, json.loads(text), text, headers
            except json.JSONDecodeError:
                pass
        return status, None, text, headers

    def _json_with_headers(
        self,
        method: str,
        path: str,
        doc: Optional[Dict] = None,
        extra_headers: Optional[Dict[str, str]] = None,
    ) -> Tuple[Dict, Optional[Dict]]:
        status, body, text, headers = self._request(
            method, path, doc, extra_headers=extra_headers
        )
        if status >= 400:
            raise ServeError(status, body if body is not None else text)
        if not isinstance(body, dict):
            raise ServeError(
                status,
                {
                    "error": {
                        "code": "bad-response",
                        "message": f"expected a JSON object, got: {text[:200]}",
                    }
                },
            )
        return body, headers

    def _json(
        self,
        method: str,
        path: str,
        doc: Optional[Dict] = None,
        extra_headers: Optional[Dict[str, str]] = None,
    ) -> Dict:
        return self._json_with_headers(
            method, path, doc, extra_headers=extra_headers
        )[0]

    # ----------------------------------------------------------------- verbs

    def submit(
        self,
        flags: Sequence[str],
        kind: str = "pca",
        deadline_seconds: Optional[float] = None,
        tag: Optional[str] = None,
        trace_id: Optional[str] = None,
    ) -> Dict:
        """Submit one job; returns the job envelope (``doc["job"]["id"]``
        is the handle). Raises :class:`ServeError` on every rejection —
        ``.body["plan"]`` carries the admission validator's facts.

        This is where a trace is BORN: the client mints a trace id (or
        forwards the caller's — a batch harness correlating many submits)
        and sends it as the ``X-Trace-Id`` header; the server stamps it
        on the job, its journal record, and every flight-recorder event,
        and echoes it back as ``doc["job"]["trace"]``."""
        from spark_examples_tpu.obs.trace import TRACE_HEADER, mint_trace_id

        trace = trace_id if trace_id is not None else mint_trace_id()
        return self._json(
            "POST",
            "/v1/jobs",
            request_doc(
                flags, kind=kind, deadline_seconds=deadline_seconds, tag=tag
            ),
            extra_headers={TRACE_HEADER: trace},
        )

    def status(self, job_id: str) -> Dict:
        return self._json("GET", f"/v1/jobs/{job_id}")

    def cancel(self, job_id: str) -> Dict:
        return self._json("POST", f"/v1/jobs/{job_id}/cancel")

    def wait(
        self,
        job_id: str,
        timeout: float = 600.0,
        poll_cap_seconds: float = 2.0,
    ) -> Dict:
        """Poll ``GET /v1/jobs/<id>`` until the job reaches a terminal
        status; raises :class:`TimeoutError` past ``timeout``.

        Pacing is server-first: a ``Retry-After`` header on a non-terminal
        response (``serve/http.py`` sends one) is honored exactly; without
        one the shared ``utils/retry.py`` full-jitter backoff paces the
        polls — both capped by ``poll_cap_seconds`` so a long job is
        polled steadily, not hammered, and a thundering herd of waiting
        clients decorrelates instead of synchronizing."""
        deadline = time.monotonic() + timeout
        attempt = 0
        while True:
            try:
                body, headers = self._json_with_headers(
                    "GET", f"/v1/jobs/{job_id}"
                )
            except ServeError as e:
                if e.status != 404 or len(self.urls) <= 1:
                    raise
                # The failover window: a surviving replica answers 404
                # for a dead peer's job until its steal scan adopts it
                # (lease expiry + grace + one scan interval). With more
                # than one endpoint configured that is a non-terminal
                # state, bounded by this wait's own deadline.
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"job {job_id} not visible on any endpoint after "
                        f"{timeout}s (failover pending?)"
                    ) from None
                headers = None
                body = None
            if body is not None and body["job"]["status"] in TERMINAL_STATUSES:
                return body
            if body is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"job {job_id} still {body['job']['status']!r} after "
                    f"{timeout}s"
                )
            delay = retry_after_seconds(headers, poll_cap_seconds)
            if delay is None:
                delay = full_jitter_delay(
                    attempt, self.backoff_base, poll_cap_seconds, self._rng
                )
            attempt += 1
            self._sleep(delay)

    def metrics(self) -> str:
        status, _body, text, _headers = self._request("GET", "/metrics")
        if status >= 400:
            raise ServeError(status, text)
        return text

    def healthz(self) -> Dict:
        return self._json("GET", "/healthz")


def submit_main(argv: Optional[Sequence[str]] = None) -> int:
    """The ``submit`` CLI verb; see the module docstring."""
    parser = argparse.ArgumentParser(prog="spark_examples_tpu submit")
    parser.add_argument(
        "--url",
        required=True,
        help=(
            "Service base URL (see serve --port), or a comma-separated "
            "endpoint list (http://a:8765,http://b:8766): the client "
            "fails over to the next endpoint when a connect is refused "
            "— the multi-replica serving form."
        ),
    )
    parser.add_argument(
        "--kind", choices=list(SUBMIT_KIND_CHOICES), default="pca"
    )
    parser.add_argument("--deadline-seconds", type=float, default=None)
    parser.add_argument("--tag", default=None)
    parser.add_argument(
        "--wait",
        action="store_true",
        help=(
            "Poll until the job reaches a terminal state (the default; "
            "spelled out for scripts that want the contract explicit). "
            "Polling honors server Retry-After hints with full-jitter "
            "backoff between them; the exit code mirrors the terminal "
            "state (0 done, 1 failed/cancelled/timed out)."
        ),
    )
    parser.add_argument(
        "--no-wait",
        action="store_true",
        help="Print the job id and return without polling.",
    )
    parser.add_argument(
        "--timeout",
        type=float,
        default=600.0,
        help="Polling timeout in seconds (with waiting enabled).",
    )
    parser.add_argument(
        "--json",
        action="store_true",
        help="Print the final job/error envelope as JSON.",
    )
    parser.add_argument(
        "flags",
        nargs=argparse.REMAINDER,
        help="PCA flag namespace after '--' (forwarded verbatim).",
    )
    ns = parser.parse_args(list(argv) if argv is not None else None)
    if ns.wait and ns.no_wait:
        parser.error("--wait and --no-wait are mutually exclusive")
    flags = list(ns.flags)
    if flags and flags[0] == "--":
        flags = flags[1:]

    client = ServeClient(ns.url)
    try:
        doc = client.submit(
            flags,
            kind=ns.kind,
            deadline_seconds=ns.deadline_seconds,
            tag=ns.tag,
        )
    except ServeError as e:
        body = e.body if isinstance(e.body, dict) else {"raw": e.body}
        print(json.dumps({"http_status": e.status, **body}, indent=2))
        return 2
    job_id = doc["job"]["id"]
    if ns.no_wait:
        print(json.dumps(doc, indent=2) if ns.json else job_id)
        return 0
    try:
        doc = client.wait(job_id, timeout=ns.timeout)
    except TimeoutError as e:
        print(str(e), file=sys.stderr)
        return 1
    job = doc["job"]
    if ns.json:
        print(json.dumps(doc, indent=2))
    elif job["status"] == "done":
        result = job.get("result") or {}
        for line in result.get("pc_lines") or []:
            print(line)
        if "similarity" in result:
            print(json.dumps(result["similarity"], indent=2))
        print(
            f"job {job_id} done in {job['seconds']:.3f}s "
            f"(compile cache {job['compile_cache']}; "
            f"manifest {job['manifest_path']})",
            file=sys.stderr,
        )
    else:
        print(
            f"job {job_id} {job['status']}: {job.get('error')}",
            file=sys.stderr,
        )
    return 0 if job["status"] == "done" else 1


__all__ = ["MAX_RESPONSE_BYTES", "ServeError", "ServeClient", "submit_main"]

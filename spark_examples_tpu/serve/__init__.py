"""Resident PCA service: executor slices, compile-once, admission-controlled.

The reference's ``VariantsPcaDriver`` is a batch job — every invocation
pays full process startup plus seconds of XLA compile before touching
data. This package keeps ONE process alive instead: a daemon that owns
the devices in independent executor slices (``serve/daemon.py`` over
``parallel/mesh.py:plan_executor_slices`` — small jobs run concurrently
beside one large job, each slice on its own sub-mesh), validates every
request device-free at admission time with the ``graftcheck plan``
validator against the TARGET slice's device count (rejections become
structured 4xx bodies carrying the plan facts), coalesces
fingerprint-compatible small jobs into bounded dispatch groups
(continuous batching, ``serve/queue.py``), journals every acknowledged
admission so accepted jobs survive a daemon kill (``serve/journal.py``),
keeps its warm compile state (XLA persistent cache + geometry ledger)
under the run dir across restarts, and exposes job
submission/status/cancel, Prometheus metrics, and health over a thin
stdlib HTTP API (``serve/http.py``). N replica daemons (``--replica-id``)
can share one run dir for host-level fault tolerance: the journal
doubles as a lease-fenced work-stealing substrate — epoch-fenced leases,
heartbeats, and steal scans move a dead replica's accepted jobs to a
survivor, with requeue-once enforced across replica lives and a
flock run-dir guard refusing unsafe sharing.

Layout:

- ``protocol.py`` — the versioned JSON request/response schema
- ``queue.py``    — bounded two-class admission queue + continuous batching
- ``journal.py``  — append-only job journal (restart replay)
- ``executor.py`` — per-job execution over ``pipeline.pca_driver.run_pipeline``
- ``daemon.py``   — the service: slices, workers, job table, metrics
- ``http.py``     — stdlib HTTP front-end + the ``serve`` CLI verb
- ``client.py``   — stdlib HTTP client + the ``submit`` CLI verb
"""

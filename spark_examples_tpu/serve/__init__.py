"""Resident PCA service: warm mesh, compile-once, admission-controlled.

The reference's ``VariantsPcaDriver`` is a batch job — every invocation
pays full process startup plus seconds of XLA compile before touching
data. This package keeps ONE process alive instead: a daemon that owns
the device mesh and the warm compile caches (``serve/daemon.py``),
validates every request device-free at admission time with the
``graftcheck plan`` validator (rejections become structured 4xx bodies
carrying the plan facts), runs admitted jobs serially through a bounded
two-class queue (small-region queries batch ahead of whole-genome jobs,
``serve/queue.py``), and exposes job submission/status/cancel, Prometheus
metrics, and health over a thin stdlib HTTP API (``serve/http.py``).

Layout:

- ``protocol.py`` — the versioned JSON request/response schema
- ``queue.py``    — bounded two-class admission queue + job records
- ``executor.py`` — per-job execution over ``pipeline.pca_driver.run_pipeline``
- ``daemon.py``   — the service: mesh, worker thread, job table, metrics
- ``http.py``     — stdlib HTTP front-end + the ``serve`` CLI verb
- ``client.py``   — stdlib HTTP client + the ``submit`` CLI verb
"""

"""Per-job execution: one admitted job through the reusable pipeline core.

``pipeline.pca_driver.run_pipeline`` is the library entry point the
batch CLI and this executor share — a served job executes the IDENTICAL
pipeline a batch invocation would, and produces the identical schema-v2
run manifest. The ``grm`` kind dispatches the same way to the analysis
core (``analyses/grm.py:run_grm_pipeline``), returning the kinship
summary with the per-job manifest carrying the ``analysis`` block. The
executor's additions are service concerns only:

- **per-job manifest placement**: every job's manifest is written to
  ``<run_dir>/jobs/<job_id>/manifest.json`` (atomic rename, validated
  after the run), so batch and served runs produce the same artifact and
  a scheduler can collect per-request provenance;
- **warm-vs-cold attribution**: the job's geometry fingerprint is checked
  against the process-wide warm-geometry ledger (``utils/cache.py``)
  BEFORE the run, so the job record says whether it rode the resident
  daemon's warm compile caches — the compile-once promise, observable
  per job;
- **stdout capture**: the pipeline prints its result rows and epilogue;
  a resident daemon must not interleave job output on its own stdout, so
  each job's prints land in ``jobs/<job_id>/stdout.log``. The capture is
  THREAD-ROUTED (:class:`_ThreadStdoutRouter`), not a process-global
  ``redirect_stdout``: only the worker thread's writes divert to the job
  log, so HTTP threads (and an embedding test harness) keep their own
  stdout while a job is mid-flight.
"""

from __future__ import annotations

import contextlib
import io
import os
import sys
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from spark_examples_tpu.serve.queue import Job


class _ThreadStdoutRouter(io.TextIOBase):
    """``sys.stdout`` stand-in for the job window: writes from the worker
    thread land in the job's log, every other thread passes through to
    the previous stdout untouched."""

    def __init__(self, fallback, thread_id: int, sink):
        self._fallback = fallback
        self._thread_id = thread_id
        self._sink = sink

    def _target(self):
        return (
            self._sink
            if threading.get_ident() == self._thread_id
            else self._fallback
        )

    def writable(self) -> bool:
        return True

    def write(self, text: str) -> int:
        return self._target().write(text)

    def flush(self) -> None:
        self._target().flush()


class _SwitchableSink(io.TextIOBase):
    """The fused group's per-phase stdout target: one worker thread runs
    K jobs' phases interleaved, so thread routing alone cannot separate
    their output — this sink stacks the CURRENT target, and the fused
    runner's per-job phases push each job's log for their duration. The
    default (bottom-of-stack) target catches group-phase output that
    belongs to no single job."""

    def __init__(self, default):
        self._stack = [default]

    def writable(self) -> bool:
        return True

    def write(self, text: str) -> int:
        return self._stack[-1].write(text)

    def flush(self) -> None:
        self._stack[-1].flush()

    @contextlib.contextmanager
    def routed(self, sink):
        self._stack.append(sink)
        try:
            yield
        finally:
            self._stack.pop()


@dataclass
class ExecutionOutcome:
    """What one completed job hands back to the daemon's job table."""

    result: Dict
    manifest_path: Optional[str]
    compile_cache: str  # "warm" | "cold"
    #: The run manifest's prover-conformance block (measured-vs-proven
    #: per prover; ``obs/metrics.py:conformance_block``) — the daemon
    #: mirrors it into the service registry so ``GET /metrics`` exports
    #: the fleet's latest pair per prover.
    conformance: Optional[Dict] = None


def job_directory(run_dir: str, job_id: str) -> str:
    return os.path.join(run_dir, "jobs", job_id)


def execute_job(job: Job, run_dir: str) -> ExecutionOutcome:
    """Run one admitted job to completion (the daemon's single worker
    thread calls this serially — jobs never share the devices)."""
    from spark_examples_tpu.obs.manifest import validate_manifest
    from spark_examples_tpu.pipeline.pca_driver import run_pipeline
    from spark_examples_tpu.utils.cache import (
        compile_fingerprint,
        geometry_seen,
    )

    job_dir = job_directory(run_dir, job.id)
    os.makedirs(job_dir, exist_ok=True)
    conf = job.conf
    # The service owns manifest placement (admission rejects an explicit
    # --metrics-json): one canonical per-job path, same schema as batch.
    conf.metrics_json = os.path.join(job_dir, "manifest.json")
    warm = geometry_seen(compile_fingerprint(conf, kind=job.request.kind))

    # The claiming slice's devices (serve/daemon.py sets them just before
    # execution): the run resolves its mesh over this subset only, so
    # concurrent slices never contend for devices. None = all devices
    # (embedders and the single-slice topology).
    devices = getattr(job, "slice_devices", None)
    similarity_only = job.request.kind == "similarity"
    with open(
        os.path.join(job_dir, "stdout.log"), "w", encoding="utf-8"
    ) as captured:
        previous = sys.stdout
        sys.stdout = _ThreadStdoutRouter(
            previous, threading.get_ident(), captured
        )
        try:
            if job.request.kind == "grm":
                # The analyses dispatch: the IDENTICAL analysis core the
                # batch `grm` verb runs (its finish_analysis_run writes
                # the same schema-v2 manifest to the per-job path and
                # records the kind-keyed warm-ledger geometry).
                from spark_examples_tpu.analyses.grm import run_grm_pipeline

                grm = run_grm_pipeline(conf, devices=devices)
                result: Dict = {"grm": grm.summary}
                manifest_doc = grm.manifest
                manifest_path = grm.manifest_path
            else:
                pipeline = run_pipeline(
                    conf, similarity_only=similarity_only, devices=devices
                )
                if similarity_only:
                    result = {"similarity": pipeline.similarity_summary}
                else:
                    result = {"pc_lines": pipeline.lines}
                manifest_doc = pipeline.manifest
                manifest_path = pipeline.manifest_path
        finally:
            sys.stdout = previous

    if manifest_path is None:
        raise RuntimeError(
            f"job {job.id} completed but its manifest was not written "
            f"(expected {conf.metrics_json})"
        )
    errors = validate_manifest(manifest_doc)
    if errors:
        raise RuntimeError(
            f"job {job.id} produced an invalid run manifest: "
            + "; ".join(errors)
        )

    return ExecutionOutcome(
        result=result,
        manifest_path=manifest_path,
        compile_cache="warm" if warm else "cold",
        conformance=(
            manifest_doc.get("conformance")
            if isinstance(manifest_doc, dict)
            else None
        ),
    )


def execute_fused_batch(
    jobs: Sequence[Job], run_dir: str
) -> List[ExecutionOutcome]:
    """Run a batch group as ONE stacked device program
    (``pipeline/fused.py``), one outcome per job in group order.

    Raises ``FusedIneligible`` BEFORE any side effect (no job directory,
    no log, no device work) when the group cannot ride the stacked
    program — the daemon catches it and falls back to the serial
    per-job loop, which is always valid. Any exception past preflight
    fails the whole group, exactly as a serial executor exception fails
    its one job."""
    from spark_examples_tpu.obs.manifest import validate_manifest
    from spark_examples_tpu.pipeline.fused import (
        preflight_fused,
        run_fused_pipeline,
    )
    from spark_examples_tpu.utils.cache import (
        batch_compile_fingerprint,
        compile_fingerprint,
        fused_group_fingerprint,
        geometry_seen,
    )

    kinds = [job.request.kind for job in jobs]
    confs = [job.conf for job in jobs]
    preflight_fused(confs, kinds)

    warm: List[bool] = []
    files: List = []
    group_warm = geometry_seen(
        fused_group_fingerprint(
            batch_compile_fingerprint(confs[0], kind=kinds[0]), len(jobs)
        )
    )
    with contextlib.ExitStack() as stack:
        for job in jobs:
            job_dir = job_directory(run_dir, job.id)
            os.makedirs(job_dir, exist_ok=True)
            job.conf.metrics_json = os.path.join(job_dir, "manifest.json")
            # Warm-vs-cold per member: the member geometry AND the
            # group's stacked geometry must both be warm — a known job
            # shape still compiles cold stacked kernels the first time
            # its group size appears.
            warm.append(
                group_warm
                and geometry_seen(
                    compile_fingerprint(job.conf, kind=job.request.kind)
                )
            )
            files.append(
                stack.enter_context(
                    open(
                        os.path.join(job_dir, "stdout.log"),
                        "w",
                        encoding="utf-8",
                    )
                )
            )
        previous = sys.stdout
        # Group-phase prints (nothing per-job by the runner's contract)
        # land in the FIRST member's log rather than the daemon's stdout.
        switch = _SwitchableSink(files[0])
        sys.stdout = _ThreadStdoutRouter(
            previous, threading.get_ident(), switch
        )
        try:
            pipelines = run_fused_pipeline(
                confs,
                kinds,
                devices=getattr(jobs[0], "slice_devices", None),
                stdout_factory=lambda j: switch.routed(files[j]),
            )
        finally:
            sys.stdout = previous

    outcomes: List[ExecutionOutcome] = []
    for job, pipeline, was_warm in zip(jobs, pipelines, warm):
        if pipeline.manifest_path is None:
            raise RuntimeError(
                f"fused job {job.id} completed but its manifest was not "
                f"written (expected {job.conf.metrics_json})"
            )
        errors = validate_manifest(pipeline.manifest)
        if errors:
            raise RuntimeError(
                f"fused job {job.id} produced an invalid run manifest: "
                + "; ".join(errors)
            )
        result: Dict = (
            {"similarity": pipeline.similarity_summary}
            if job.request.kind == "similarity"
            else {"pc_lines": pipeline.lines}
        )
        outcomes.append(
            ExecutionOutcome(
                result=result,
                manifest_path=pipeline.manifest_path,
                compile_cache="warm" if was_warm else "cold",
                conformance=(
                    pipeline.manifest.get("conformance")
                    if isinstance(pipeline.manifest, dict)
                    else None
                ),
            )
        )
    return outcomes


__all__ = [
    "ExecutionOutcome",
    "execute_fused_batch",
    "execute_job",
    "job_directory",
]

"""Per-job execution: one admitted job through the reusable pipeline core.

``pipeline.pca_driver.run_pipeline`` is the library entry point the
batch CLI and this executor share — a served job executes the IDENTICAL
pipeline a batch invocation would, and produces the identical schema-v2
run manifest. The ``grm`` kind dispatches the same way to the analysis
core (``analyses/grm.py:run_grm_pipeline``), returning the kinship
summary with the per-job manifest carrying the ``analysis`` block. The
executor's additions are service concerns only:

- **per-job manifest placement**: every job's manifest is written to
  ``<run_dir>/jobs/<job_id>/manifest.json`` (atomic rename, validated
  after the run), so batch and served runs produce the same artifact and
  a scheduler can collect per-request provenance;
- **warm-vs-cold attribution**: the job's geometry fingerprint is checked
  against the process-wide warm-geometry ledger (``utils/cache.py``)
  BEFORE the run, so the job record says whether it rode the resident
  daemon's warm compile caches — the compile-once promise, observable
  per job;
- **stdout capture**: the pipeline prints its result rows and epilogue;
  a resident daemon must not interleave job output on its own stdout, so
  each job's prints land in ``jobs/<job_id>/stdout.log``. The capture is
  THREAD-ROUTED (:class:`_ThreadStdoutRouter`), not a process-global
  ``redirect_stdout``: only the worker thread's writes divert to the job
  log, so HTTP threads (and an embedding test harness) keep their own
  stdout while a job is mid-flight.
"""

from __future__ import annotations

import io
import os
import sys
import threading
from dataclasses import dataclass
from typing import Dict, Optional

from spark_examples_tpu.serve.queue import Job


class _ThreadStdoutRouter(io.TextIOBase):
    """``sys.stdout`` stand-in for the job window: writes from the worker
    thread land in the job's log, every other thread passes through to
    the previous stdout untouched."""

    def __init__(self, fallback, thread_id: int, sink):
        self._fallback = fallback
        self._thread_id = thread_id
        self._sink = sink

    def _target(self):
        return (
            self._sink
            if threading.get_ident() == self._thread_id
            else self._fallback
        )

    def writable(self) -> bool:
        return True

    def write(self, text: str) -> int:
        return self._target().write(text)

    def flush(self) -> None:
        self._target().flush()


@dataclass
class ExecutionOutcome:
    """What one completed job hands back to the daemon's job table."""

    result: Dict
    manifest_path: Optional[str]
    compile_cache: str  # "warm" | "cold"
    #: The run manifest's prover-conformance block (measured-vs-proven
    #: per prover; ``obs/metrics.py:conformance_block``) — the daemon
    #: mirrors it into the service registry so ``GET /metrics`` exports
    #: the fleet's latest pair per prover.
    conformance: Optional[Dict] = None


def job_directory(run_dir: str, job_id: str) -> str:
    return os.path.join(run_dir, "jobs", job_id)


def execute_job(job: Job, run_dir: str) -> ExecutionOutcome:
    """Run one admitted job to completion (the daemon's single worker
    thread calls this serially — jobs never share the devices)."""
    from spark_examples_tpu.obs.manifest import validate_manifest
    from spark_examples_tpu.pipeline.pca_driver import run_pipeline
    from spark_examples_tpu.utils.cache import (
        compile_fingerprint,
        geometry_seen,
    )

    job_dir = job_directory(run_dir, job.id)
    os.makedirs(job_dir, exist_ok=True)
    conf = job.conf
    # The service owns manifest placement (admission rejects an explicit
    # --metrics-json): one canonical per-job path, same schema as batch.
    conf.metrics_json = os.path.join(job_dir, "manifest.json")
    warm = geometry_seen(compile_fingerprint(conf, kind=job.request.kind))

    # The claiming slice's devices (serve/daemon.py sets them just before
    # execution): the run resolves its mesh over this subset only, so
    # concurrent slices never contend for devices. None = all devices
    # (embedders and the single-slice topology).
    devices = getattr(job, "slice_devices", None)
    similarity_only = job.request.kind == "similarity"
    with open(
        os.path.join(job_dir, "stdout.log"), "w", encoding="utf-8"
    ) as captured:
        previous = sys.stdout
        sys.stdout = _ThreadStdoutRouter(
            previous, threading.get_ident(), captured
        )
        try:
            if job.request.kind == "grm":
                # The analyses dispatch: the IDENTICAL analysis core the
                # batch `grm` verb runs (its finish_analysis_run writes
                # the same schema-v2 manifest to the per-job path and
                # records the kind-keyed warm-ledger geometry).
                from spark_examples_tpu.analyses.grm import run_grm_pipeline

                grm = run_grm_pipeline(conf, devices=devices)
                result: Dict = {"grm": grm.summary}
                manifest_doc = grm.manifest
                manifest_path = grm.manifest_path
            else:
                pipeline = run_pipeline(
                    conf, similarity_only=similarity_only, devices=devices
                )
                if similarity_only:
                    result = {"similarity": pipeline.similarity_summary}
                else:
                    result = {"pc_lines": pipeline.lines}
                manifest_doc = pipeline.manifest
                manifest_path = pipeline.manifest_path
        finally:
            sys.stdout = previous

    if manifest_path is None:
        raise RuntimeError(
            f"job {job.id} completed but its manifest was not written "
            f"(expected {conf.metrics_json})"
        )
    errors = validate_manifest(manifest_doc)
    if errors:
        raise RuntimeError(
            f"job {job.id} produced an invalid run manifest: "
            + "; ".join(errors)
        )

    return ExecutionOutcome(
        result=result,
        manifest_path=manifest_path,
        compile_cache="warm" if warm else "cold",
        conformance=(
            manifest_doc.get("conformance")
            if isinstance(manifest_doc, dict)
            else None
        ),
    )


__all__ = ["ExecutionOutcome", "execute_job", "job_directory"]

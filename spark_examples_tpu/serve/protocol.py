"""Versioned JSON wire schema of the resident PCA service.

One request shape, one response envelope, one error envelope — all
carrying ``{"protocol": {"id": ..., "version": ...}}`` so clients and
servers from different trees fail loudly instead of half-parsing each
other. Analysis requests are expressed as the EXISTING flag namespace
(``config.build_pca_parser``'s argv form): the service adds no second
configuration grammar, and anything expressible as a batch CLI invocation
is expressible as a served job.

Request document (``POST /v1/jobs``)::

    {
      "protocol": {"id": "spark-examples-tpu/serve", "version": 1},
      "kind": "pca" | "similarity",
      "flags": ["--num-samples", "64", "--references", "17:0:20000"],
      "deadline_seconds": 30.0,      # optional: fail unstarted past this
      "tag": "nightly-brca1"         # optional client label
    }

``kind`` selects the result surface: ``pca`` returns the emitted PC rows,
``similarity`` stops after the ingest+similarity stage and returns a
host-side summary of the Gramian (shape, nonzero rows, trace). Both ride
the identical pipeline (``pipeline.pca_driver.run_pipeline``). ``grm``
runs the GRM/kinship analysis (``analyses/grm.py:run_grm_pipeline`` —
the identical analysis the batch ``grm`` verb runs) and returns the
kinship summary (shape, sites, trace, diagonal/off-diagonal means; the
N×N matrix itself never rides a response). The other per-site analyses
(``ld``, ``assoc``) are RESERVED kinds: recognized, rejected with
``reserved-kind`` — batch-only until their M-sized output spill gets a
served placement story — so a future server that serves them is a
protocol version bump, not a silent behavior change.

Versioning contract: a request whose ``protocol.version`` differs from
:data:`PROTOCOL_VERSION` is rejected with ``unsupported-protocol-version``
(HTTP 400) — never best-effort parsed. Unknown top-level fields are
rejected too (``unknown-field``): silently ignoring them would let a
future client believe a new knob was honored.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Mapping, Optional, Sequence, Tuple

PROTOCOL_ID = "spark-examples-tpu/serve"
PROTOCOL_VERSION = 1

#: Request kinds and the result surface each returns. ``grm`` joined when
#: the analyses subsystem landed (its flags parse through the REAL
#: ``config.build_grm_parser``, its admission plan runs with
#: ``analysis="grm"``, and its warm-ledger fingerprint is kind-keyed so a
#: GRM run never pre-warms the PCA geometry).
JOB_KINDS = ("pca", "similarity", "grm")

#: Analysis kinds that exist as batch CLI verbs but are NOT served yet:
#: their outputs are per-site (M-sized) files, and a served job has no
#: client-visible placement for an O(M) artifact until the result-surface
#: story lands. Requests naming them get ``reserved-kind`` (HTTP 400) —
#: a deliberate, tested rejection distinct from an unknown kind.
RESERVED_KINDS = ("ld", "assoc")

#: Terminal job states (``GET /v1/jobs/<id>`` polling stops here).
TERMINAL_STATUSES = ("done", "failed", "cancelled")

_REQUEST_FIELDS = frozenset(
    {"protocol", "kind", "flags", "deadline_seconds", "tag"}
)


class ProtocolError(ValueError):
    """A request document that violates the wire schema; ``code`` is the
    machine-readable error code the HTTP layer returns in the 400 body."""

    def __init__(self, code: str, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


@dataclass(frozen=True)
class JobRequest:
    """One validated analysis request."""

    kind: str
    flags: Tuple[str, ...]
    deadline_seconds: Optional[float] = None
    tag: Optional[str] = None


def protocol_block() -> Dict:
    return {"id": PROTOCOL_ID, "version": PROTOCOL_VERSION}


def request_doc(
    flags: Sequence[str],
    kind: str = "pca",
    deadline_seconds: Optional[float] = None,
    tag: Optional[str] = None,
) -> Dict:
    """The wire form of one request (what ``serve/client.py`` posts)."""
    doc: Dict = {
        "protocol": protocol_block(),
        "kind": kind,
        "flags": list(flags),
    }
    if deadline_seconds is not None:
        doc["deadline_seconds"] = float(deadline_seconds)
    if tag is not None:
        doc["tag"] = str(tag)
    return doc


def parse_request(doc) -> JobRequest:
    """Validate one request document; raises :class:`ProtocolError` with a
    machine-readable code on every schema violation."""
    if not isinstance(doc, Mapping):
        raise ProtocolError("bad-request", "request body is not a JSON object")
    unknown = set(doc) - _REQUEST_FIELDS
    if unknown:
        raise ProtocolError(
            "unknown-field",
            f"unknown request field(s) {sorted(unknown)}; this server "
            f"speaks {PROTOCOL_ID} v{PROTOCOL_VERSION}",
        )
    protocol = doc.get("protocol")
    if not isinstance(protocol, Mapping):
        raise ProtocolError(
            "protocol-missing",
            "request carries no 'protocol' object; expected "
            f"{{'id': {PROTOCOL_ID!r}, 'version': {PROTOCOL_VERSION}}}",
        )
    if protocol.get("id") != PROTOCOL_ID:
        raise ProtocolError(
            "protocol-id",
            f"protocol.id {protocol.get('id')!r} != {PROTOCOL_ID!r}",
        )
    if protocol.get("version") != PROTOCOL_VERSION:
        raise ProtocolError(
            "unsupported-protocol-version",
            f"protocol.version {protocol.get('version')!r} is not supported "
            f"(this server speaks version {PROTOCOL_VERSION})",
        )
    kind = doc.get("kind")
    if kind in RESERVED_KINDS:
        raise ProtocolError(
            "reserved-kind",
            f"kind {kind!r} is a batch-only analysis for now (run the "
            f"CLI verb); served kinds are {list(JOB_KINDS)}",
        )
    if kind not in JOB_KINDS:
        raise ProtocolError(
            "unknown-kind",
            f"kind {kind!r} is not one of {list(JOB_KINDS)}",
        )
    flags = doc.get("flags")
    if not isinstance(flags, (list, tuple)) or not all(
        isinstance(f, str) for f in flags
    ):
        raise ProtocolError(
            "bad-flags",
            "'flags' must be a list of strings (the PCA CLI argv form)",
        )
    deadline = doc.get("deadline_seconds")
    if deadline is not None:
        if (
            isinstance(deadline, bool)
            or not isinstance(deadline, (int, float))
            or deadline <= 0
        ):
            raise ProtocolError(
                "bad-deadline",
                f"'deadline_seconds' must be a positive number, got "
                f"{deadline!r}",
            )
        deadline = float(deadline)
    tag = doc.get("tag")
    if tag is not None and not isinstance(tag, str):
        raise ProtocolError("bad-tag", f"'tag' must be a string, got {tag!r}")
    return JobRequest(
        kind=kind,
        flags=tuple(flags),
        deadline_seconds=deadline,
        tag=tag,
    )


def error_doc(
    code: str,
    message: str,
    plan: Optional[Mapping] = None,
    retry_after_seconds: Optional[float] = None,
) -> Dict:
    """The error envelope every non-2xx response carries. ``plan`` is the
    admission validator's structured report (issues + geometry facts) on
    plan rejections, so a 4xx tells the client exactly which contract its
    configuration broke — not just that it broke one."""
    doc: Dict = {
        "protocol": protocol_block(),
        "error": {"code": code, "message": message},
    }
    if plan is not None:
        doc["plan"] = dict(plan)
    if retry_after_seconds is not None:
        doc["error"]["retry_after_seconds"] = float(retry_after_seconds)
    return doc


def job_doc(
    job_id: str,
    kind: str,
    job_class: str,
    status: str,
    submitted_unix: float,
    tag: Optional[str] = None,
    started_unix: Optional[float] = None,
    finished_unix: Optional[float] = None,
    seconds: Optional[float] = None,
    error: Optional[str] = None,
    result: Optional[Mapping] = None,
    manifest_path: Optional[str] = None,
    compile_cache: Optional[str] = None,
    plan_geometry: Optional[Mapping] = None,
    slice_name: Optional[str] = None,
    batch_size: Optional[int] = None,
    fused_size: Optional[int] = None,
    trace: Optional[str] = None,
    cost: Optional[Mapping] = None,
) -> Dict:
    """The job envelope (submit response and ``GET /v1/jobs/<id>``).
    ``slice``/``batch_size`` are execution attribution (which executor
    slice ran the job, how many jobs rode its dispatch group);
    ``fused_size`` (additive) is the stacked-program group size when the
    job rode fused batch execution — 1 means a serial dispatch, even
    inside a multi-job batch group;
    ``trace`` echoes the job's distributed-tracing id (the client-sent
    ``X-Trace-Id`` when one rode the submit, a server-minted id
    otherwise); ``cost`` is the admission-time cost prediction
    (``obs/costmodel.py:CostPrediction.to_dict``, with measured fields
    merged once the job completes) — additive response fields;
    request-side strictness is unchanged."""
    return {
        "protocol": protocol_block(),
        "job": {
            "id": job_id,
            "trace": trace,
            "kind": kind,
            "class": job_class,
            "status": status,
            "tag": tag,
            "submitted_unix": submitted_unix,
            "started_unix": started_unix,
            "finished_unix": finished_unix,
            "seconds": seconds,
            "error": error,
            "result": dict(result) if result is not None else None,
            "manifest_path": manifest_path,
            "compile_cache": compile_cache,
            "plan_geometry": (
                dict(plan_geometry) if plan_geometry is not None else None
            ),
            "slice": slice_name,
            "batch_size": batch_size,
            "fused_size": fused_size,
            "cost": dict(cost) if cost is not None else None,
        },
    }


__all__ = [
    "PROTOCOL_ID",
    "PROTOCOL_VERSION",
    "JOB_KINDS",
    "RESERVED_KINDS",
    "TERMINAL_STATUSES",
    "ProtocolError",
    "JobRequest",
    "protocol_block",
    "request_doc",
    "parse_request",
    "error_doc",
    "job_doc",
]

"""The resident PCA service: warm process, admission control, one worker.

:class:`PcaService` is the daemon's brain, HTTP-free (``serve/http.py``
is a thin dispatch onto it, so every behavior is testable in-process):

- **owns the devices**: the backend is initialized ONCE at
  :meth:`start` (the process-startup cost every batch invocation pays),
  and a single worker thread executes admitted jobs serially against
  them — jobs never contend for HBM or compile caches, and the
  in-process jit caches stay warm across jobs
  (``utils/cache.py``'s warm-geometry ledger makes that observable);
- **admits device-free**: every request is validated by the
  ``graftcheck plan`` validator (``check/plan.py``) BEFORE it may queue —
  flag-grammar errors, geometry contradictions, HBM/host-memory/exactness
  violations are structured 4xx bodies carrying the plan facts, and the
  devices never see a doomed configuration;
- **schedules two classes**: the bounded admission queue
  (``serve/queue.py``) drains small-region queries between whole-genome
  jobs, with per-job deadlines, queued-job cancellation, and 429
  backpressure past capacity;
- **drains gracefully**: :meth:`begin_drain` stops admission (503),
  lets the worker finish every admitted job, then the worker exits —
  the SIGTERM path of the ``serve`` CLI verb.

Telemetry rides the existing ``obs/`` stack: one service-level
:class:`~spark_examples_tpu.obs.metrics.MetricsRegistry` (scraped at
``GET /metrics``), per-request spans in a
:class:`~spark_examples_tpu.obs.spans.SpanRecorder`, and the standard
:class:`~spark_examples_tpu.obs.heartbeat.Heartbeat` emitting service
liveness (queue depth, in-flight, warm/cold compile counts) to stderr.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, Optional, Tuple

from spark_examples_tpu.serve.executor import ExecutionOutcome, execute_job
from spark_examples_tpu.serve.protocol import (
    ProtocolError,
    error_doc,
    job_doc,
    parse_request,
)
from spark_examples_tpu.serve.queue import (
    DEFAULT_LARGE_CAPACITY,
    DEFAULT_SMALL_CAPACITY,
    BoundedJobQueue,
    Job,
    QueueClosed,
    QueueFull,
    classify_conf,
)
from spark_examples_tpu.utils import faults

#: How often the watchdog checks the worker thread's pulse. A dead worker
#: is replaced within ~this bound, so one crashed job never looks like a
#: wedged daemon to pollers.
WATCHDOG_INTERVAL_SECONDS = 0.05

#: Plan-rejection codes that are RESOURCE bounds (the request is
#: well-formed but too big for the declared budgets) — surfaced as HTTP
#: 413 rather than 400, so clients can distinguish "fix the flags" from
#: "shrink the request or find a bigger service".
MEM_LIMIT_CODES = frozenset(
    {
        "host-mem-over-budget",
        "host-mem-unprovable",
        "dense-exceeds-hbm",
        "sharded-exceeds-hbm",
    }
)

#: Terminal jobs kept queryable after completion (per-job manifests stay
#: on disk forever; only the in-memory record — result payload included —
#: is bounded). Without a cap the job table of a long-lived daemon grows
#: monotonically: the control plane must obey the same bounded-memory
#: discipline ``graftcheck hostmem`` enforces on ingest.
DEFAULT_TERMINAL_RETENTION = 256

#: Flags a served job may not carry: multi-controller topology belongs to
#: the daemon's own launch, and every daemon-host write path belongs to
#: the service (one canonical per-job directory; see ``serve/executor.py``)
#: — a client-chosen ``--output-path``/``--profile-dir``/``--save-variants``
#: would be an arbitrary-path write primitive on the service host.
_RESERVED_FLAG_FIELDS = (
    ("coordinator_address", "--coordinator-address"),
    ("num_processes", "--num-processes"),
    ("process_id", "--process-id"),
    ("metrics_json", "--metrics-json"),
    ("output_path", "--output-path"),
    ("profile_dir", "--profile-dir"),
    ("save_variants", "--save-variants"),
    # Daemon-host write paths AND process-wide kill switches: a served
    # job carrying a fault plan could SIGKILL the daemon (kill@... fires
    # os.kill on the whole process), and checkpoint/resume directories
    # are arbitrary-path read/write primitives on the service host.
    ("fault_plan", "--fault-plan"),
    ("gramian_checkpoint_dir", "--gramian-checkpoint-dir"),
    ("resume_from", "--resume-from"),
    # The analyses' per-site output paths are daemon-host write primitives
    # too; a served grm job returns the kinship SUMMARY, never a
    # client-placed matrix file.
    ("grm_out", "--grm-out"),
)


def _parse_job_flags(flags, kind: str = "pca"):
    """Parse a request's flag list through the REAL parser of the job's
    kind (``check/plan.py:ANALYSIS_SURFACES`` — never a drifted copy;
    ``pca``/``similarity`` share the PCA surface, ``grm`` parses the grm
    verb's); argparse errors raise ``ValueError``."""
    from spark_examples_tpu.check.plan import ANALYSIS_SURFACES, _RaisingParser

    build_parser, conf_cls = ANALYSIS_SURFACES[
        kind if kind in ANALYSIS_SURFACES else "pca"
    ]
    parser = build_parser(_RaisingParser(prog="serve-job", add_help=False))
    ns = parser.parse_args(list(flags))
    return conf_cls._from_namespace(ns)


class PcaService:
    """The resident service; see the module docstring for the contract."""

    def __init__(
        self,
        run_dir: Optional[str] = None,
        small_capacity: int = DEFAULT_SMALL_CAPACITY,
        large_capacity: int = DEFAULT_LARGE_CAPACITY,
        host_mem_budget: Optional[int] = None,
        heartbeat_seconds: float = 0.0,
        executor: Optional[Callable[[Job, str], ExecutionOutcome]] = None,
        terminal_retention: int = DEFAULT_TERMINAL_RETENTION,
    ):
        if terminal_retention < 1:
            raise ValueError(
                f"terminal_retention must be >= 1, got {terminal_retention}"
            )
        self.run_dir = run_dir or tempfile.mkdtemp(prefix="spark-serve-")
        self.host_mem_budget = host_mem_budget
        self.heartbeat_seconds = float(heartbeat_seconds)
        self.terminal_retention = int(terminal_retention)
        self._executor = executor if executor is not None else execute_job
        self._queue = BoundedJobQueue(small_capacity, large_capacity)
        # lock order: service table lock before nothing — it is a leaf
        # (job-state flips and table reads only; the queue's own leaf lock
        # is never taken while holding it: admission puts happen outside).
        self._lock = threading.Lock()
        self._table: Dict[str, Job] = {}
        self._terminal_order: Deque[str] = deque()
        self._seq = 0
        self._inflight = 0
        self._terminal = 0
        self._draining = threading.Event()
        self._worker: Optional[threading.Thread] = None
        self._watchdog: Optional[threading.Thread] = None
        self._heartbeat = None
        self._started_unix: Optional[float] = None
        self.device_count: Optional[int] = None
        self.platform: Optional[str] = None

        from spark_examples_tpu.obs import MetricsRegistry, SpanRecorder

        self.registry = MetricsRegistry()
        self.spans = SpanRecorder()
        self._register_metrics()

    # ------------------------------------------------------------ telemetry

    def _register_metrics(self) -> None:
        from spark_examples_tpu.obs.metrics import (
            COMPILE_CACHE_GEOMETRY_HITS,
            COMPILE_CACHE_GEOMETRY_MISSES,
            HOST_PEAK_RSS_BYTES,
            SERVE_JOBS_DONE,
            SERVE_JOBS_INFLIGHT,
            SERVE_QUEUE_DEPTH,
            SERVE_WORKER_RESTARTS,
            read_host_peak_rss_bytes,
            well_known_counter,
            well_known_gauge,
        )
        from spark_examples_tpu.utils.cache import compile_cache_stats

        well_known_gauge(self.registry, SERVE_QUEUE_DEPTH).set_function(
            lambda: float(self._queue.total_depth())
        )
        well_known_gauge(self.registry, SERVE_JOBS_INFLIGHT).set_function(
            lambda: float(self._inflight)
        )
        well_known_gauge(self.registry, SERVE_JOBS_DONE).set_function(
            lambda: float(self._terminal)
        )
        well_known_gauge(
            self.registry, COMPILE_CACHE_GEOMETRY_HITS
        ).set_function(lambda: float(compile_cache_stats()[0]))
        well_known_gauge(
            self.registry, COMPILE_CACHE_GEOMETRY_MISSES
        ).set_function(lambda: float(compile_cache_stats()[1]))
        if read_host_peak_rss_bytes() is not None:
            well_known_gauge(self.registry, HOST_PEAK_RSS_BYTES).set_function(
                lambda: float(read_host_peak_rss_bytes() or 0)
            )
        self._submitted = self.registry.counter(
            "serve_jobs_submitted_total",
            "Jobs admitted to the queue, by admission class.",
            labelnames=("job_class",),
        )
        self._rejected = self.registry.counter(
            "serve_jobs_rejected_total",
            "Requests rejected at admission, by rejection code.",
            labelnames=("code",),
        )
        self._completed = self.registry.counter(
            "serve_jobs_completed_total",
            "Jobs that reached a terminal state, by status.",
            labelnames=("status",),
        )
        self._job_seconds = self.registry.histogram(
            "serve_job_seconds",
            "Wall-clock of completed jobs, by admission class.",
            labelnames=("job_class",),
        )
        self._worker_restarts = well_known_counter(
            self.registry, SERVE_WORKER_RESTARTS
        )

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "PcaService":
        """Initialize the backend (the once-per-process cost), start the
        worker and the optional service heartbeat."""
        if self._worker is not None:
            return self
        # Force the lazy env-var fault plan to parse NOW (the batch path
        # does the same in run_pipeline): a typo'd site name must fail the
        # daemon at startup, not surface as a crash/restart loop where
        # every job rides its one requeue and then fails with a
        # misleading "worker-crashed:" error.
        faults.active()
        import jax

        # The warm-mesh moment: devices enumerate here, once; every
        # admitted job reuses this initialized backend (and, for repeated
        # geometries, its live jit caches).
        self.device_count = jax.device_count()
        self.platform = jax.devices()[0].platform
        os.makedirs(self.run_dir, exist_ok=True)
        self._started_unix = time.time()
        self._worker = threading.Thread(
            target=self._worker_loop, name="serve-worker", daemon=True
        )
        self._worker.start()
        # The self-healing half: a watchdog that replaces a dead worker
        # thread instead of letting one crashed job wedge the daemon.
        self._watchdog = threading.Thread(
            target=self._watchdog_loop, name="serve-watchdog", daemon=True
        )
        self._watchdog.start()
        if self.heartbeat_seconds > 0:
            from spark_examples_tpu.obs.heartbeat import Heartbeat

            self._heartbeat = Heartbeat(
                self.heartbeat_seconds, self.registry
            ).start()
        return self

    def begin_drain(self) -> None:
        """Stop admission (new submissions get 503); already-admitted jobs
        still run to completion."""
        self._draining.set()
        self._queue.close()

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def wait_drained(self, timeout: Optional[float] = None) -> bool:
        """Block until the worker finished every admitted job and exited
        (call :meth:`begin_drain` first). Returns ``False`` on timeout.
        Re-reads ``self._worker`` per step: the watchdog may replace a
        crashed worker mid-drain, and the drain only completes when the
        CURRENT worker exits with nothing left in flight."""
        deadline = (
            None if timeout is None else time.monotonic() + float(timeout)
        )
        while True:
            worker = self._worker
            if worker is None:
                break
            step = 0.1
            if deadline is not None:
                step = min(step, max(0.0, deadline - time.monotonic()))
            joinable = True
            try:
                worker.join(timeout=step)
            except RuntimeError:
                # _recover_worker publishes its replacement a beat before
                # start() (publish-first keeps the dead worker from ever
                # reading as "current" here); an unstarted thread is not
                # joinable yet — treat it as alive and poll again.
                joinable = False
                time.sleep(min(step, 0.005))
            with self._lock:
                inflight = self._inflight
                # A crash mid-drain leaves the watchdog a beat of
                # settlement work AFTER it started the replacement: the
                # crashed job may still read ``running`` (or transiently
                # ``queued``) while the new worker already drained the
                # queue. The drain contract is "every admitted job reached
                # a terminal state", so wait for the table to settle too.
                unsettled = any(
                    job.status in ("queued", "running")
                    for job in self._table.values()
                )
            if (
                joinable
                and not worker.is_alive()
                and self._worker is worker
                and self._queue.drained
                and inflight == 0
                and not unsettled
            ):
                break
            if deadline is not None and time.monotonic() >= deadline:
                return False
        if self._heartbeat is not None:
            self._heartbeat.stop()
            self._heartbeat = None
        return True

    def stop(self, timeout: float = 30.0) -> bool:
        """Drain and join (tests and the CLI's shutdown path)."""
        self.begin_drain()
        return self.wait_drained(timeout=timeout)

    # ------------------------------------------------------------ admission

    def submit(self, doc) -> Tuple[int, Dict]:
        """One ``POST /v1/jobs`` body → ``(http_status, response_doc)``."""
        if self.draining:
            self._rejected.labels(code="draining").inc()
            return 503, error_doc(
                "draining",
                "service is draining; submit to another replica",
                retry_after_seconds=30.0,
            )
        try:
            request = parse_request(doc)
        except ProtocolError as e:
            self._rejected.labels(code=e.code).inc()
            return 400, error_doc(e.code, e.message)
        try:
            conf = _parse_job_flags(request.flags, kind=request.kind)
        except ValueError as e:
            self._rejected.labels(code="flag-grammar").inc()
            return 400, error_doc("flag-grammar", str(e))
        for field, flag in _RESERVED_FLAG_FIELDS:
            # `is not None`, not truthiness: --process-id 0 is the
            # canonical coordinator id and must be rejected like any other.
            if getattr(conf, field, None) is not None:
                self._rejected.labels(code="reserved-flag").inc()
                return 400, error_doc(
                    "reserved-flag",
                    f"{flag} is owned by the service and may not ride a "
                    "served job (manifests land at the per-job path; "
                    "multi-controller topology belongs to the daemon "
                    "launch)",
                )

        # Device-free admission validation: the graftcheck plan validator
        # over the daemon's REAL device count and host-memory budget. An
        # exit-2 plan becomes a structured 4xx carrying the plan facts.
        from spark_examples_tpu.check.plan import validate_plan

        report = validate_plan(
            conf,
            plan_devices=self.device_count,
            host_mem_budget=self.host_mem_budget,
            # The grm kind admits through the analysis's own plan entry
            # (the analyses admission gate + Gramian proofs); pca and
            # similarity keep the default PCA surface.
            analysis="grm" if request.kind == "grm" else "pca",
        )
        plan_block = {
            "ok": report.ok,
            "issues": [
                {"code": i.code, "severity": i.severity, "message": i.message}
                for i in report.issues
            ],
            "geometry": report.geometry,
            "shape_checks": report.shape_checks,
        }
        if not report.ok:
            error_codes = [
                i.code for i in report.issues if i.severity == "error"
            ]
            status = (
                413 if any(c in MEM_LIMIT_CODES for c in error_codes) else 400
            )
            self._rejected.labels(code="plan-rejected").inc()
            return status, error_doc(
                "plan-rejected",
                "admission plan validation rejected this configuration: "
                + "; ".join(error_codes),
                plan=plan_block,
            )

        now = time.time()
        with self._lock:
            self._seq += 1
            job_id = f"job-{self._seq:06d}"
        job = Job(
            id=job_id,
            request=request,
            conf=conf,
            job_class=classify_conf(conf),
            submitted_unix=now,
            deadline_unix=(
                now + request.deadline_seconds
                if request.deadline_seconds is not None
                else None
            ),
            plan_geometry=dict(report.geometry),
        )
        with self._lock:
            self._table[job.id] = job
        try:
            self._queue.put(job)
        except QueueFull as e:
            with self._lock:
                del self._table[job.id]
            self._rejected.labels(code="queue-full").inc()
            return 429, error_doc(
                "queue-full", str(e), retry_after_seconds=5.0
            )
        except QueueClosed as e:
            with self._lock:
                del self._table[job.id]
            self._rejected.labels(code="draining").inc()
            return 503, error_doc(
                "draining", str(e), retry_after_seconds=30.0
            )
        self._submitted.labels(job_class=job.job_class).inc()
        return 202, self._job_doc(job)

    # --------------------------------------------------------------- lookup

    def job_status(self, job_id: str) -> Tuple[int, Dict]:
        with self._lock:
            job = self._table.get(job_id)
            if job is None:
                return 404, error_doc(
                    "unknown-job", f"no job {job_id!r} on this service"
                )
            return 200, self._job_doc_locked(job)

    def cancel(self, job_id: str) -> Tuple[int, Dict]:
        """Cancel one still-queued job; running and finished jobs conflict
        (the serial worker cannot abandon a dispatched pipeline without
        poisoning the device state every other job shares)."""
        with self._lock:
            job = self._table.get(job_id)
        if job is None:
            return 404, error_doc(
                "unknown-job", f"no job {job_id!r} on this service"
            )
        removed = self._queue.remove(job_id)
        with self._lock:
            if removed is not None and job.status == "queued":
                job.status = "cancelled"
                job.finished_unix = time.time()
                self._mark_terminal_locked(job)
                doc = self._job_doc_locked(job)
            elif job.status in ("running", "queued"):
                # status 'queued' with removed=None is the pop window:
                # the worker claimed the job but has not flipped it to
                # running yet — it IS about to run, report it as such.
                return 409, error_doc(
                    "job-running",
                    f"job {job_id} is already on the devices; a running "
                    "job cannot be cancelled",
                )
            else:
                return 409, error_doc(
                    "job-finished",
                    f"job {job_id} already reached status {job.status!r}",
                )
        self._completed.labels(status="cancelled").inc()
        return 200, doc

    # ---------------------------------------------------------------- state

    def healthz(self) -> Dict:
        """Mesh/queue liveness (``GET /healthz``)."""
        worker = self._worker
        uptime = (
            time.time() - self._started_unix
            if self._started_unix is not None
            else None
        )
        with self._lock:
            inflight = self._inflight
            terminal = self._terminal
            total = len(self._table)
        return {
            "status": "draining" if self.draining else "ok",
            "mesh": {
                "devices": self.device_count,
                "platform": self.platform,
            },
            "queue": {
                "depth": self._queue.depth(),
                "capacity": {
                    "small": self._queue.small_capacity,
                    "large": self._queue.large_capacity,
                },
                "worker_alive": worker is not None and worker.is_alive(),
                "worker_restarts": int(self._worker_restarts.value),
            },
            "jobs": {
                "tracked": total,
                "inflight": inflight,
                "terminal": terminal,
            },
            "uptime_seconds": uptime,
            "run_dir": self.run_dir,
        }

    def metrics_text(self) -> str:
        """Prometheus text exposition (``GET /metrics``) — the registry's
        existing export, unchanged."""
        return self.registry.prometheus_text()

    def _mark_terminal_locked(self, job: Job) -> None:
        """Lifetime counter + bounded retention: the oldest terminal
        records past ``terminal_retention`` leave the table (their
        manifests stay on disk; a later status query is 404 by design —
        the in-memory control plane must stay O(retention), not O(jobs
        ever served)."""
        self._terminal += 1
        self._terminal_order.append(job.id)
        while len(self._terminal_order) > self.terminal_retention:
            evicted = self._terminal_order.popleft()
            self._table.pop(evicted, None)

    def _job_doc(self, job: Job) -> Dict:
        with self._lock:
            return self._job_doc_locked(job)

    def _job_doc_locked(self, job: Job) -> Dict:
        return job_doc(
            job_id=job.id,
            kind=job.request.kind,
            job_class=job.job_class,
            status=job.status,
            tag=job.request.tag,
            submitted_unix=job.submitted_unix,
            started_unix=job.started_unix,
            finished_unix=job.finished_unix,
            seconds=job.seconds,
            error=job.error,
            result=job.result,
            manifest_path=job.manifest_path,
            compile_cache=job.compile_cache,
            plan_geometry=job.plan_geometry,
        )

    # --------------------------------------------------------------- worker

    def _worker_loop(self) -> None:
        while True:
            job = self._queue.pop(timeout=0.2)
            if job is None:
                if self._queue.drained:
                    return
                continue
            self._run_job(job)

    def _run_job(self, job: Job) -> None:
        now = time.time()
        if job.deadline_unix is not None and now > job.deadline_unix:
            with self._lock:
                job.status = "failed"
                job.error = (
                    f"deadline-exceeded: queued "
                    f"{now - job.submitted_unix:.1f}s, deadline was "
                    f"{job.deadline_unix - job.submitted_unix:.1f}s"
                )
                job.finished_unix = now
                self._mark_terminal_locked(job)
            self._completed.labels(status="failed").inc()
            return
        with self._lock:
            job.status = "running"
            job.started_unix = now
            self._inflight = 1
        # Registered kill-point: job claimed and flipped to running, BEFORE
        # any device work — the requeue-eligible window (a crash here is
        # side-effect-free; the watchdog re-puts the job once).
        faults.kill_point("serve.worker.claim")
        with self._lock:
            job.device_began = True
        # Registered kill-point: device work marked begun, executor about
        # to run — a crash from here on must NOT be requeued (device state
        # under a crashed update cannot be trusted for a silent retry).
        faults.kill_point("serve.worker.mid-job")
        started = time.perf_counter()
        outcome: Optional[ExecutionOutcome] = None
        error: Optional[str] = None
        try:
            with self.spans.span(f"job {job.id} [{job.request.kind}]"):
                outcome = self._executor(job, self.run_dir)
        except Exception as e:  # noqa: BLE001 — the job FAILS, the service lives
            error = f"{type(e).__name__}: {e}"
        seconds = time.perf_counter() - started
        with self._lock:
            job.finished_unix = time.time()
            job.seconds = seconds
            self._inflight = 0
            self._mark_terminal_locked(job)
            if error is not None:
                job.status = "failed"
                job.error = error
            else:
                job.status = "done"
                job.result = outcome.result
                job.manifest_path = outcome.manifest_path
                job.compile_cache = outcome.compile_cache
        self._completed.labels(status=job.status).inc()
        self._job_seconds.labels(job_class=job.job_class).observe(seconds)

    # ------------------------------------------------------------- watchdog

    def _watchdog_loop(self) -> None:
        """Monitor the worker thread's pulse; replace it when it dies.

        The worker loop only returns by contract when the queue is closed
        AND drained — any other exit is a crash (an escaped
        ``BaseException``; the deterministic stand-in is
        ``utils/faults.InjectedWorkerCrash``, which by design escapes the
        job-failure ``except Exception``). The watchdog applies the
        recovery policy (:meth:`_recover_worker`) and keeps the daemon
        serving; it exits only when a drain completed cleanly."""
        while True:
            worker = self._worker
            if worker is None:
                return
            worker.join(timeout=WATCHDOG_INTERVAL_SECONDS)
            if worker.is_alive():
                continue
            with self._lock:
                inflight = self._inflight
            if self._queue.drained and inflight == 0:
                # Contract exit: drain finished every admitted job.
                return
            self._recover_worker()

    def _recover_worker(self) -> None:
        """One dead worker: settle its in-flight job, start a replacement.

        Policy (the acceptance contract of the chaos tests):
        - an in-flight job that had NOT begun device work is requeued
          once — its claim was side-effect-free, so one silent retry is
          safe and invisible to the client;
        - an in-flight job that touched the devices (or already rode its
          one requeue) is marked ``failed`` with a structured
          ``worker-crashed:`` error — the daemon stays healthy, the
          client gets a terminal status instead of a forever-running job;
        - a fresh worker thread takes over either way.
        """
        crashed: Optional[Job] = None
        with self._lock:
            for job in self._table.values():
                if job.status == "running":
                    crashed = job
                    break
            # Reset BEFORE the replacement starts: the new worker owns
            # this flag the moment it pops a job.
            self._inflight = 0
        # Replacement FIRST, job settlement second: a client that observes
        # the crashed job's terminal status (or its requeue) must never
        # then find healthz reporting a dead worker — the failure and the
        # recovery must be visible in that order, not the reverse.
        self._worker_restarts.inc(1)
        replacement = threading.Thread(
            target=self._worker_loop, name="serve-worker", daemon=True
        )
        self._worker = replacement
        replacement.start()
        if crashed is None:
            return
        with self._lock:
            requeue = not crashed.device_began and crashed.requeues < 1
            if requeue:
                crashed.requeues += 1
                crashed.status = "queued"
                crashed.started_unix = None
            else:
                self._fail_crashed_locked(
                    crashed,
                    "worker-crashed: the worker thread died mid-job "
                    "after device work began; not requeued (device "
                    "state under a crashed update cannot be trusted)"
                    if crashed.device_began
                    else "worker-crashed: the worker thread died "
                    "mid-claim and the job already rode its one "
                    "requeue",
                )
        if requeue:
            try:
                # Outside the table lock (the admission path's lock order).
                self._queue.put(crashed)
            except (QueueFull, QueueClosed) as e:
                with self._lock:
                    self._fail_crashed_locked(
                        crashed,
                        f"worker-crashed: requeue rejected ({e}); the "
                        "claim was side-effect-free but the queue would "
                        "not take the job back",
                    )
                self._completed.labels(status="failed").inc()
        else:
            self._completed.labels(status="failed").inc()

    def _fail_crashed_locked(self, job: Job, error: str) -> None:
        job.status = "failed"
        job.error = error
        job.finished_unix = time.time()
        self._mark_terminal_locked(job)


__all__ = ["MEM_LIMIT_CODES", "PcaService", "WATCHDOG_INTERVAL_SECONDS"]

"""The resident PCA service: warm process, admission control, executor slices.

:class:`PcaService` is the daemon's brain, HTTP-free (``serve/http.py``
is a thin dispatch onto it, so every behavior is testable in-process):

- **owns the devices, in slices**: the backend is initialized ONCE at
  :meth:`start` (the process-startup cost every batch invocation pays)
  and partitioned into independent **executor slices**
  (``parallel/mesh.py:plan_executor_slices``): a large slice for
  whole-genome-class jobs plus optional small slices sized for
  statically-bounded small jobs, each slice its own device subset, its
  own mesh, its own worker thread — so a 0.229 s BRCA1-class query runs
  CONCURRENTLY beside a multi-second whole-genome job instead of
  head-blocking behind it. Jobs on one slice never touch another
  slice's devices, and the in-process jit caches stay warm across jobs
  (``utils/cache.py``'s warm-geometry ledger makes that observable);
- **admits device-free, per slice**: every request is validated by the
  ``graftcheck plan`` validator (``check/plan.py``) BEFORE it may queue —
  against the device count of the slice that will RUN it, not the whole
  pod — and flag-grammar errors, geometry contradictions,
  HBM/host-memory/exactness violations are structured 4xx bodies
  carrying the plan facts;
- **batches continuously**: a freed small-slice worker coalesces every
  queued small job with a compatible batch fingerprint
  (``utils/cache.py:batch_compile_fingerprint``) into one dispatch
  group (``serve/queue.py:pop_batch``), bounded by ``batch_max_jobs``
  and ``batch_linger_seconds`` — results stay byte-identical to serial
  execution, only the scheduling changes;
- **survives restarts**: every acknowledged admission is journaled
  (``serve/journal.py``) before its 202 leaves the socket, the
  warm-geometry ledger and the XLA persistent compilation cache are
  keyed under the run directory, so a restarted daemon replays
  accepted-but-unfinished jobs (requeue-once semantics preserved via
  the journaled ``device_began`` flag) and serves its first
  repeat-geometry job warm instead of paying the whole-genome recompile;
- **drains gracefully**: :meth:`begin_drain` stops admission (503),
  lets every slice worker finish every admitted job, then the workers
  exit — the SIGTERM path of the ``serve`` CLI verb.

Telemetry rides the existing ``obs/`` stack: one service-level
:class:`~spark_examples_tpu.obs.metrics.MetricsRegistry` (scraped at
``GET /metrics``) with per-slice gauges, per-request spans in a
:class:`~spark_examples_tpu.obs.spans.SpanRecorder`, and the standard
:class:`~spark_examples_tpu.obs.heartbeat.Heartbeat` emitting service
liveness (queue depth, in-flight, slice busyness, batching, warm/cold
compile counts) to stderr.
"""

from __future__ import annotations

import os
import re
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

from spark_examples_tpu.serve.executor import (
    ExecutionOutcome,
    execute_fused_batch,
    execute_job,
)
from spark_examples_tpu.serve.journal import (
    DEFAULT_LEASE_SECONDS,
    JobJournal,
    LeaseStore,
    RunDirLock,
    acquire_run_dir_lock,
    adoption_action,
    compact_journal,
    compact_journal_shared,
    journal_path,
    replay_journal,
    revalidate_pending,
    steal_candidates,
)
from spark_examples_tpu.serve.protocol import (
    ProtocolError,
    error_doc,
    job_doc,
    parse_request,
    request_doc,
)
from spark_examples_tpu.obs.trace import mint_trace_id, normalize_trace_id
from spark_examples_tpu.serve.queue import (
    DEFAULT_AGE_CAP_SECONDS,
    DEFAULT_BATCH_LINGER_SECONDS,
    DEFAULT_BATCH_MAX_JOBS,
    DEFAULT_LARGE_CAPACITY,
    DEFAULT_SMALL_CAPACITY,
    SMALL_JOB_MAX_SITES,
    BoundedJobQueue,
    Job,
    QueueClosed,
    QueueFull,
    classify_conf,
)
from spark_examples_tpu.utils import faults

#: How often the watchdog checks each worker thread's pulse. A dead
#: worker is replaced within ~this bound, so one crashed job never looks
#: like a wedged daemon to pollers.
WATCHDOG_INTERVAL_SECONDS = 0.05

#: A replica renews its leases this many times per TTL — two missed
#: ticks still leave one renewal before expiry, so only a genuinely
#: stalled (or dead) replica ever lets a lease lapse.
LEASE_RENEWALS_PER_TTL = 3

#: Replica-id grammar: filesystem-safe (it names lease/heartbeat/lock
#: files and is embedded in job ids), bounded, and never empty.
_REPLICA_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")

#: Shared-journal size past which a replica's scan triggers runtime
#: compaction (startup-only compaction would let settled records — and
#: the cost of every steal-scan fold — grow until the next restart).
JOURNAL_COMPACT_BYTES = 4 << 20

#: Plan-rejection codes that are RESOURCE bounds (the request is
#: well-formed but too big for the declared budgets) — surfaced as HTTP
#: 413 rather than 400, so clients can distinguish "fix the flags" from
#: "shrink the request or find a bigger service".
MEM_LIMIT_CODES = frozenset(
    {
        # "host-mem-unprovable" is retired: conf_host_peak_bytes is
        # TOTAL now, so every job kind proves a finite bound and the
        # only host-memory rejection left is a bound over budget.
        "host-mem-over-budget",
        "dense-exceeds-hbm",
        "sharded-exceeds-hbm",
        "fused-group-exceeds-hbm",
    }
)

#: Terminal jobs kept queryable after completion (per-job manifests stay
#: on disk forever; only the in-memory record — result payload included —
#: is bounded). Without a cap the job table of a long-lived daemon grows
#: monotonically: the control plane must obey the same bounded-memory
#: discipline ``graftcheck hostmem`` enforces on ingest.
DEFAULT_TERMINAL_RETENTION = 256

#: Flags a served job may not carry: multi-controller topology belongs to
#: the daemon's own launch, and every daemon-host write path belongs to
#: the service (one canonical per-job directory; see ``serve/executor.py``)
#: — a client-chosen ``--output-path``/``--profile-dir``/``--save-variants``
#: would be an arbitrary-path write primitive on the service host.
_RESERVED_FLAG_FIELDS = (
    ("coordinator_address", "--coordinator-address"),
    ("num_processes", "--num-processes"),
    ("process_id", "--process-id"),
    ("metrics_json", "--metrics-json"),
    ("output_path", "--output-path"),
    ("profile_dir", "--profile-dir"),
    ("save_variants", "--save-variants"),
    # Daemon-host write paths AND process-wide kill switches: a served
    # job carrying a fault plan could SIGKILL the daemon (kill@... fires
    # os.kill on the whole process), and checkpoint/resume directories
    # are arbitrary-path read/write primitives on the service host.
    ("fault_plan", "--fault-plan"),
    ("gramian_checkpoint_dir", "--gramian-checkpoint-dir"),
    ("resume_from", "--resume-from"),
    # The analyses' per-site output paths are daemon-host write primitives
    # too; a served grm job returns the kinship SUMMARY, never a
    # client-placed matrix file.
    ("grm_out", "--grm-out"),
)
# NOT reserved: --fused-jobs. It is a pure plan directive — admission
# validates the K-lane stacked geometry (an over-HBM group is a
# structured 413 via MEM_LIMIT_CODES) but group MEMBERSHIP stays the
# daemon's dispatch decision: the flag is fingerprint-invariant
# (utils/cache.py:_NON_GEOMETRY_FIELDS) and nothing in the execution
# path reads it, so a declared K can neither force nor split a group.


def _parse_job_flags(flags, kind: str = "pca"):
    """Parse a request's flag list through the REAL parser of the job's
    kind (``check/plan.py:ANALYSIS_SURFACES`` — never a drifted copy;
    ``pca``/``similarity`` share the PCA surface, ``grm`` parses the grm
    verb's); argparse errors raise ``ValueError``."""
    from spark_examples_tpu.check.plan import ANALYSIS_SURFACES, _RaisingParser

    build_parser, conf_cls = ANALYSIS_SURFACES[
        kind if kind in ANALYSIS_SURFACES else "pca"
    ]
    parser = build_parser(_RaisingParser(prog="serve-job", add_help=False))
    ns = parser.parse_args(list(flags))
    return conf_cls._from_namespace(ns)


class _SliceWorker:
    """One executor slice's runtime state: its device subset, its worker
    thread, and what it is running right now. Mutable fields
    (``thread``/``done``/``running_job_id``/``pending_batch``) are
    guarded by the owning service's table lock except where noted."""

    def __init__(self, spec, devices):
        self.spec = spec
        self.devices = list(devices)
        self.thread: Optional[threading.Thread] = None
        #: Clean contract exit observed (drain finished for this slice's
        #: classes); the watchdog stops monitoring a done worker.
        self.done = False
        self.running_job_id: Optional[str] = None
        #: Jobs popped into the current dispatch group but not yet
        #: started — a crashed worker's untouched batch tail is requeued
        #: (those jobs were never claimed, so the retry is free).
        self.pending_batch: List[Job] = []


class PcaService:
    """The resident service; see the module docstring for the contract."""

    def __init__(
        self,
        run_dir: Optional[str] = None,
        small_capacity: int = DEFAULT_SMALL_CAPACITY,
        large_capacity: int = DEFAULT_LARGE_CAPACITY,
        host_mem_budget: Optional[int] = None,
        heartbeat_seconds: float = 0.0,
        executor: Optional[Callable[[Job, str], ExecutionOutcome]] = None,
        terminal_retention: int = DEFAULT_TERMINAL_RETENTION,
        small_slices: Optional[int] = 0,
        small_slice_devices: int = 1,
        small_site_limit: int = SMALL_JOB_MAX_SITES,
        batch_max_jobs: int = DEFAULT_BATCH_MAX_JOBS,
        batch_linger_seconds: float = DEFAULT_BATCH_LINGER_SECONDS,
        batch_fuse: bool = True,
        ordering: str = "cost",
        age_cap_seconds: float = DEFAULT_AGE_CAP_SECONDS,
        persistent_cache: bool = False,
        replica_id: Optional[str] = None,
        lease_seconds: float = DEFAULT_LEASE_SECONDS,
        lease_grace_seconds: Optional[float] = None,
        steal_interval_seconds: Optional[float] = None,
        guard_run_dir: bool = False,
        deadline_feasibility: bool = True,
    ):
        if terminal_retention < 1:
            raise ValueError(
                f"terminal_retention must be >= 1, got {terminal_retention}"
            )
        if small_site_limit < 1:
            raise ValueError(
                f"small_site_limit must be >= 1 site, got {small_site_limit}"
            )
        if batch_max_jobs < 1:
            raise ValueError(
                f"batch_max_jobs must be >= 1, got {batch_max_jobs}"
            )
        if batch_linger_seconds < 0:
            raise ValueError(
                f"batch_linger_seconds must be >= 0, got "
                f"{batch_linger_seconds}"
            )
        if small_slices is not None and small_slices < 0:
            raise ValueError(
                f"small_slices must be >= 0 (or None = auto), got "
                f"{small_slices}"
            )
        if small_slice_devices < 1:
            raise ValueError(
                f"small_slice_devices must be >= 1, got "
                f"{small_slice_devices}"
            )
        if replica_id is not None and not _REPLICA_ID_RE.match(replica_id):
            raise ValueError(
                f"replica_id must match {_REPLICA_ID_RE.pattern} (it names "
                f"lease and lock files), got {replica_id!r}"
            )
        if lease_seconds <= 0:
            raise ValueError(
                f"lease_seconds must be > 0, got {lease_seconds}"
            )
        if lease_grace_seconds is not None and lease_grace_seconds < 0:
            raise ValueError(
                f"lease_grace_seconds must be >= 0, got "
                f"{lease_grace_seconds}"
            )
        if steal_interval_seconds is not None and steal_interval_seconds <= 0:
            raise ValueError(
                f"steal_interval_seconds must be > 0, got "
                f"{steal_interval_seconds}"
            )
        self.run_dir = run_dir or tempfile.mkdtemp(prefix="spark-serve-")
        self.host_mem_budget = host_mem_budget
        self.heartbeat_seconds = float(heartbeat_seconds)
        self.terminal_retention = int(terminal_retention)
        #: None = auto (one small slice when a device can be spared);
        #: resolved against the real device count at :meth:`start`.
        self.small_slices = small_slices
        self.small_slice_devices = int(small_slice_devices)
        self.small_site_limit = int(small_site_limit)
        self.batch_max_jobs = int(batch_max_jobs)
        self.batch_linger_seconds = float(batch_linger_seconds)
        #: Run multi-job batch groups as ONE stacked device program when
        #: the group is eligible (pipeline/fused.py preflight); ``False``
        #: restores the serial per-job dispatch loop unconditionally.
        self.batch_fuse = bool(batch_fuse)
        self.persistent_cache = bool(persistent_cache)
        self._executor = executor if executor is not None else execute_job
        self._queue = BoundedJobQueue(
            small_capacity,
            large_capacity,
            ordering=ordering,
            age_cap_seconds=age_cap_seconds,
        )
        # (job-state flips and table reads only; the queue's and
        # journal's own leaf locks are never taken while holding it:
        # admission puts and journal appends happen outside.)
        # lock order: service table lock before nothing — it is a leaf.
        self._lock = threading.Lock()
        self._table: Dict[str, Job] = {}
        self._terminal_order: Deque[str] = deque()
        self._seq = 0
        self._inflight = 0
        self._terminal = 0
        self._draining = threading.Event()
        self._workers: List[_SliceWorker] = []
        self._watchdog: Optional[threading.Thread] = None
        self._heartbeat = None
        self._journal: Optional[JobJournal] = None
        #: Multi-replica identity (None = solo mode: no leases, no
        #: stealing, journal records stay epoch-less — byte-for-byte the
        #: single-daemon behavior).
        self.replica_id = replica_id
        self.lease_seconds = float(lease_seconds)
        self.lease_grace_seconds = (
            float(lease_grace_seconds)
            if lease_grace_seconds is not None
            else float(lease_seconds)
        )
        self.steal_interval_seconds = (
            float(steal_interval_seconds)
            if steal_interval_seconds is not None
            else float(lease_seconds)
        )
        self._guard_run_dir = bool(guard_run_dir)
        self._run_dir_lock: Optional[RunDirLock] = None
        #: Flight recorder (obs/recorder.py): every lifecycle transition
        #: of every job this replica touches, crash-durably flushed — the
        #: per-replica half of the fleet's merged trace.
        self._recorder = None
        self._lease_store: Optional[LeaseStore] = None
        self._lease_thread: Optional[threading.Thread] = None
        self._lease_stop = threading.Event()
        self._started_unix: Optional[float] = None
        self._replayed_jobs = 0
        self._primed_geometries = 0
        self.device_count: Optional[int] = None
        self.platform: Optional[str] = None
        #: Reject jobs whose deadline is below the calibrated cost
        #: estimate at admission (413 ``deadline-infeasible``) instead of
        #: queueing work that is guaranteed to expire. ``False`` restores
        #: the optimistic pre-cost-observatory admission.
        self.deadline_feasibility = bool(deadline_feasibility)
        #: Fleet-shared predicted-vs-measured ledger (obs/calibration.py):
        #: every replica appends to the one file under the run dir, so
        #: the fold — and calibrated admission — sees the whole fleet.
        from spark_examples_tpu.obs.calibration import CalibrationLedger

        self._calibration = CalibrationLedger(self.run_dir)
        # Expired queued jobs are swept at admission time (capacity must
        # not be held by jobs that can never run); the sink routes them
        # to the same terminal path a dequeued-too-late job takes.
        self._queue.set_expired_sink(self._expire_queued_job)

        from spark_examples_tpu.obs import MetricsRegistry, SpanRecorder

        self.registry = MetricsRegistry()
        self.spans = SpanRecorder()
        self._register_metrics()

    # ------------------------------------------------------------ telemetry

    def _register_metrics(self) -> None:
        from spark_examples_tpu.obs.metrics import (
            COMPILE_CACHE_GEOMETRY_HITS,
            COMPILE_CACHE_GEOMETRY_MISSES,
            HOST_PEAK_RSS_BYTES,
            SERVE_BATCH_JOBS,
            SERVE_BATCHES,
            SERVE_JOBS_DONE,
            SERVE_JOBS_INFLIGHT,
            SERVE_JOURNAL_REPLAYED,
            SERVE_QUEUE_DEPTH,
            SERVE_SLICES,
            SERVE_SLICES_BUSY,
            SERVE_WORKER_RESTARTS,
            read_host_peak_rss_bytes,
            well_known_counter,
            well_known_gauge,
        )
        from spark_examples_tpu.utils.cache import compile_cache_stats

        well_known_gauge(self.registry, SERVE_QUEUE_DEPTH).set_function(
            lambda: float(self._queue.total_depth())
        )
        well_known_gauge(self.registry, SERVE_JOBS_INFLIGHT).set_function(
            lambda: float(self._inflight)
        )
        well_known_gauge(self.registry, SERVE_JOBS_DONE).set_function(
            lambda: float(self._terminal)
        )
        well_known_gauge(self.registry, SERVE_SLICES).set_function(
            lambda: float(len(self._workers))
        )
        well_known_gauge(self.registry, SERVE_SLICES_BUSY).set_function(
            lambda: float(
                sum(
                    1
                    for w in self._workers
                    if w.running_job_id is not None
                )
            )
        )
        well_known_gauge(
            self.registry, COMPILE_CACHE_GEOMETRY_HITS
        ).set_function(lambda: float(compile_cache_stats()[0]))
        well_known_gauge(
            self.registry, COMPILE_CACHE_GEOMETRY_MISSES
        ).set_function(lambda: float(compile_cache_stats()[1]))
        if read_host_peak_rss_bytes() is not None:
            well_known_gauge(self.registry, HOST_PEAK_RSS_BYTES).set_function(
                lambda: float(read_host_peak_rss_bytes() or 0)
            )
        self._submitted = self.registry.counter(
            "serve_jobs_submitted_total",
            "Jobs admitted to the queue, by admission class.",
            labelnames=("job_class",),
        )
        self._rejected = self.registry.counter(
            "serve_jobs_rejected_total",
            "Requests rejected at admission, by rejection code.",
            labelnames=("code",),
        )
        self._completed = self.registry.counter(
            "serve_jobs_completed_total",
            "Jobs that reached a terminal state, by status.",
            labelnames=("status",),
        )
        self._job_seconds = self.registry.histogram(
            "serve_job_seconds",
            "Wall-clock of completed jobs, by admission class.",
            labelnames=("job_class",),
        )
        from spark_examples_tpu.obs.metrics import (
            COST_CALIBRATION_SAMPLES,
            COST_MEASURED_MEAN_SECONDS,
            COST_PREDICTED_MEAN_SECONDS,
            COST_PREDICTION_RATIO,
            SERVE_JOB_WALL_SECONDS,
            SERVE_QUEUE_WAIT_SECONDS,
            WIDE_SECONDS_BUCKETS,
        )

        self._queue_wait_seconds = self.registry.histogram(
            SERVE_QUEUE_WAIT_SECONDS,
            "Admission-to-dequeue wait of jobs, by admission class.",
            labelnames=("job_class",),
            buckets=WIDE_SECONDS_BUCKETS,
        )
        self._job_wall_seconds = self.registry.histogram(
            SERVE_JOB_WALL_SECONDS,
            "Executor wall-clock of completed jobs, by kind, admission "
            "class, and compile cache disposition.",
            labelnames=("kind", "job_class", "compile"),
            buckets=WIDE_SECONDS_BUCKETS,
        )
        self._prediction_ratio = self.registry.gauge(
            COST_PREDICTION_RATIO,
            "measured/predicted wall-clock ratio of the most recently "
            "completed job, by kind.",
            labelnames=("kind",),
        )
        # Fleet calibration aggregates (this replica's fold of the shared
        # ledger): NaN while no completed job has been recorded — the
        # heartbeat's cost segment keys off the NaN guard.
        well_known_gauge(
            self.registry, COST_CALIBRATION_SAMPLES
        ).set_function(lambda: float(self._calibration.fold.overall.n))
        well_known_gauge(
            self.registry, COST_PREDICTED_MEAN_SECONDS
        ).set_function(
            lambda: (
                self._calibration.fold.overall.predicted_sum
                / self._calibration.fold.overall.n
                if self._calibration.fold.overall.n
                else float("nan")
            )
        )
        well_known_gauge(
            self.registry, COST_MEASURED_MEAN_SECONDS
        ).set_function(
            lambda: (
                self._calibration.fold.overall.measured_sum
                / self._calibration.fold.overall.n
                if self._calibration.fold.overall.n
                else float("nan")
            )
        )
        self._slice_inflight = self.registry.gauge(
            "serve_slice_inflight",
            "Jobs currently executing on each executor slice (0 or 1 — "
            "a slice runs its dispatch group serially).",
            labelnames=("slice",),
        )
        self._worker_restarts = well_known_counter(
            self.registry, SERVE_WORKER_RESTARTS
        )
        self._batches = well_known_counter(self.registry, SERVE_BATCHES)
        self._batch_jobs = well_known_counter(
            self.registry, SERVE_BATCH_JOBS
        )
        from spark_examples_tpu.obs.metrics import (
            SERVE_FUSED_GROUPS,
            SERVE_FUSED_JOBS,
        )

        self._fused_groups = well_known_counter(
            self.registry, SERVE_FUSED_GROUPS
        )
        self._fused_jobs = well_known_counter(
            self.registry, SERVE_FUSED_JOBS
        )
        self._serial_jobs = self.registry.counter(
            "serve_serial_jobs_total",
            "Jobs dispatched as their own device program (the non-fused "
            "path; fused vs serial partitions every executed job).",
        )
        self._journal_replayed = well_known_counter(
            self.registry, SERVE_JOURNAL_REPLAYED
        )
        from spark_examples_tpu.obs.metrics import (
            SERVE_JOBS_STOLEN,
            SERVE_LEASE_RENEWALS,
            SERVE_REPLICAS_ALIVE,
        )

        self._lease_renewals = well_known_counter(
            self.registry, SERVE_LEASE_RENEWALS
        )
        self._jobs_stolen = well_known_counter(
            self.registry, SERVE_JOBS_STOLEN
        )
        # Solo mode exports 0 honestly: nothing is heartbeating the run
        # dir's replica directory, so no replica failover is available.
        well_known_gauge(self.registry, SERVE_REPLICAS_ALIVE).set_function(
            lambda: float(
                self._lease_store.alive_count()
                if self._lease_store is not None
                else 0
            )
        )

    # ------------------------------------------------------------- tracing

    def _flush_recorder(self) -> None:
        """The fault-hook target (``utils/faults.add_flush_hook``): make
        the ring durable before an injected fault fires. fsync'd — this
        may be the last Python the process executes."""
        if self._recorder is not None:
            self._recorder.flush(fsync=True)

    def _trace_event(
        self,
        name: str,
        ph: str = "i",
        job: Optional[Job] = None,
        job_id: Optional[str] = None,
        trace: Optional[str] = None,
        tid: str = "control",
        flush: bool = False,
        **args,
    ) -> None:
        """Record one flight-recorder event (no-op before :meth:`start`).
        ``flush=True`` drains the ring with a buffered write (no fsync:
        a ``kill -9`` keeps OS page-cache writes, and the fault hook
        fsyncs before injected kills) — cheap enough for every terminal
        transition."""
        recorder = self._recorder
        if recorder is None:
            return
        if job is not None:
            job_id = job.id
            trace = trace if trace is not None else job.trace_id
        recorder.record(name, ph=ph, trace=trace, job=job_id, tid=tid, **args)
        if flush:
            recorder.flush(fsync=False)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "PcaService":
        """Initialize the backend (the once-per-process cost), carve the
        executor slices, prime the persistent warm state, replay the job
        journal, then start the per-slice workers and the optional
        service heartbeat."""
        if self._workers:
            return self
        # Force the lazy env-var fault plan to parse NOW (the batch path
        # does the same in run_pipeline): a typo'd site name must fail the
        # daemon at startup, not surface as a crash/restart loop where
        # every job rides its one requeue and then fails with a
        # misleading "worker-crashed:" error.
        faults.active()
        os.makedirs(self.run_dir, exist_ok=True)
        if self._guard_run_dir:
            # Raises RunDirBusy (CLI exit 2): a second unreplicated
            # daemon on this run dir would corrupt the journal; replicas
            # with distinct ids coexist by design.
            self._run_dir_lock = acquire_run_dir_lock(
                self.run_dir, self.replica_id
            )
        # The flight recorder comes up BEFORE journal replay so replayed
        # adoptions and startup steals are on the record; its ring is
        # flushed at every registered fault kill-point (the hook below
        # runs as the last Python before an injected SIGKILL), at every
        # terminal transition, and at drain — the chaos harness's
        # `kill -9` always lands on a segment holding the events that
        # led up to it.
        from spark_examples_tpu.obs.recorder import FlightRecorder

        self._recorder = FlightRecorder(
            self.run_dir, name=self.replica_id or "solo"
        )
        faults.add_flush_hook(self._flush_recorder)
        if self.replica_id is not None:
            self._lease_store = LeaseStore(
                self.run_dir,
                self.replica_id,
                lease_seconds=self.lease_seconds,
                grace_seconds=self.lease_grace_seconds,
            )
            self._lease_store.heartbeat()
        from spark_examples_tpu.utils.cache import (
            attach_geometry_ledger,
            enable_persistent_compile_cache,
        )

        if self.persistent_cache:
            # Warm state half 1: XLA compile artifacts keyed under the
            # run dir — a restarted daemon reloads them from disk instead
            # of recompiling (the ~9.5 s whole-genome recompile of
            # BENCH_r05 becomes a cache read).
            enable_persistent_compile_cache(
                os.path.join(self.run_dir, "jax-cache")
            )
        import jax

        # The warm-mesh moment: devices enumerate here, once; every
        # admitted job reuses this initialized backend (and, for repeated
        # geometries, its live jit caches).
        devices = list(jax.devices())
        self.device_count = len(devices)
        self.platform = devices[0].platform
        from spark_examples_tpu.parallel.mesh import (
            plan_executor_slices,
            resolve_small_slices,
        )

        small = resolve_small_slices(self.small_slices, len(devices))
        specs = plan_executor_slices(
            len(devices), small, self.small_slice_devices
        )
        self._workers = [
            _SliceWorker(
                spec,
                devices[
                    spec.device_start : spec.device_start + spec.device_count
                ],
            )
            for spec in specs
        ]
        if self.persistent_cache:
            # Warm state half 2: the warm-geometry ledger primes from
            # (and persists to) the run dir, so warm-vs-cold attribution
            # survives the process. Gated on the SAME switch as the XLA
            # cache above: a primed "warm" is only honest because the
            # compile artifacts reload from disk — with
            # --no-persistent-cache a restarted daemon recompiles, so it
            # must report cold too (see
            # utils/cache.py:attach_geometry_ledger).
            self._primed_geometries = attach_geometry_ledger(
                os.path.join(self.run_dir, "geometry.ledger")
            )
        self._journal = JobJournal(
            journal_path(self.run_dir), replica=self.replica_id
        )
        self._replay_journal()
        self._started_unix = time.time()
        for worker in self._workers:
            thread = threading.Thread(
                target=self._worker_loop,
                args=(worker,),
                name=f"serve-worker-{worker.spec.name}",
                daemon=True,
            )
            worker.thread = thread
            thread.start()
        # The self-healing half: a watchdog that replaces a dead worker
        # thread instead of letting one crashed job wedge its slice.
        self._watchdog = threading.Thread(
            target=self._watchdog_loop, name="serve-watchdog", daemon=True
        )
        self._watchdog.start()
        if self._lease_store is not None:
            self._lease_thread = threading.Thread(
                target=self._lease_loop,
                name=f"serve-lease-{self.replica_id}",
                daemon=True,
            )
            self._lease_thread.start()
        if self.heartbeat_seconds > 0:
            from spark_examples_tpu.obs.heartbeat import Heartbeat

            self._heartbeat = Heartbeat(
                self.heartbeat_seconds, self.registry
            ).start()
        return self

    def _replay_journal(self) -> None:
        """Reload accepted-but-unfinished jobs from the journal (prior
        admissions against this run dir). Jobs that never began device
        work requeue with their one retry consumed; jobs journaled
        ``began`` fail with a structured error — the exact policy the
        in-process watchdog applies to a crashed worker, extended to a
        crashed process. In multi-replica mode the replay only ADOPTS
        jobs it can lease: this replica's previous life's jobs re-claim
        their lease, a dead peer's expired jobs steal (epoch+1), and a
        live peer's jobs are skipped — they stay in the shared journal,
        owned by their replica."""
        assert self._journal is not None
        pending, max_seq = replay_journal(self._journal.path)
        with self._lock:
            self._seq = max(self._seq, max_seq)
        requeued = []
        for record in pending:
            stolen = False
            if self._lease_store is not None:
                foreign = (
                    record.lease_replica is not None
                    and record.lease_replica != self.replica_id
                )
                if foreign:
                    # Startup-replay steals pass the same registered
                    # kill-point as the running steal scan: a kill here
                    # must leave the job claimable by any other replica.
                    faults.kill_point("serve.steal.pre-claim")
                epoch = self._lease_store.claim(
                    record.job_id,
                    steal=True,
                    min_epoch=record.lease_epoch,
                    min_replica=record.lease_replica,
                )
                if epoch is None:
                    continue  # a live peer's job (or we lost the race)
                fresh = self._revalidate_claim(record.job_id, epoch)
                if fresh is None:
                    continue  # settled between our fold and our claim
                record = fresh
                stolen = foreign
                # Registered kill-point: claimed on disk, lease record
                # not yet journaled (same window as the submit path).
                faults.kill_point("serve.lease.post-claim")
                self._journal.lease(record.job_id, epoch, stolen=stolen)
                if stolen:
                    self._jobs_stolen.inc(1)
                    # The merged trace's steal edge: a flow arrow from
                    # the dead owner's last recorded event to this claim.
                    self._trace_event(
                        "steal",
                        job_id=record.job_id,
                        trace=record.trace_id,
                        flush=True,
                        epoch=epoch,
                        **{"from": record.lease_replica},
                    )
            if self._adopt_pending(record, stolen=stolen):
                requeued.append(record)
        if self._lease_store is not None:
            # Lease-aware compaction: only the holder of the journal's
            # exclusive compaction lock compacts (a replica starting
            # while a peer is mid-compaction skips — never two
            # rewriters); the winner re-folds UNDER the lock so peers'
            # concurrent records survive the rewrite.
            compact_journal_shared(
                self._journal.path, lease_dir=self._lease_store.lease_dir
            )
        else:
            # Solo mode: exclusive ownership (enforced by the run-dir
            # guard), so the replay's own pending list is the truth.
            # Began and unparseable records leave the journal (their
            # table entries — when any — are terminal, and replaying
            # them again would be wrong).
            compact_journal(self._journal.path, requeued)

    def _adopt_pending(
        self, record, stolen: bool, count_replayed: bool = True
    ) -> bool:
        """Adopt one replayed/stolen pending job into this replica's
        table and queue; returns ``True`` iff the job was requeued.
        ``stolen`` selects the structured-error wording for jobs whose
        device work had begun under the dead owner."""
        try:
            request = parse_request(record.request_doc)
            conf = _parse_job_flags(request.flags, kind=request.kind)
        except (ProtocolError, ValueError) as e:
            print(
                f"serve: journal record {record.job_id} no longer "
                f"parses ({e}); dropping it",
                file=sys.stderr,
            )
            # A shared journal re-folds at compaction, so a silently
            # skipped record would replay forever: tombstone it.
            if self._journal is not None:
                self._journal.terminal(
                    record.job_id,
                    "rejected",
                    epoch=self._lease_epoch(record.job_id),
                )
            if self._lease_store is not None:
                self._lease_store.release(record.job_id)
            return False
        job = Job(
            id=record.job_id,
            request=request,
            conf=conf,
            job_class=classify_conf(
                conf, small_site_limit=self.small_site_limit
            ),
            submitted_unix=record.submitted_unix,
            deadline_unix=record.deadline_unix,
            batch_key=self._batch_key(conf, request.kind),
            # The restart/steal consumed the job's one free retry: a
            # worker crash on the adopted copy must fail it, not loop
            # it through a third life.
            requeues=1,
            # The journaled trace id keeps the stolen/replayed job in the
            # SAME span tree its submit opened; pre-tracing journals get
            # a fresh id so every adopted job is still traceable.
            trace_id=record.trace_id or mint_trace_id(),
            # The ORIGINAL admission prediction rides the steal/replay
            # (like the trace id): the calibration pair must compare
            # against what admission promised, not a re-prediction under
            # the adopter's warm state.
            cost_prediction=self._cost_from_record(record),
        )
        job.cost_estimate_seconds = (
            job.cost_prediction.best_estimate_seconds
            if job.cost_prediction is not None
            else None
        )
        if count_replayed:
            self._journal_replayed.inc(1)
            self._replayed_jobs += 1
        self._trace_event(
            "adopt",
            job=job,
            flush=True,
            stolen=stolen,
            device_began=record.device_began,
            from_replica=record.lease_replica,
        )
        if adoption_action(record.device_began) == "fail":
            # The requeue-once boundary holds ACROSS replica lives: the
            # journaled began flag was written by whichever life started
            # the device work, and no later life may silently re-run it
            # (the policy itself is journal.adoption_action — shared
            # with the model checker).
            with self._lock:
                self._table[job.id] = job
                self._fail_crashed_locked(
                    job,
                    (
                        f"replica-failover: replica "
                        f"{record.lease_replica or 'unknown'} died after "
                        "this job's device work began; not re-run "
                        "(device state under a crashed update cannot be "
                        "trusted for a silent retry)"
                    )
                    if stolen
                    else (
                        "daemon-restarted: the daemon died after this "
                        "job's device work began; not re-run (device "
                        "state under a crashed update cannot be trusted "
                        "for a silent retry)"
                    ),
                )
            self._journal_terminal(job)
            self._completed.labels(status="failed").inc()
            self._record_failed_cost(job)
            return False
        with self._lock:
            self._table[job.id] = job
        try:
            # Replayed and stolen jobs alike re-enter capacity-exempt
            # (the contract is on inject_reclaimed): their 202 was
            # acknowledged by the previous owner.
            self._queue.inject_reclaimed(job)
        except (QueueFull, QueueClosed) as e:
            with self._lock:
                self._fail_crashed_locked(
                    job,
                    f"{'replica-failover' if stolen else 'daemon-restarted'}"
                    f": could not requeue ({e})",
                )
            self._journal_terminal(job)
            self._completed.labels(status="failed").inc()
            self._record_failed_cost(job)
            return False
        return True

    def begin_drain(self) -> None:
        """Stop admission (new submissions get 503); already-admitted jobs
        still run to completion."""
        self._draining.set()
        self._queue.close()
        # SIGTERM rides through here (serve/http.py's signal handler):
        # the drain decision itself becomes durable immediately.
        self._trace_event("drain-begin", flush=True)

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def wait_drained(self, timeout: Optional[float] = None) -> bool:
        """Block until every slice worker finished every admitted job and
        exited (call :meth:`begin_drain` first). Returns ``False`` on
        timeout. Polls rather than joins: the watchdog may replace a
        crashed worker mid-drain (publish-before-start), and the drain
        only completes when every CURRENT worker exited cleanly with
        nothing left in flight and the job table settled."""
        deadline = (
            None if timeout is None else time.monotonic() + float(timeout)
        )
        while True:
            workers = list(self._workers)
            with self._lock:
                inflight = self._inflight
                # A crash mid-drain leaves the watchdog a beat of
                # settlement work AFTER it started the replacement: the
                # crashed job may still read ``running`` (or transiently
                # ``queued``) while the new worker already drained the
                # queue. The drain contract is "every admitted job
                # reached a terminal state", so wait for the table too.
                unsettled = any(
                    job.status in ("queued", "running")
                    for job in self._table.values()
                )
            if (
                workers
                and all(w.done for w in workers)
                and self._queue.drained
                and inflight == 0
                and not unsettled
            ):
                break
            if not workers:
                # Never started: no worker will ever drain anything —
                # return immediately (queued jobs, if any, are simply
                # abandoned with the service, exactly as before slices).
                break
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(0.02)
        if self._heartbeat is not None:
            self._heartbeat.stop()
            self._heartbeat = None
        self._lease_stop.set()
        if self._lease_thread is not None:
            self._lease_thread.join(timeout=5.0)
            self._lease_thread = None
        if self._lease_store is not None:
            # An intentional departure, not a death: withdraw the
            # heartbeat so surviving peers do not report the pool
            # degraded over a clean scale-down.
            self._lease_store.retire()
        if self._recorder is not None:
            self._trace_event("drained")
            faults.remove_flush_hook(self._flush_recorder)
            self._recorder.close()
        self._calibration.close()
        if self._run_dir_lock is not None:
            self._run_dir_lock.release()
            self._run_dir_lock = None
        return True

    def stop(self, timeout: float = 30.0) -> bool:
        """Drain and join (tests and the CLI's shutdown path)."""
        self.begin_drain()
        return self.wait_drained(timeout=timeout)

    # ------------------------------------------------------------ admission

    def _batch_key(self, conf, kind: str) -> Optional[str]:
        from spark_examples_tpu.utils.cache import batch_compile_fingerprint

        try:
            return batch_compile_fingerprint(conf, kind=kind)
        except Exception:
            return None  # an unkeyable conf simply never coalesces

    def admission_devices(self, job_class: str) -> Optional[int]:
        """The device count admission validates ``job_class`` against: the
        count of the slice that will RUN the job (``None`` before
        :meth:`start` — the validator then skips device-bound checks,
        exactly like ``graftcheck plan`` without ``--plan-devices``)."""
        for worker in self._workers:
            if job_class in worker.spec.job_classes:
                return worker.spec.device_count
        return self.device_count

    def submit(self, doc, trace_id: Optional[str] = None) -> Tuple[int, Dict]:
        """One ``POST /v1/jobs`` body → ``(http_status, response_doc)``.
        ``trace_id`` is the client's ``X-Trace-Id`` header (malformed or
        absent → a server-minted id): the job's whole fleet-side life is
        recorded under it."""
        if self.draining:
            self._rejected.labels(code="draining").inc()
            return 503, error_doc(
                "draining",
                "service is draining; submit to another replica",
                retry_after_seconds=30.0,
            )
        try:
            request = parse_request(doc)
        except ProtocolError as e:
            self._rejected.labels(code=e.code).inc()
            return 400, error_doc(e.code, e.message)
        try:
            conf = _parse_job_flags(request.flags, kind=request.kind)
        except ValueError as e:
            self._rejected.labels(code="flag-grammar").inc()
            return 400, error_doc("flag-grammar", str(e))
        for field, flag in _RESERVED_FLAG_FIELDS:
            # `is not None`, not truthiness: --process-id 0 is the
            # canonical coordinator id and must be rejected like any other.
            if getattr(conf, field, None) is not None:
                self._rejected.labels(code="reserved-flag").inc()
                return 400, error_doc(
                    "reserved-flag",
                    f"{flag} is owned by the service and may not ride a "
                    "served job (manifests land at the per-job path; "
                    "multi-controller topology belongs to the daemon "
                    "launch)",
                )

        job_class = classify_conf(
            conf, small_site_limit=self.small_site_limit
        )
        # Device-free admission validation: the graftcheck plan validator
        # over the REAL device count of the slice this class runs on (a
        # small job must fit its small slice, not the whole pod) and the
        # host-memory budget. An exit-2 plan becomes a structured 4xx
        # carrying the plan facts.
        from spark_examples_tpu.check.plan import validate_plan

        report = validate_plan(
            conf,
            plan_devices=self.admission_devices(job_class),
            host_mem_budget=self.host_mem_budget,
            # The grm kind admits through the analysis's own plan entry
            # (the analyses admission gate + Gramian proofs); pca and
            # similarity keep the default PCA surface.
            analysis="grm" if request.kind == "grm" else "pca",
        )
        plan_block = {
            "ok": report.ok,
            "issues": [
                {"code": i.code, "severity": i.severity, "message": i.message}
                for i in report.issues
            ],
            "geometry": report.geometry,
            "shape_checks": report.shape_checks,
        }
        if not report.ok:
            error_codes = [
                i.code for i in report.issues if i.severity == "error"
            ]
            status = (
                413 if any(c in MEM_LIMIT_CODES for c in error_codes) else 400
            )
            self._rejected.labels(code="plan-rejected").inc()
            return status, error_doc(
                "plan-rejected",
                "admission plan validation rejected this configuration: "
                + "; ".join(error_codes),
                plan=plan_block,
            )

        # Admission-time cost prediction: the ONE estimator (check/
        # plan.py:predict_job_cost, shared with the plan CLI and bench)
        # over the geometry the validator above just computed — no second
        # validation — then calibrated against the fleet's measured
        # history. Prediction is telemetry plus a feasibility gate; a
        # cost-model failure must never take admission down with it.
        prediction = None
        try:
            from spark_examples_tpu.check.plan import predict_job_cost

            prediction = predict_job_cost(
                conf,
                kind=request.kind,
                plan_devices=self.admission_devices(job_class),
                geometry=report.geometry,
            )
            prediction = self._calibration.calibrated_estimate(prediction)
        except Exception as e:  # noqa: BLE001 — telemetry, not a gate
            print(f"serve: cost prediction failed: {e}", file=sys.stderr)
        if (
            self.deadline_feasibility
            and prediction is not None
            and request.deadline_seconds is not None
            and request.deadline_seconds < prediction.best_estimate_seconds
        ):
            estimate = prediction.best_estimate_seconds
            self._rejected.labels(code="deadline-infeasible").inc()
            doc = error_doc(
                "deadline-infeasible",
                f"deadline_seconds={request.deadline_seconds:.4g} is below "
                f"the calibrated estimate of {estimate:.4g}s for this "
                f"geometry (model predicted "
                f"{prediction.predicted_seconds:.4g}s, "
                f"{prediction.compile} compile, "
                f"{prediction.calibration_samples} calibration samples); "
                "raise the deadline, or start the service with "
                "--no-deadline-feasibility to queue it anyway",
                plan=plan_block,
            )
            doc["cost"] = prediction.to_dict()
            doc["cost"]["requested_deadline_seconds"] = float(
                request.deadline_seconds
            )
            return 413, doc

        now = time.time()
        with self._lock:
            self._seq += 1
            # Replica-stamped ids keep N concurrent admitters collision-
            # free on one shared journal (each replica's sequence only
            # ever continues past what the fold has seen).
            job_id = (
                f"job-{self.replica_id}-{self._seq:06d}"
                if self.replica_id is not None
                else f"job-{self._seq:06d}"
            )
        job = Job(
            id=job_id,
            request=request,
            conf=conf,
            job_class=job_class,
            submitted_unix=now,
            deadline_unix=(
                now + request.deadline_seconds
                if request.deadline_seconds is not None
                else None
            ),
            plan_geometry=dict(report.geometry),
            batch_key=self._batch_key(conf, request.kind),
            trace_id=normalize_trace_id(trace_id) or mint_trace_id(),
            cost_prediction=prediction,
        )
        # The queue orders each class lane by this calibrated estimate
        # (SJF; serve/queue.py) — stamped here so the queue itself stays
        # free of cost-model imports.
        job.cost_estimate_seconds = (
            prediction.best_estimate_seconds
            if prediction is not None
            else None
        )
        with self._lock:
            self._table[job.id] = job
        # Durable admission: journaled BEFORE the queue can hand the job
        # to a worker — a worker's own `began`/`terminal` records must
        # never race ahead of the `accepted` record they refer to (the
        # replay fold is order-insensitive as defense in depth, but the
        # happy path keeps the file causally ordered). A crash between
        # here and the 202 leaves at most one phantom replayed run whose
        # client never got an id — wasted compute, never double-trusted
        # device work; a rejected put below appends a terminal tombstone
        # so the record cannot resurrect.
        self._journal_accepted(job)
        self._trace_event(
            "accepted",
            job=job,
            flush=True,
            job_class=job.job_class,
            kind=job.request.kind,
        )
        # Registered kill-point: accepted record durable, lease NOT yet
        # claimed — the one-record orphan window. A kill here strands a
        # journaled job with no lease file; the steal scan's orphan
        # branch must reclaim it off the dead owner's stale heartbeat.
        faults.kill_point("serve.submit.post-accept")
        if self._lease_store is not None:
            # Lease the job the moment it is durably accepted: from here
            # on a dead replica's work is visibly expired, stealable
            # state rather than invisible in-memory state. The id is
            # fresh, so the epoch-1 claim can only fail if this replica
            # was deposed as a zombie and a peer's orphan sweep already
            # took the job — refuse the admission rather than run a job
            # another replica owns.
            epoch = self._lease_store.claim(job.id)
            if epoch is None:
                # No tombstone: the lease holder (or its stealer) owns
                # the journal's last word on this id. The client never
                # gets this 202, so a later phantom run is wasted
                # compute, never double-trusted device work.
                with self._lock:
                    del self._table[job.id]
                self._rejected.labels(code="lease-unavailable").inc()
                return 503, error_doc(
                    "lease-unavailable",
                    f"could not lease {job.id} (a peer replica claimed "
                    "it — this replica may be recovering from a stall); "
                    "resubmit",
                    retry_after_seconds=5.0,
                )
            # Post-claim stale-fold fence — found by `graftcheck proto`:
            # if this replica stalled between the accepted append and
            # the claim, a restarting peer may have adopted AND settled
            # the job; enqueueing it now would re-run finished device
            # work. Same revalidation the replay/steal paths use.
            if self._journal is not None:
                if self._revalidate_claim(job.id, epoch) is None:
                    with self._lock:
                        del self._table[job.id]
                    self._rejected.labels(code="lease-unavailable").inc()
                    return 503, error_doc(
                        "lease-unavailable",
                        f"lost the lease race for {job.id} (a peer "
                        "replica adopted it between our accept and our "
                        "claim); resubmit",
                        retry_after_seconds=5.0,
                    )
            # Registered kill-point: lease file linked, its journal
            # record not yet appended (the fold's fence lags the disk).
            faults.kill_point("serve.lease.post-claim")
            if self._journal is not None:
                self._journal.lease(job.id, epoch)
            self._trace_event("lease", job=job, epoch=epoch)
        try:
            self._queue.put(job)
        except QueueFull as e:
            with self._lock:
                del self._table[job.id]
            self._journal_tombstone(job)
            self._rejected.labels(code="queue-full").inc()
            return 429, error_doc(
                "queue-full", str(e), retry_after_seconds=5.0
            )
        except QueueClosed as e:
            with self._lock:
                del self._table[job.id]
            self._journal_tombstone(job)
            self._rejected.labels(code="draining").inc()
            return 503, error_doc(
                "draining", str(e), retry_after_seconds=30.0
            )
        self._submitted.labels(job_class=job.job_class).inc()
        return 202, self._job_doc(job)

    def _journal_accepted(self, job: Job) -> None:
        if self._journal is None:
            return
        self._journal.accepted(
            job_id=job.id,
            request_doc=request_doc(
                job.request.flags,
                kind=job.request.kind,
                deadline_seconds=job.request.deadline_seconds,
                tag=job.request.tag,
            ),
            job_class=job.job_class,
            submitted_unix=job.submitted_unix,
            deadline_unix=job.deadline_unix,
            trace_id=job.trace_id,
            cost=(
                job.cost_prediction.to_dict()
                if job.cost_prediction is not None
                else None
            ),
        )

    def _cost_from_record(self, record):
        """Rehydrate a journaled cost prediction (None on pre-cost
        journals and junk blocks — replay must never die on one)."""
        if not getattr(record, "cost", None):
            return None
        from spark_examples_tpu.obs.costmodel import CostPrediction

        return CostPrediction.from_dict(record.cost)

    def _expire_queued_job(self, job: Job) -> None:
        """The queue's expired-sink target: a job swept out of the queue
        because its deadline passed before any worker reached it. Called
        OUTSIDE the queue lock (see ``BoundedJobQueue.put``); routes to
        the same terminal path a dequeued-too-late job takes."""
        now = time.time()
        with self._lock:
            if job.status != "queued":
                return
            job.status = "failed"
            job.error = (
                f"deadline-exceeded: queued {now - job.submitted_unix:.1f}s,"
                f" deadline was "
                f"{(job.deadline_unix or now) - job.submitted_unix:.1f}s "
                "(swept at admission — expired before any worker freed up)"
            )
            job.finished_unix = now
            self._mark_terminal_locked(job)
        self._journal_terminal(job)
        self._completed.labels(status="failed").inc()

    def _lease_epoch(self, job_id: str) -> Optional[int]:
        return (
            self._lease_store.epoch_of(job_id)
            if self._lease_store is not None
            else None
        )

    def _journal_terminal(self, job: Job) -> None:
        if self._journal is not None:
            self._journal.terminal(
                job.id, job.status, epoch=self._lease_epoch(job.id)
            )
        if self._lease_store is not None:
            self._lease_store.release(job.id)
        self._trace_event(
            "terminal",
            job=job,
            flush=True,
            status=job.status,
            **({"error": job.error} if job.error else {}),
        )

    def _journal_tombstone(self, job: Job) -> None:
        """Admission-path tombstone: the accepted record may not replay."""
        if self._journal is not None:
            self._journal.terminal(
                job.id, "rejected", epoch=self._lease_epoch(job.id)
            )
        if self._lease_store is not None:
            self._lease_store.release(job.id)
        self._trace_event("terminal", job=job, flush=True, status="rejected")

    # --------------------------------------------------------------- lookup

    def job_status(self, job_id: str) -> Tuple[int, Dict]:
        with self._lock:
            job = self._table.get(job_id)
            if job is None:
                return 404, error_doc(
                    "unknown-job", f"no job {job_id!r} on this service"
                )
            return 200, self._job_doc_locked(job)

    def cancel(self, job_id: str) -> Tuple[int, Dict]:
        """Cancel one still-queued job; running and finished jobs conflict
        (a slice worker cannot abandon a dispatched pipeline without
        poisoning the device state every other job on its slice shares)."""
        with self._lock:
            job = self._table.get(job_id)
        if job is None:
            return 404, error_doc(
                "unknown-job", f"no job {job_id!r} on this service"
            )
        removed = self._queue.remove(job_id)
        with self._lock:
            if removed is not None and job.status == "queued":
                job.status = "cancelled"
                job.finished_unix = time.time()
                self._mark_terminal_locked(job)
                doc = self._job_doc_locked(job)
            elif job.status in ("running", "queued"):
                # status 'queued' with removed=None is the pop window:
                # the worker claimed the job but has not flipped it to
                # running yet — it IS about to run, report it as such.
                return 409, error_doc(
                    "job-running",
                    f"job {job_id} is already on the devices; a running "
                    "job cannot be cancelled",
                )
            else:
                return 409, error_doc(
                    "job-finished",
                    f"job {job_id} already reached status {job.status!r}",
                )
        self._journal_terminal(job)
        self._completed.labels(status="cancelled").inc()
        return 200, doc

    # ---------------------------------------------------------------- state

    def healthz(self) -> Dict:
        """Mesh/queue/slice liveness (``GET /healthz``)."""
        uptime = (
            time.time() - self._started_unix
            if self._started_unix is not None
            else None
        )
        workers = list(self._workers)
        with self._lock:
            inflight = self._inflight
            terminal = self._terminal
            total = len(self._table)
            slices = [
                {
                    "name": w.spec.name,
                    "classes": list(w.spec.job_classes),
                    "devices": w.spec.device_count,
                    "busy": w.running_job_id is not None,
                    "worker_alive": (
                        w.thread is not None and w.thread.is_alive()
                    ),
                }
                for w in workers
            ]
        replica_block = None
        degraded = False
        if self._lease_store is not None:
            peers = self._lease_store.peers()
            degraded = any(not p["alive"] for p in peers)
            replica_block = {
                "id": self.replica_id,
                "lease_seconds": self.lease_seconds,
                "grace_seconds": self.lease_grace_seconds,
                "leases_held": len(self._lease_store.owned_jobs()),
                "alive": self._lease_store.alive_count(),
                "peers": peers,
                # Degraded = admitting WITHOUT live failover cover: some
                # known peer stopped heartbeating (its jobs are being
                # stolen). Admission continues — that is the point of
                # replication — but a balancer can see the thinner pool.
                "degraded": degraded,
                "jobs_stolen": int(self._jobs_stolen.value),
                "lease_renewals": int(self._lease_renewals.value),
            }
        doc_status = (
            "draining"
            if self.draining
            else ("degraded" if degraded else "ok")
        )
        return {
            "status": doc_status,
            "replica": replica_block,
            "mesh": {
                "devices": self.device_count,
                "platform": self.platform,
            },
            "slices": slices,
            "queue": {
                "depth": self._queue.depth(),
                "capacity": {
                    "small": self._queue.small_capacity,
                    "large": self._queue.large_capacity,
                },
                "worker_alive": any(s["worker_alive"] for s in slices),
                "worker_restarts": int(self._worker_restarts.value),
            },
            "jobs": {
                "tracked": total,
                "inflight": inflight,
                "terminal": terminal,
            },
            "warm_state": {
                "journal_replayed": self._replayed_jobs,
                "primed_geometries": self._primed_geometries,
                "persistent_cache": self.persistent_cache,
            },
            "uptime_seconds": uptime,
            "run_dir": self.run_dir,
        }

    def metrics_text(self) -> str:
        """Prometheus text exposition (``GET /metrics``) — the registry's
        existing export, unchanged."""
        return self.registry.prometheus_text()

    @staticmethod
    def _merged_quantiles(snapshots) -> Optional[Dict]:
        """Merge same-bucket histogram snapshots (children of one family
        share bucket bounds by construction) and report the standard
        quantile trio — the fleet-stats shape for one latency surface."""
        from spark_examples_tpu.obs.metrics import histogram_quantile

        merged: Dict[str, int] = {}
        total = 0.0
        count = 0
        for snap in snapshots:
            for bound, cumulative in snap["buckets"].items():
                merged[bound] = merged.get(bound, 0) + int(cumulative)
            total += float(snap["sum"])
            count += int(snap["count"])
        if count == 0:
            return None
        snapshot = {"buckets": merged, "sum": total, "count": count}
        return {
            "count": count,
            "mean": total / count,
            "p50": histogram_quantile(snapshot, 0.50),
            "p95": histogram_quantile(snapshot, 0.95),
            "p99": histogram_quantile(snapshot, 0.99),
        }

    def _histogram_by_label(self, name: str, label: str) -> Dict[str, Dict]:
        """Group one histogram family's children by a single label value
        and merge each group's snapshots into quantiles."""
        family = self.registry.get(name)
        if family is None:
            return {}
        groups: Dict[str, List] = {}
        for child in family.children():
            key = child.labels_dict.get(label, "")
            groups.setdefault(key, []).append(child.snapshot())
        out: Dict[str, Dict] = {}
        for key, snaps in sorted(groups.items()):
            merged = self._merged_quantiles(snaps)
            if merged is not None:
                out[key] = merged
        return out

    def fleet_stats(self) -> Dict:
        """``GET /v1/fleet/stats``: per-class latency quantiles, the
        fleet calibration fold, and recovery counters in one JSON
        document. Quantiles and counters are THIS replica's (each
        replica's registry sees its own executions); the calibration
        block is fleet-wide — every replica appends to the one shared
        ledger, and this call re-folds it from disk so peers' completed
        jobs are merged in."""
        from spark_examples_tpu.obs.metrics import (
            SERVE_JOB_WALL_SECONDS,
            SERVE_QUEUE_WAIT_SECONDS,
        )
        from spark_examples_tpu.serve.protocol import protocol_block

        fold = self._calibration.refresh()
        uptime = (
            time.time() - self._started_unix
            if self._started_unix is not None
            else None
        )
        with self._lock:
            tracked = len(self._table)
            inflight = self._inflight
            terminal = self._terminal
        classes: Dict[str, Dict] = {}
        for job_class, wall in self._histogram_by_label(
            SERVE_JOB_WALL_SECONDS, "job_class"
        ).items():
            classes.setdefault(job_class, {})["wall_seconds"] = wall
        for job_class, wait in self._histogram_by_label(
            SERVE_QUEUE_WAIT_SECONDS, "job_class"
        ).items():
            classes.setdefault(job_class, {})["queue_wait_seconds"] = wait
        return {
            "protocol": protocol_block(),
            "replica": self.replica_id,
            "uptime_seconds": uptime,
            "jobs": {
                "tracked": tracked,
                "inflight": inflight,
                "terminal": terminal,
                "queue_depth": self._queue.total_depth(),
            },
            "classes": classes,
            "kinds": self._histogram_by_label(
                SERVE_JOB_WALL_SECONDS, "kind"
            ),
            "compile": self._histogram_by_label(
                SERVE_JOB_WALL_SECONDS, "compile"
            ),
            "calibration": fold.summary(),
            # Fused vs serial partitions every executed job: the fleet's
            # live answer to "is batch fusion actually engaging?".
            "dispatch": {
                "fused_groups": int(self._fused_groups.value),
                "fused_jobs": int(self._fused_jobs.value),
                "serial_jobs": int(self._serial_jobs.value),
            },
            "counters": {
                "jobs_stolen": int(self._jobs_stolen.value),
                "worker_restarts": int(self._worker_restarts.value),
                "journal_replayed": int(self._journal_replayed.value),
                "lease_renewals": int(self._lease_renewals.value),
                "replicas_alive": (
                    self._lease_store.alive_count()
                    if self._lease_store is not None
                    else 0
                ),
            },
            "run_dir": self.run_dir,
        }

    def _mark_terminal_locked(self, job: Job) -> None:
        """Lifetime counter + bounded retention: the oldest terminal
        records past ``terminal_retention`` leave the table (their
        manifests stay on disk; a later status query is 404 by design —
        the in-memory control plane must stay O(retention), not O(jobs
        ever served)."""
        self._terminal += 1
        self._terminal_order.append(job.id)
        while len(self._terminal_order) > self.terminal_retention:
            evicted = self._terminal_order.popleft()
            self._table.pop(evicted, None)

    def _job_doc(self, job: Job) -> Dict:
        with self._lock:
            return self._job_doc_locked(job)

    def _job_doc_locked(self, job: Job) -> Dict:
        return job_doc(
            job_id=job.id,
            kind=job.request.kind,
            job_class=job.job_class,
            status=job.status,
            tag=job.request.tag,
            submitted_unix=job.submitted_unix,
            started_unix=job.started_unix,
            finished_unix=job.finished_unix,
            seconds=job.seconds,
            error=job.error,
            result=job.result,
            manifest_path=job.manifest_path,
            compile_cache=job.compile_cache,
            plan_geometry=job.plan_geometry,
            slice_name=job.slice,
            batch_size=job.batch_size,
            fused_size=job.fused_size,
            trace=job.trace_id,
            cost=self._job_cost_doc_locked(job),
        )

    def _job_cost_doc_locked(self, job: Job) -> Optional[Dict]:
        """The job envelope's ``cost`` block: the admission prediction
        with measured fields merged in once they exist."""
        prediction = job.cost_prediction
        if prediction is None:
            return None
        doc = prediction.to_dict()
        if job.queue_wait_seconds is not None:
            doc["queue_wait_seconds"] = job.queue_wait_seconds
        if job.seconds is not None:
            doc["measured_seconds"] = job.seconds
        if job.compile_cache:
            doc["compile"] = job.compile_cache
        return doc

    # --------------------------------------------------------------- worker

    def _worker_loop(self, worker: _SliceWorker) -> None:
        classes = worker.spec.job_classes
        while True:
            batch = self._queue.pop_batch(
                timeout=0.2,
                classes=classes,
                max_batch=self.batch_max_jobs,
                linger_seconds=self.batch_linger_seconds,
            )
            if not batch:
                if self._queue.drained_for(classes):
                    return
                continue
            self._run_batch(worker, batch)

    def _run_batch(self, worker: _SliceWorker, batch: List[Job]) -> None:
        """One dispatch group: the batch's jobs on this slice's warm
        caches. When fusion is on and the group preflights eligible, the
        whole group runs as ONE stacked device program
        (:meth:`_run_fused`); otherwise the jobs run back to back.
        Results are identical either way — batching and fusion only
        remove inter-job queue latency, re-pops, and per-job dispatch."""
        if len(batch) > 1:
            self._batches.inc(1)
            self._batch_jobs.inc(len(batch))
        if (
            self.batch_fuse
            and len(batch) > 1
            # Custom executors (embedders, test stubs) know nothing of
            # fused groups — fusion exists only for the real executor.
            and self._executor is execute_job
        ):
            from spark_examples_tpu.pipeline.fused import (
                FusedIneligible,
                preflight_fused,
            )

            try:
                # Device-free eligibility check BEFORE any lifecycle
                # mutation: an ineligible group falls through to the
                # serial loop with zero observable difference.
                preflight_fused(
                    [job.conf for job in batch],
                    [job.request.kind for job in batch],
                )
            except FusedIneligible as e:
                self._trace_event(
                    "fuse-ineligible",
                    job=batch[0],
                    tid=worker.spec.name,
                    reason=str(e),
                    group=len(batch),
                )
            else:
                self._run_fused(worker, batch)
                return
        with self._lock:
            worker.pending_batch = list(batch)
        for job in batch:
            job.batch_size = len(batch)
            with self._lock:
                if job in worker.pending_batch:
                    worker.pending_batch.remove(job)
            self._run_job(worker, job)
        with self._lock:
            worker.pending_batch = []

    def _run_fused(self, worker: _SliceWorker, batch: List[Job]) -> None:
        """One ELIGIBLE dispatch group as one stacked device program:
        predispatch every member (the same fences and journal boundary
        the serial path crosses), hand the survivors to
        ``executor.execute_fused_batch`` as one call, then settle each
        member with its own outcome. A member that expires or loses its
        lease at predispatch drops out of the group — the stacked
        program runs over the survivors only."""
        with self._lock:
            worker.pending_batch = list(batch)
        dispatched: List[Job] = []
        for job in batch:
            job.batch_size = len(batch)
            job.fused_size = len(batch)
            with self._lock:
                if job in worker.pending_batch:
                    worker.pending_batch.remove(job)
            if self._predispatch_job(worker, job):
                dispatched.append(job)
        with self._lock:
            worker.pending_batch = []
        if not dispatched:
            return
        # The journaled began records carry the PLANNED group size; the
        # envelope reports what actually dispatched.
        for job in dispatched:
            job.fused_size = len(dispatched)
        started = time.perf_counter()
        outcomes: Optional[List[ExecutionOutcome]] = None
        error: Optional[str] = None
        try:
            with self.spans.span(
                f"fused group x{len(dispatched)} "
                f"[{dispatched[0].request.kind}/{worker.spec.name}]"
            ):
                outcomes = execute_fused_batch(dispatched, self.run_dir)
        except Exception as e:  # noqa: BLE001 — the group FAILS, the service lives
            # Past predispatch every member's device_began is journaled:
            # a failure fails the WHOLE group (no silent serial retry —
            # the requeue-once boundary holds for fused members too).
            error = f"{type(e).__name__}: {e}"
        wall = time.perf_counter() - started
        # Amortized marginal cost: the group shared one device program,
        # so each member's measured wall — the quantity the calibration
        # ledger learns per geometry — is its share of the group's.
        seconds = wall / len(dispatched)
        if error is None:
            self._fused_groups.inc(1)
            self._fused_jobs.inc(len(dispatched))
        for idx, job in enumerate(dispatched):
            outcome = outcomes[idx] if outcomes is not None else None
            self._settle_job(worker, job, outcome, error, seconds)

    def _predispatch_job(self, worker: _SliceWorker, job: Job) -> bool:
        """Everything between dequeue and the executor call: queue-wait
        stamping, the deadline and lease fences, the running flip, the
        durable requeue-once boundary. Returns False when the job
        terminated (expired or abandoned) before device work — the
        caller must not execute it. Shared verbatim by the serial path
        (:meth:`_run_job`) and the fused group path (:meth:`_run_fused`),
        so a fused member's lifecycle records are indistinguishable from
        a serial member's up to the executor call."""
        now = time.time()
        # Queue wait is a fact the moment the worker holds the job,
        # whatever happens next (run, expire, lease-lost abandon).
        job.dequeued_unix = now
        job.queue_wait_seconds = max(0.0, now - job.submitted_unix)
        self._queue_wait_seconds.labels(job_class=job.job_class).observe(
            job.queue_wait_seconds
        )
        if job.deadline_unix is not None and now > job.deadline_unix:
            with self._lock:
                job.status = "failed"
                job.error = (
                    f"deadline-exceeded: queued "
                    f"{now - job.submitted_unix:.1f}s, deadline was "
                    f"{job.deadline_unix - job.submitted_unix:.1f}s"
                )
                job.finished_unix = now
                self._mark_terminal_locked(job)
            self._journal_terminal(job)
            self._completed.labels(status="failed").inc()
            return False
        if (
            self._lease_store is not None
            and not self._lease_store.still_owner(job.id)
        ):
            # Deposed while queued (stalled renewals, clock skew): the
            # job belongs to whichever replica stole the lease. Abandon
            # BEFORE any device work and publish nothing — no terminal
            # record (the stealer owns the journal's last word), only a
            # local status for this replica's pollers.
            self._lease_store.forget(job.id)
            with self._lock:
                self._fail_crashed_locked(
                    job,
                    "lease-lost: this replica's lease on the job expired "
                    "before dispatch; a peer replica owns it now and its "
                    "run decides the outcome",
                )
            self._completed.labels(status="failed").inc()
            self._trace_event(
                "abandoned", job=job, flush=True, reason="lease-lost"
            )
            return False
        with self._lock:
            job.status = "running"
            job.started_unix = now
            job.slice = worker.spec.name
            worker.running_job_id = job.id
            self._inflight += 1
        self._slice_inflight.labels(slice=worker.spec.name).set(1)
        # The job span opens on the slice's thread lane; flushed so an
        # arbitrary-time kill still leaves the B durable (the exporter
        # closes a B whose E died with the process as a truncated span).
        self._trace_event(
            "job",
            ph="B",
            job=job,
            tid=worker.spec.name,
            flush=True,
            job_class=job.job_class,
            kind=job.request.kind,
            batch_size=job.batch_size,
            **({"fused_size": job.fused_size} if job.fused_size > 1 else {}),
            # Durable on THIS replica's segment before any kill-point:
            # the post-mortem report's queue-wait source for a job whose
            # owner (and its histograms) died mid-run.
            queue_wait=job.queue_wait_seconds,
            **(
                {"epoch": self._lease_epoch(job.id)}
                if self._lease_store is not None
                else {}
            ),
        )
        # Registered kill-point: job claimed and flipped to running, BEFORE
        # any device work — the requeue-eligible window (a crash here is
        # side-effect-free; the watchdog re-puts the job once).
        faults.kill_point("serve.worker.claim")
        with self._lock:
            job.device_began = True
        # Durable requeue-once boundary: the journal must know device work
        # began BEFORE it begins — a process death after this line must
        # not silently re-run the job on restart, whichever replica
        # replays or steals it.
        if self._journal is not None:
            self._journal.began(
                job.id,
                epoch=self._lease_epoch(job.id),
                fused_size=job.fused_size,
            )
        self._trace_event(
            "device-began",
            job=job,
            tid=worker.spec.name,
            flush=True,
            **(
                {"epoch": self._lease_epoch(job.id)}
                if self._lease_store is not None
                else {}
            ),
        )
        # Registered kill-point: device work marked begun, executor about
        # to run — a crash from here on must NOT be requeued (device state
        # under a crashed update cannot be trusted for a silent retry).
        faults.kill_point("serve.worker.mid-job")
        # The slice's devices ride the job record down to the executor
        # (the executor's callable signature stays (job, run_dir) for
        # embedders and test stubs).
        job.slice_devices = worker.devices
        return True

    def _run_job(self, worker: _SliceWorker, job: Job) -> None:
        if not self._predispatch_job(worker, job):
            return
        self._serial_jobs.inc(1)
        started = time.perf_counter()
        outcome: Optional[ExecutionOutcome] = None
        error: Optional[str] = None
        try:
            with self.spans.span(
                f"job {job.id} [{job.request.kind}/{worker.spec.name}]"
            ):
                outcome = self._executor(job, self.run_dir)
        except Exception as e:  # noqa: BLE001 — the job FAILS, the service lives
            error = f"{type(e).__name__}: {e}"
        seconds = time.perf_counter() - started
        self._settle_job(worker, job, outcome, error, seconds)

    def _settle_job(
        self,
        worker: _SliceWorker,
        job: Job,
        outcome: Optional[ExecutionOutcome],
        error: Optional[str],
        seconds: float,
    ) -> None:
        """Everything after the executor returns: the pre-publish lease
        fence, the terminal flip, tracing, journaling, counters, and the
        calibration pair. For a fused group member ``seconds`` is the
        group wall divided by the group size — the amortized marginal
        cost, which is exactly what the calibration ledger should learn
        for a job that rode a shared device program."""
        if (
            self._lease_store is not None
            and not self._lease_store.still_owner(job.id)
        ):
            # The pre-publish fence: a zombie replica (paused past its
            # lease, deposed by a stealer's higher epoch) must detect the
            # loss and abandon BEFORE publishing — no terminal record, no
            # result; the stolen run's terminal is the journal's only
            # valid word on this job (and fold-time epoch fencing ignores
            # this replica's write even if a pause landed it anyway).
            self._lease_store.forget(job.id)
            with self._lock:
                job.finished_unix = time.time()
                job.seconds = seconds
                self._inflight -= 1
                worker.running_job_id = None
                self._fail_crashed_locked(
                    job,
                    "lease-lost: this replica was deposed while the job "
                    "ran (lease expired past the grace window); result "
                    "abandoned unpublished — the stealing replica's run "
                    "decides the outcome",
                )
            self._slice_inflight.labels(slice=worker.spec.name).set(0)
            self._completed.labels(status="failed").inc()
            self._trace_event(
                "job",
                ph="E",
                job=job,
                tid=worker.spec.name,
                flush=True,
                status="failed",
                abandoned="lease-lost",
            )
            return
        with self._lock:
            job.finished_unix = time.time()
            job.seconds = seconds
            self._inflight -= 1
            worker.running_job_id = None
            self._mark_terminal_locked(job)
            if error is not None:
                job.status = "failed"
                job.error = error
            else:
                job.status = "done"
                job.result = outcome.result
                job.manifest_path = outcome.manifest_path
                job.compile_cache = outcome.compile_cache
        self._slice_inflight.labels(slice=worker.spec.name).set(0)
        self._trace_event(
            "job",
            ph="E",
            job=job,
            tid=worker.spec.name,
            status=job.status,
            compile_cache=job.compile_cache,
            **({"error": error} if error else {}),
        )
        if outcome is not None and outcome.conformance:
            self._mirror_conformance(outcome.conformance)
        self._journal_terminal(job)
        self._completed.labels(status=job.status).inc()
        self._job_seconds.labels(job_class=job.job_class).observe(seconds)
        self._job_wall_seconds.labels(
            kind=job.request.kind,
            job_class=job.job_class,
            compile=job.compile_cache
            or (
                job.cost_prediction.compile
                if job.cost_prediction is not None
                else "cold"
            ),
        ).observe(seconds)
        if job.status == "done":
            self._record_job_cost(job, seconds)
            self._stamp_manifest_cost(job)
        else:
            self._record_failed_cost(job)

    def _record_job_cost(self, job: Job, seconds: float) -> None:
        """Feed one COMPLETED job's (predicted, measured) pair into the
        fleet calibration ledger and the ratio gauge. Done-only: a failed
        job's wall clock measures the failure path, not the geometry's
        cost, and would poison the learned ratios. Best-effort — the
        ledger is telemetry, never a reason to fail a finished job."""
        prediction = job.cost_prediction
        if prediction is None:
            return
        try:
            if prediction.predicted_seconds > 0:
                self._prediction_ratio.labels(kind=job.request.kind).set(
                    seconds / prediction.predicted_seconds
                )
            self._calibration.record(
                fingerprint=prediction.fingerprint,
                kind=job.request.kind,
                job_class=job.job_class,
                predicted_seconds=prediction.predicted_seconds,
                measured_seconds=seconds,
                queue_wait_seconds=job.queue_wait_seconds or 0.0,
                compile=job.compile_cache or prediction.compile,
                job_id=job.id,
                trace_id=job.trace_id,
                unix=job.finished_unix,
            )
        except Exception as e:  # noqa: BLE001 — telemetry, not the job
            print(
                f"serve: calibration record failed for {job.id}: {e}",
                file=sys.stderr,
            )

    def _record_failed_cost(self, job: Job) -> None:
        """A failed job (crashed executor, fenced-off steal) still gets a
        ledger row — ``status: failed``, which the ratio fold skips — so
        the post-mortem report can put its fleet-side wall (submission
        to fenced terminal) next to what admission predicted. The
        queue wait is omitted when this replica never dequeued the job
        (the owner that did may be dead; its flight-recorder segment
        holds the wait). Best-effort, like every ledger write."""
        prediction = job.cost_prediction
        if prediction is None:
            return
        try:
            settled = job.finished_unix or time.time()
            self._calibration.record(
                fingerprint=prediction.fingerprint,
                kind=job.request.kind,
                job_class=job.job_class,
                predicted_seconds=prediction.predicted_seconds,
                measured_seconds=max(0.0, settled - job.submitted_unix),
                queue_wait_seconds=job.queue_wait_seconds,
                compile=job.compile_cache or prediction.compile,
                job_id=job.id,
                trace_id=job.trace_id,
                unix=settled,
                status="failed",
            )
        except Exception as e:  # noqa: BLE001 — telemetry, not the job
            print(
                f"serve: calibration record failed for {job.id}: {e}",
                file=sys.stderr,
            )

    def _stamp_manifest_cost(self, job: Job) -> None:
        """Rewrite the finished job's manifest with its ``cost`` block
        (predicted vs measured vs queue wait) — the per-job half of the
        ledger, queryable post-mortem without the service. Atomic
        (``obs/manifest.py:write_manifest``) and best-effort."""
        prediction = job.cost_prediction
        if prediction is None or not job.manifest_path:
            return
        try:
            from spark_examples_tpu.obs.manifest import (
                read_manifest,
                write_manifest,
            )

            doc = read_manifest(job.manifest_path)
            cost = prediction.to_dict()
            cost["measured_seconds"] = job.seconds
            cost["queue_wait_seconds"] = job.queue_wait_seconds or 0.0
            cost["compile"] = job.compile_cache or prediction.compile
            doc["cost"] = cost
            write_manifest(job.manifest_path, doc)
        except Exception as e:  # noqa: BLE001 — telemetry, not the job
            print(
                f"serve: manifest cost stamp failed for {job.id}: {e}",
                file=sys.stderr,
            )

    def _mirror_conformance(self, block: Dict) -> None:
        """Mirror a completed job's manifest ``conformance`` block into
        the SERVICE registry (last-write-wins per prover), so ``GET
        /metrics`` exports the fleet's latest measured-vs-proven pair —
        a scrape sees prover conformance without chasing per-job
        manifests. Best-effort: a malformed block is dropped, never a
        job failure."""
        from spark_examples_tpu.obs.metrics import record_prover_conformance

        for prover, pair in block.items():
            if not isinstance(pair, dict):
                continue
            measured = pair.get("measured")
            if not isinstance(measured, (int, float)):
                continue
            proven = pair.get("proven")
            try:
                record_prover_conformance(
                    self.registry,
                    prover,
                    measured,
                    proven if isinstance(proven, (int, float)) else None,
                )
            except Exception:
                continue

    # ------------------------------------------------------------- watchdog

    def _watchdog_loop(self) -> None:
        """Monitor every slice worker's pulse; replace any that dies.

        A worker loop only returns by contract when the queue is closed
        AND drained of its classes — any other exit is a crash (an
        escaped ``BaseException``; the deterministic stand-in is
        ``utils/faults.InjectedWorkerCrash``, which by design escapes the
        job-failure ``except Exception``). The watchdog applies the
        recovery policy (:meth:`_recover_worker`) per slice — a crashing
        whole-genome job can never take a small-slice worker with it —
        and exits only when every slice drained cleanly."""
        while True:
            workers = self._workers
            if not workers or all(w.done for w in workers):
                return
            for worker in workers:
                if worker.done:
                    continue
                thread = worker.thread
                if thread is None:
                    worker.done = True
                    continue
                thread.join(timeout=WATCHDOG_INTERVAL_SECONDS)
                if thread.is_alive():
                    continue
                with self._lock:
                    running = worker.running_job_id
                    settled = not worker.pending_batch
                if (
                    running is None
                    and settled
                    and self._queue.drained_for(worker.spec.job_classes)
                ):
                    # Contract exit: this slice drained every job it owed.
                    worker.done = True
                    continue
                self._recover_worker(worker)

    def _recover_worker(self, worker: _SliceWorker) -> None:
        """One dead slice worker: settle its in-flight job, requeue its
        untouched batch tail, start a replacement on the same slice.

        Policy (the acceptance contract of the chaos tests):
        - an in-flight job that had NOT begun device work is requeued
          once — its claim was side-effect-free, so one silent retry is
          safe and invisible to the client;
        - an in-flight job that touched the devices (or already rode its
          one requeue) is marked ``failed`` with a structured
          ``worker-crashed:`` error — the slice stays healthy, the
          client gets a terminal status instead of a forever-running job;
        - jobs popped into the dispatch group but never started are
          requeued unconditionally (they were never claimed);
        - a fresh worker thread takes over the slice either way.
        """
        with self._lock:
            crashed: Optional[Job] = None
            if worker.running_job_id is not None:
                crashed = self._table.get(worker.running_job_id)
                worker.running_job_id = None
                # The crashed worker never reached its decrement; the new
                # worker owns the gauge the moment it claims a job.
                self._inflight = max(0, self._inflight - 1)
            untouched = list(worker.pending_batch)
            worker.pending_batch = []
        self._slice_inflight.labels(slice=worker.spec.name).set(0)
        if crashed is not None:
            # Close the dead worker's open job span (the B was recorded
            # on the worker thread; pairing is by (replica, job, name),
            # so this E from the watchdog thread closes it cleanly).
            self._trace_event(
                "job",
                ph="E",
                job=crashed,
                tid=worker.spec.name,
                flush=True,
                status="worker-crashed",
            )
        # Replacement FIRST, job settlement second: a client that observes
        # the crashed job's terminal status (or its requeue) must never
        # then find healthz reporting a dead worker — the failure and the
        # recovery must be visible in that order, not the reverse.
        self._worker_restarts.inc(1)
        replacement = threading.Thread(
            target=self._worker_loop,
            args=(worker,),
            name=f"serve-worker-{worker.spec.name}",
            daemon=True,
        )
        worker.thread = replacement
        replacement.start()
        for job in untouched:
            # Never claimed: re-admission is free (does not consume the
            # one requeue), preserves class ordering, and is
            # capacity-exempt — these jobs already held queue slots.
            try:
                self._queue.put(job, enforce_capacity=False)
            except (QueueFull, QueueClosed) as e:
                with self._lock:
                    self._fail_crashed_locked(
                        job,
                        f"worker-crashed: dispatch-group requeue rejected "
                        f"({e})",
                    )
                self._journal_terminal(job)
                self._completed.labels(status="failed").inc()
        if crashed is None:
            return
        with self._lock:
            requeue = not crashed.device_began and crashed.requeues < 1
            if requeue:
                crashed.requeues += 1
                crashed.status = "queued"
                crashed.started_unix = None
            else:
                self._fail_crashed_locked(
                    crashed,
                    "worker-crashed: the worker thread died mid-job "
                    "after device work began; not requeued (device "
                    "state under a crashed update cannot be trusted)"
                    if crashed.device_began
                    else "worker-crashed: the worker thread died "
                    "mid-claim and the job already rode its one "
                    "requeue",
                )
        if requeue:
            try:
                # Outside the table lock (the admission path's lock
                # order); capacity-exempt like the batch tail above.
                self._queue.put(crashed, enforce_capacity=False)
            except (QueueFull, QueueClosed) as e:
                with self._lock:
                    self._fail_crashed_locked(
                        crashed,
                        f"worker-crashed: requeue rejected ({e}); the "
                        "claim was side-effect-free but the queue would "
                        "not take the job back",
                    )
                self._journal_terminal(crashed)
                self._completed.labels(status="failed").inc()
        else:
            self._journal_terminal(crashed)
            self._completed.labels(status="failed").inc()

    # ----------------------------------------------------- lease protocol

    def _lease_loop(self) -> None:
        """The replica's lease-maintenance thread: heartbeat + renewals
        every TTL/``LEASE_RENEWALS_PER_TTL``, and a steal scan every
        ``steal_interval_seconds``. Maintenance errors are logged, never
        fatal — a replica that cannot renew simply loses its leases to a
        peer, which is the designed degradation, not a crash."""
        interval = self.lease_seconds / LEASE_RENEWALS_PER_TTL
        last_steal = time.monotonic()
        while not self._lease_stop.wait(timeout=interval):
            try:
                self._lease_tick()
                now = time.monotonic()
                if now - last_steal >= self.steal_interval_seconds:
                    last_steal = now
                    self._steal_expired()
                    self._maybe_compact()
            except Exception as e:  # noqa: BLE001 — maintenance survives
                print(
                    f"serve[{self.replica_id}]: lease maintenance error: "
                    f"{type(e).__name__}: {e}",
                    file=sys.stderr,
                )

    def _lease_tick(self) -> None:
        """One maintenance beat: publish liveness, renew every owned
        lease, abandon any we lost (stolen by a peer, or expired under a
        stall — renewing a lapsed lease would race its stealer)."""
        store = self._lease_store
        assert store is not None
        store.heartbeat()
        owned = store.owned_jobs()
        if not owned:
            return
        # Registered kill-point: this replica owns leases and is about to
        # renew them — a kill here is the canonical host loss (every
        # lease lapses unrenewed; peers steal the jobs). `crash` kills
        # just this maintenance thread: the in-process stand-in.
        faults.kill_point("serve.lease.pre-renew")
        for job_id in owned:
            if store.renew(job_id):
                self._lease_renewals.inc(1)
            else:
                self._abandon_lease_lost(job_id)

    def _abandon_lease_lost(self, job_id: str) -> None:
        """A lease this replica held is gone. A still-QUEUED job is
        pulled from the queue and failed locally WITHOUT a terminal
        record — the journal's last word belongs to the job's new owner.
        A running (or mid-claim) job is left to ``_run_job``'s
        pre-publish fence, which performs the same abandonment at the
        moment publication would have happened."""
        assert self._lease_store is not None
        self._lease_store.forget(job_id)
        removed = self._queue.remove(job_id)
        if removed is None:
            return  # running / popped: the pre-publish fence decides
        with self._lock:
            job = self._table.get(job_id)
            if job is None or job.status != "queued":
                return
            self._fail_crashed_locked(
                job,
                "lease-lost: this replica's lease expired before "
                "dispatch; a peer replica owns the job now and its run "
                "decides the outcome",
            )
        self._completed.labels(status="failed").inc()
        self._trace_event(
            "abandoned", job=job, flush=True, reason="lease-lost"
        )

    def _steal_expired(self) -> None:
        """Scan for jobs whose lease expired because their owner died,
        and reclaim them under a fencing epoch. The journal fold (NOT
        the lease file) decides live-ness of the job itself: a lease
        left behind by a settled job is skipped, and compaction sweeps
        it. Stolen jobs keep their original deadline budget — an
        expired one fails with the structured ``deadline-exceeded`` code
        at re-dispatch instead of running late.

        Candidates are claimed in descending calibrated-cost order (cost
        unknown sorts last): when several replicas race over a dead
        owner's orphans, each claim is one lease link and loses work to
        contention — spending the first, least-contended claims on the
        most expensive stranded jobs recovers the most stranded seconds
        per scan. File order breaks ties, so the scan stays
        deterministic for a given journal."""
        store = self._lease_store
        assert store is not None
        if self.draining or self._journal is None:
            return  # a draining replica must not adopt work it won't run
        expired = {view.job_id for view in store.expired_foreign()}
        peers = store.peers()
        if not expired and all(p["alive"] for p in peers):
            # Steady state: nothing expired and every known peer is
            # heartbeating — orphans need a dead owner, and an owner
            # always heartbeats before its first admission. Skip the
            # journal fold entirely (the scan stays O(listdir)).
            return
        pending, _max_seq = replay_journal(self._journal.path)
        alive_peers = {p["id"] for p in peers if p["alive"]}
        # Candidate selection (expired foreign leases + accepted-but-
        # never-leased orphans of dead owners) is the pure
        # journal.steal_candidates — shared with the model checker.
        candidates = steal_candidates(
            pending,
            expired,
            self.replica_id,
            alive_peers,
            lambda job_id: store.current(job_id) is not None,
        )
        for record in sorted(
            enumerate(candidates),
            key=lambda pair: (-self._record_steal_cost(pair[1]), pair[0]),
        ):
            self._steal_one(record[1])

    def _record_steal_cost(self, record) -> float:
        """The journaled admission estimate of one steal candidate, for
        highest-cost-first claim ordering; ``-inf`` when the record
        predates cost predictions (those sort last, in file order)."""
        prediction = self._cost_from_record(record)
        if prediction is None:
            return float("-inf")
        return float(prediction.best_estimate_seconds)

    def _steal_one(self, record) -> None:
        store = self._lease_store
        assert store is not None and self._journal is not None
        # Registered kill-point: steal target identified, fencing epoch
        # about to be link-claimed — a kill here must leave the job
        # claimable by any other replica.
        faults.kill_point("serve.steal.pre-claim")
        epoch = store.claim(
            record.job_id,
            steal=True,
            min_epoch=record.lease_epoch,
            min_replica=record.lease_replica,
        )
        if epoch is None:
            return  # another stealer won the link race (or owner woke)
        fresh = self._revalidate_claim(record.job_id, epoch)
        if fresh is None:
            return  # settled between our fold and our claim
        # Registered kill-point: claimed on disk, lease record not yet
        # journaled (same window as the submit path).
        faults.kill_point("serve.lease.post-claim")
        self._journal.lease(record.job_id, epoch, stolen=True)
        self._jobs_stolen.inc(1)
        self._trace_event(
            "steal",
            job_id=record.job_id,
            trace=fresh.trace_id,
            flush=True,
            epoch=epoch,
            **{"from": record.lease_replica},
        )
        self._adopt_pending(fresh, stolen=True, count_replayed=False)

    def _maybe_compact(self) -> None:
        """Bound the shared journal — and every fold over it — across a
        long-lived replica's life: startup compaction alone would let
        settled-job records accumulate until the next restart. When the
        file outgrows the threshold, the compaction-lock holder rewrites
        it to O(pending); losers skip and retry at a later scan."""
        if self._journal is None or self._lease_store is None:
            return
        try:
            size = os.path.getsize(self._journal.path)
        except OSError:
            return
        if size >= JOURNAL_COMPACT_BYTES:
            compact_journal_shared(
                self._journal.path, lease_dir=self._lease_store.lease_dir
            )

    def _revalidate_claim(self, job_id: str, epoch: int):
        """Post-claim fence against a STALE FOLD: between the fold a
        steal decision was made from and the claim itself, the job's
        previous holder may have settled it and released its lease —
        which is exactly what would have made our claim succeed at a
        fresh epoch. The settle's terminal write strictly precedes the
        lease unlink, so a re-fold AFTER a successful claim necessarily
        sees it: a settled (or higher-fenced) job abandons the claim
        before any lease record is journaled or any work adopted.
        Returns the re-folded pending record to adopt, or ``None``. The
        fence itself is the pure journal.revalidate_pending — shared
        with the model checker."""
        assert self._journal is not None and self._lease_store is not None
        pending, _max_seq = replay_journal(self._journal.path)
        record = revalidate_pending(pending, job_id, epoch)
        if record is not None:
            # Re-folded, not the caller's snapshot: the record's
            # began/deadline facts are as fresh as the fence.
            return record
        self._lease_store.release(job_id)
        return None

    def _fail_crashed_locked(self, job: Job, error: str) -> None:
        job.status = "failed"
        job.error = error
        job.finished_unix = time.time()
        self._mark_terminal_locked(job)


__all__ = [
    "LEASE_RENEWALS_PER_TTL",
    "MEM_LIMIT_CODES",
    "PcaService",
    "WATCHDOG_INTERVAL_SECONDS",
]

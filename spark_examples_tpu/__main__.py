from spark_examples_tpu.cli import main

raise SystemExit(main())
